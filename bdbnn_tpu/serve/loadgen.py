"""SLO load generators + the ``serve-bench`` orchestration.

Three layers:

1. the in-process :class:`LoadGenerator` (PR 5) driving a submit
   callable closed- or open-loop;
2. the traffic-shaped arrival processes (:func:`build_schedule`:
   poisson / diurnal / flash-crowd / heavy-tail / slow-client — all
   pre-drawn from the seed, so the OFFERED load is deterministic) and
   the raw-socket :class:`HttpLoadGenerator` that replays a schedule
   against the network front end (serve/http.py) over real TCP;
3. the strict-JSON SLO verdict builders (:func:`slo_verdict` v1
   aggregates; :func:`http_slo_verdict` adds the v2 per-priority
   latency blocks, per-tenant shed rates and the max/min fairness
   ratio).

Two canonical load models (Schroeder et al.'s open-vs-closed
distinction):

- **closed loop** — ``concurrency`` workers each keep exactly one
  request in flight (submit, wait, repeat). Measures the system's
  sustainable throughput; latency is flow-controlled by the system
  itself.
- **open loop** — requests arrive on a Poisson process at ``rate``
  req/s regardless of completions (arrivals are pre-scheduled from a
  seeded ``random.Random``, so the offered load is deterministic per
  seed). This is what production traffic looks like: an overloaded
  server keeps receiving requests, which is exactly what exercises the
  bounded queue + load shedding path.

The output is a deterministic-schema strict-JSON **SLO verdict**:
p50/p95/p99 latency, throughput, mean batch occupancy, shed rate,
drain/preemption disposition — the serving analogue of the training
side's BENCH/ACCURACY artifacts, and what ``compare`` judges across
builds (``--tol-rel``, exit 3 on regression).

``run_serve_bench`` wires the whole serving stack together: engine
(AOT-warmed buckets) → micro-batcher (bounded queue) → load generator,
with a run directory (manifest + ``events.jsonl`` carrying ``serve``
events) so ``summarize``/``watch``/``compare`` see serving runs through
the same pipeline as training runs. SIGTERM/SIGINT latches a
``PreemptionHandler`` flag (train/resilience.py); the generator stops
offering load, the batcher drains, and every accepted request is
answered before the verdict is written.
"""

from __future__ import annotations

import json
import math
import os
import random
import socket
import threading
import time
from collections import namedtuple
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from bdbnn_tpu.serve.batching import LoadShedError, MicroBatcher

VERDICT_NAME = "verdict.json"
# v2: per-priority latency blocks, per-tenant shed rates, fairness
# ratio and the scenario name joined the verdict (serve/http.py); v1
# aggregate fields are unchanged, so v1 consumers keep working.
# v3: the replica-pool blocks (serve/pool.py) — ``replicas``
# (per-replica device/version/occupancy/restart table), ``scaling``
# (the serve-bench --replicas sweep: throughput per N + the
# efficiency-at-max ratio compare judges) and ``swap`` (blue/green
# rollout disposition: versions, shed-due-to-swap, completed-by-
# version ledger). All three are null on single-replica runs, so v1/v2
# consumers keep working unchanged.
# v4: the request-path ``attribution`` block (obs/rtrace.py) —
# per-priority p50/p99 decomposed by lifecycle stage (read/admit/
# queue/coalesce/dispatch/compute/respond), the stage-sum-vs-e2e
# reconciliation identity, the slowest-K tail-exemplar waterfalls per
# priority and the two-clock documentation. Null when tracing is off,
# so v1-v3 consumers keep working unchanged.
# v5: the ``canary`` block (serve/canary.py) — one canary episode's
# full evidence: fraction, cohort identity, per-detector
# value/threshold/fired table, decision + trigger, rollback count,
# shadow-mirroring accounting with the max-abs logit drift, and the
# promote wall seconds. Null when no canary stage ran, so v1-v4
# consumers keep working unchanged.
# v6: the ``fleet`` block (serve/fleet.py) — the cross-host router's
# disposition: the per-host ledger table (proxied / completed /
# relayed 429/503 / retries by cause / retried-away / probe
# transitions), the fleet totals whose per-host sums must equal the
# client observation (``ledger_consistent``), the zero-tolerance
# ``dropped`` now summed across hosts, the retry rate and the
# max/min per-host p99 spread — the sources of ``compare``'s
# ``serve_fleet_dropped`` / ``serve_fleet_retry_rate`` /
# ``serve_fleet_host_p99_spread`` gates. Null on single-host runs,
# so v1-v5 consumers keep working unchanged.
# v7: the ``fleet_attribution`` block (obs/rtrace.py FleetTracer via
# serve/fleet.py) — the cross-host waterfall: per-priority e2e
# p50/p99 decomposed into router stages (probe_wait/pick/connect/
# retry_hop) + network + the backend's stitched stage blocks,
# retry-hop share, per-host stage spread, slowest-K cross-host
# exemplars naming host AND stage, and the cross-hop reconciliation
# identity with tolerance — the sources of ``compare``'s
# ``serve_fleet_p99_network_ms`` / ``serve_fleet_retry_hop_share`` /
# ``serve_fleet_stage_spread_max`` gates. Null when router tracing
# is off, so v1-v6 consumers keep working unchanged.
# v8: the ``capacity`` block (obs/capacity.py) — the capacity &
# demand observatory: the per-(model, tenant, priority) demand table
# with the ledger identity (offered == admitted + rejected + shed),
# the utilization windows (replica busy fraction, batch occupancy,
# rtrace queue share, admission token headroom, residency bytes),
# the SLO error-budget plane (per-priority burn-rate peaks over fast
# + slow windows, breach episodes from --slo-p99-ms /
# --slo-shed-rate) and the saturation-headroom estimate — the
# sources of ``compare``'s ``serve_burn_rate_max`` /
# ``serve_headroom_rps`` / ``serve_demand_shed_ratio_max`` gates.
# Also in v8: serve-mode (no scenario) verdicts now record the
# MEASURED offered rate derived from observed arrival stamps in
# ``rate_rps`` — previously null; scenario/bench verdicts keep the
# scheduled rate. Null ``capacity`` on pre-v8 producers, so v1-v7
# consumers keep working unchanged.
VERDICT_SCHEMA_VERSION = 8


def percentile(sorted_vals: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over an ASCENDING sequence; None on an
    empty window (the caller renders "no data", never crashes on the
    exact moment — startup, post-drain — it is most likely to look).
    A singleton window answers every q with its one sample. q outside
    [0, 100] is a caller bug and raises. Nearest-rank (not
    interpolated) so the verdict is reproducible across numpy versions
    and needs no numpy at all."""
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile q must be in [0, 100], got {q!r}")
    if not sorted_vals:
        return None
    rank = max(int(math.ceil(q / 100.0 * len(sorted_vals))), 1)
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def _pct(vals: Sequence[float], q: float, digits: int = 3) -> Optional[float]:
    """None-propagating rounded percentile — the verdict/stats helper
    that makes empty windows land as null instead of a TypeError."""
    v = percentile(vals, q)
    return None if v is None else round(v, digits)


class LoadGenerator:
    """Offer load to a submit callable; collect per-request latency.

    ``submit_fn(payload) -> Future`` (the micro-batcher's ``submit``);
    ``sample_fn(i) -> payload`` supplies request payloads (cycled from a
    small pregenerated pool in serve-bench). ``stop_fn()`` polled
    between arrivals — the SIGTERM latch."""

    def __init__(
        self,
        submit_fn: Callable[[Any], Future],
        sample_fn: Callable[[int], Any],
        *,
        mode: str = "open",
        requests: int = 200,
        rate: float = 100.0,
        concurrency: int = 4,
        seed: int = 0,
        stop_fn: Callable[[], bool] = lambda: False,
    ):
        if mode not in ("open", "closed"):
            raise ValueError(f"mode must be open|closed, got {mode!r}")
        self.submit_fn = submit_fn
        self.sample_fn = sample_fn
        self.mode = mode
        self.requests = int(requests)
        self.rate = float(rate)
        self.concurrency = max(int(concurrency), 1)
        self.seed = int(seed)
        self.stop_fn = stop_fn
        self._lock = threading.Lock()
        self.latencies_ms: List[float] = []
        self.shed = 0
        self.failed = 0  # accepted but errored (NOT load shedding)
        self.submitted = 0
        # accepted-Future accounting: _done callbacks may run a beat
        # AFTER result() wakes its waiter (Future resolves waiters
        # first), so run() must wait for _processed to catch up with
        # _accepted before snapshotting counters into the verdict
        self._accepted = 0
        self._processed = 0
        self._inflight: List[Future] = []

    # -- submission ----------------------------------------------------

    def _one(
        self, i: int, wait: bool, t0: Optional[float] = None
    ) -> Optional[Future]:
        """Submit request ``i``; latency is measured from ``t0`` when
        given — open-loop mode passes the SCHEDULED arrival time, so a
        generator that falls behind under overload charges the backlog
        delay to the requests that suffered it (no coordinated
        omission) instead of under-reporting the tail."""
        if t0 is None:
            t0 = time.perf_counter()
        try:
            fut = self.submit_fn(self.sample_fn(i))
        except LoadShedError:
            with self._lock:
                self.shed += 1
                self.submitted += 1
            return None
        with self._lock:
            self.submitted += 1
            self._accepted += 1

        def _done(f: Future, t0=t0):
            lat = (time.perf_counter() - t0) * 1000.0
            exc = None if f.cancelled() else f.exception()
            with self._lock:
                if not f.cancelled() and exc is None:
                    self.latencies_ms.append(lat)
                elif isinstance(exc, LoadShedError):
                    # accepted but shed by a racing drain: still load
                    # shedding, still part of the accounting identity
                    self.shed += 1
                else:
                    # engine/runner breakage is NOT shedding — an
                    # operator must not read a broken artifact as queue
                    # overload
                    self.failed += 1
                self._processed += 1

        fut.add_done_callback(_done)
        if wait:
            try:
                fut.result()
            except Exception:
                pass  # recorded as not-completed; the verdict shows it
        return fut

    def _run_closed(self) -> None:
        per_worker = self.requests // self.concurrency
        extra = self.requests % self.concurrency

        def worker(wid: int, n: int):
            # each worker owns a disjoint id range; min(wid, extra)
            # accounts for the +1 requests handed to workers < extra,
            # so ids cover exactly 0..requests-1 with no overlap
            base = wid * per_worker + min(wid, extra)
            for j in range(n):
                if self.stop_fn():
                    return
                self._one(base + j, wait=True)

        threads = [
            threading.Thread(
                target=worker, args=(w, per_worker + (1 if w < extra else 0))
            )
            for w in range(self.concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _run_open(self) -> None:
        # the arrival schedule is drawn up front from the seed —
        # deterministic offered load, independent of service times
        rng = random.Random(self.seed)
        gaps = [rng.expovariate(self.rate) for _ in range(self.requests)]
        t_next = time.perf_counter()
        for i, gap in enumerate(gaps):
            t_next += gap
            delay = t_next - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if self.stop_fn():
                return
            # latency clock starts at the SCHEDULED arrival, not the
            # (possibly late) submit — see _one
            fut = self._one(i, wait=False, t0=t_next)
            if fut is not None:
                self._inflight.append(fut)

    def run(self) -> Dict[str, Any]:
        """Offer the configured load; returns raw counters (the caller
        builds the verdict after the batcher drains)."""
        t0 = time.perf_counter()
        if self.mode == "closed":
            self._run_closed()
        else:
            self._run_open()
        # answered-before-verdict: wait for whatever is still in flight
        # (the batcher keeps consuming; on drain it answers everything)
        for fut in self._inflight:
            try:
                fut.result(timeout=60.0)
            except Exception:
                pass
        wall_s = time.perf_counter() - t0
        # settle: every accepted Future's _done callback must have
        # landed, or the last request's latency/shed increment could be
        # missing from the snapshot
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with self._lock:
                if self._processed >= self._accepted:
                    break
            time.sleep(0.001)
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": len(self.latencies_ms),
                "shed": self.shed,
                "failed": self.failed,
                "wall_s": wall_s,
                "latencies_ms": sorted(self.latencies_ms),
            }


# ---------------------------------------------------------------------------
# Traffic-shaped arrival processes + the socket load generator
# ---------------------------------------------------------------------------

# one scheduled request of a scenario: seconds-from-start, priority
# class, tenant, whether the CLIENT dribbles the body (slow-client
# scenario — the server must tolerate slow writers without stalling
# everyone else), and which co-resident model the request targets
# (x-model header; None = the server's default model)
Arrival = namedtuple(
    "Arrival", ("t", "priority", "tenant", "slow", "model"),
    defaults=(None,),
)

# the provenance scalars the verdict needs from an engine — held
# instead of the engine itself so dropping the engine actually frees
# its device weights (the A/B bench relies on this)
_EngineMeta = namedtuple("_EngineMeta", ("arch", "dataset"))

SCENARIOS = (
    "poisson", "diurnal", "flash_crowd", "heavy_tail", "slow_client",
)


def _weighted_pick(rng: random.Random, options: Sequence, weights) -> Any:
    """Deterministic weighted draw from a seeded Random (no
    random.choices: one rng.random() per draw keeps the consumption
    schedule obvious and stable)."""
    total = float(sum(weights))
    x = rng.random() * total
    acc = 0.0
    for opt, w in zip(options, weights):
        acc += float(w)
        if x < acc:
            return opt
    return options[-1]


def build_schedule(
    scenario: str,
    *,
    requests: int,
    rate: float,
    seed: int,
    priorities: int = 3,
    priority_weights: Optional[Sequence[float]] = None,
    tenants: Sequence[str] = ("tenant-a", "tenant-b"),
    tenant_weights: Optional[Sequence[float]] = None,
    flash_factor: float = 8.0,
    diurnal_amp: float = 0.8,
    heavy_sigma: float = 1.5,
    slow_fraction: float = 0.2,
    models: Optional[Sequence[str]] = None,
    model_weights: Optional[Sequence[float]] = None,
) -> List[Arrival]:
    """A deterministic arrival schedule for one scenario — drawn up
    front from ``random.Random(seed)``, so the OFFERED load is
    seed-reproducible regardless of how the server responds.

    - ``poisson``      constant-rate memoryless arrivals (PR 5's open
      loop, now with priorities/tenants attached)
    - ``diurnal``      a full sinusoidal day compressed into the run:
      rate(t) = rate·(1 + amp·sin(2πt/T)), T = the nominal run length
      — exercises sustained swing between underload and overload
    - ``flash_crowd``  baseline Poisson with a ``flash_factor``×
      burst over the middle sixth of the run — the thundering herd
      that must shed LOW classes while priority 0 keeps its p99
    - ``heavy_tail``   lognormal inter-arrivals (σ = ``heavy_sigma``)
      with the mean matched to 1/rate: long quiet stretches punctuated
      by dense clumps, the realistic non-Poisson mix
    - ``slow_client``  Poisson arrivals where a seeded
      ``slow_fraction`` of requests dribble their body bytes — the
      server must not let a slow writer stall fast ones

    Priorities and tenants are drawn per request from the seeded RNG
    (defaults: 10%% priority-0, 30%% priority-1, 60%% priority-2;
    uniform tenants) — pass explicit weights to skew."""
    if scenario not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {scenario!r} (want one of {SCENARIOS})"
        )
    if requests <= 0 or rate <= 0:
        raise ValueError("need requests > 0 and rate > 0")
    if priority_weights is None:
        # default mix: a thin premium class over a broad best-effort
        # base, truncated/padded to the configured class count
        base = [0.1, 0.3, 0.6]
        priority_weights = (
            base[:priorities]
            if priorities <= 3
            else base + [0.6] * (priorities - 3)
        )
    if len(priority_weights) != priorities:
        raise ValueError(
            f"priority_weights must have {priorities} entries, got "
            f"{len(priority_weights)}"
        )
    if tenant_weights is None:
        tenant_weights = [1.0] * len(tenants)
    if len(tenant_weights) != len(tenants):
        raise ValueError(
            f"tenant_weights must have {len(tenants)} entries, got "
            f"{len(tenant_weights)}"
        )
    if models and model_weights is None:
        model_weights = [1.0] * len(models)
    if models and len(model_weights) != len(models):
        raise ValueError(
            f"model_weights must have {len(models)} entries, got "
            f"{len(model_weights)}"
        )
    rng = random.Random(seed)
    duration = requests / rate  # nominal run length at the base rate
    flash_t0, flash_t1 = duration / 3.0, duration / 3.0 + duration / 6.0
    mu = math.log(1.0 / rate) - heavy_sigma**2 / 2.0

    out: List[Arrival] = []
    t = 0.0
    for _ in range(int(requests)):
        if scenario == "heavy_tail":
            gap = rng.lognormvariate(mu, heavy_sigma)
        else:
            r = rate
            if scenario == "diurnal":
                r = max(
                    rate * (1.0 + diurnal_amp
                            * math.sin(2.0 * math.pi * t / duration)),
                    rate * 0.05,
                )
            elif scenario == "flash_crowd" and flash_t0 <= t < flash_t1:
                r = rate * flash_factor
            gap = rng.expovariate(r)
        t += gap
        slow = scenario == "slow_client" and rng.random() < slow_fraction
        out.append(Arrival(
            t=t,
            priority=_weighted_pick(
                rng, list(range(priorities)), priority_weights
            ),
            tenant=_weighted_pick(rng, list(tenants), tenant_weights),
            slow=slow,
            model=(
                _weighted_pick(rng, list(models), model_weights)
                if models else None
            ),
        ))
    return out


def recv_response(rfile) -> Tuple[int, Dict[str, str], bytes]:
    """Minimal HTTP/1.1 response parse off a socket makefile('rb') —
    shared by the socket load generator and the fleet router's proxy
    client (serve/fleet.py), so both sides of the fleet speak exactly
    the same wire dialect."""
    line = rfile.readline()
    if not line:
        raise ConnectionError("server closed the connection")
    parts = line.decode("latin-1").split(None, 2)
    status = int(parts[1])
    headers: Dict[str, str] = {}
    while True:
        h = rfile.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        name, _, value = h.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    n = int(headers.get("content-length", 0) or 0)
    body = rfile.read(n) if n else b""
    return status, headers, body


class HttpLoadGenerator:
    """Offer a prebuilt :func:`build_schedule` schedule to a live
    server over REAL sockets (raw stdlib sockets — slow-client body
    dribble needs byte-level control no high-level client gives).

    A dispatcher walks the schedule by wall clock and hands each
    arrival to a worker pool (``concurrency`` persistent keep-alive
    connections); latency is measured from the SCHEDULED arrival, so a
    backlogged pool charges the delay to the requests that suffered it
    (no coordinated omission). ``stop_fn`` is polled between arrivals
    — the SIGTERM latch.

    The ledger separates the outcomes that matter for the drain
    contract: every request must get SOME response (2xx/4xx/5xx);
    ``dropped`` counts requests that got none — the number the
    zero-dropped acceptance test pins at 0."""

    def __init__(
        self,
        host: str,
        port: int,
        schedule: Sequence[Arrival],
        *,
        body_fn: Callable[[int], bytes],
        content_type: str = "application/octet-stream",
        path: str = "/v1/predict",
        concurrency: int = 16,
        stop_fn: Callable[[], bool] = lambda: False,
        slow_chunks: int = 4,
        slow_gap_s: float = 0.02,
        timeout_s: float = 60.0,
        on_arrival: Optional[Callable[[int], None]] = None,
    ):
        self.host = host
        self.port = int(port)
        self.schedule = list(schedule)
        self.body_fn = body_fn
        self.content_type = content_type
        self.path = path
        self.concurrency = max(int(concurrency), 1)
        self.stop_fn = stop_fn
        self.slow_chunks = max(int(slow_chunks), 1)
        self.slow_gap_s = float(slow_gap_s)
        self.timeout_s = float(timeout_s)
        # fires with the schedule index after each arrival is offered —
        # the swap-under-load orchestration keys its trigger off it
        self.on_arrival = on_arrival
        self._lock = threading.Lock()
        self.by_status: Dict[int, int] = {}
        self.dropped = 0
        self.submitted = 0
        self.lat_by_priority: Dict[int, List[float]] = {}

    # -- one request over one (reused) connection ----------------------

    def _connect(self):
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        return sock, sock.makefile("rb")

    def _send(self, sock, i: int, arr: Arrival) -> None:
        body = self.body_fn(i)
        model = (
            f"x-model: {arr.model}\r\n"
            if getattr(arr, "model", None) else ""
        )
        head = (
            f"POST {self.path} HTTP/1.1\r\n"
            f"host: {self.host}:{self.port}\r\n"
            f"x-priority: {arr.priority}\r\n"
            f"x-tenant: {arr.tenant}\r\n"
            + model
            + f"content-type: {self.content_type}\r\n"
            f"content-length: {len(body)}\r\n\r\n"
        ).encode("latin-1")
        if not arr.slow:
            sock.sendall(head + body)
            return
        # slow client: headers at once, then the body in dribbled
        # chunks — the server's reader must wait it out without
        # blocking anyone else's requests
        sock.sendall(head)
        step = max(len(body) // self.slow_chunks, 1)
        for off in range(0, len(body), step):
            sock.sendall(body[off:off + step])
            time.sleep(self.slow_gap_s)

    def _one(self, conn, i: int, arr: Arrival, t_sched: float):
        """Returns (conn, status|None); reconnects once on a torn
        keep-alive connection before counting the request dropped."""
        for attempt in (0, 1):
            if conn is None:
                try:
                    conn = self._connect()
                except OSError:
                    conn = None
                    continue
            sock, rfile = conn
            try:
                self._send(sock, i, arr)
                status, headers, _body = recv_response(rfile)
            except (OSError, ValueError, ConnectionError):
                try:
                    sock.close()
                except OSError:
                    pass
                conn = None
                continue
            lat_ms = (time.perf_counter() - t_sched) * 1000.0
            with self._lock:
                self.by_status[status] = self.by_status.get(status, 0) + 1
                if status == 200:
                    self.lat_by_priority.setdefault(
                        arr.priority, []
                    ).append(lat_ms)
            if headers.get("connection", "").lower() == "close":
                try:
                    sock.close()
                except OSError:
                    pass
                conn = None
            return conn, status
        with self._lock:
            self.dropped += 1
        return conn, None

    def run(self) -> Dict[str, Any]:
        import queue as _queue

        work: "_queue.Queue" = _queue.Queue()

        def worker():
            conn = None
            while True:
                item = work.get()
                if item is None:
                    break
                i, arr, t_sched = item
                conn, _status = self._one(conn, i, arr, t_sched)
            if conn is not None:
                try:
                    conn[0].close()
                except OSError:
                    pass

        workers = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.concurrency)
        ]
        for w in workers:
            w.start()
        t0 = time.perf_counter()
        for i, arr in enumerate(self.schedule):
            delay = (t0 + arr.t) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if self.stop_fn():
                break
            with self._lock:
                self.submitted += 1
            # latency clock starts at the SCHEDULED arrival
            work.put((i, arr, t0 + arr.t))
            if self.on_arrival is not None:
                try:
                    self.on_arrival(i)
                except Exception:
                    pass  # an orchestration hook must not stop the load
        for _ in workers:
            work.put(None)
        for w in workers:
            w.join(self.timeout_s)
        wall_s = time.perf_counter() - t0
        with self._lock:
            responses = sum(self.by_status.values())
            # a worker outliving its join (server wedged past
            # timeout_s) holds requests that are in `submitted` but in
            # neither `responses` nor `dropped` — they got NO answer
            # within the measurement, which is exactly what `dropped`
            # exists to count; the zero-dropped gate must not pass them
            missing = self.submitted - responses - self.dropped
            if missing > 0:
                self.dropped += missing
            return {
                "submitted": self.submitted,
                "responses": responses,
                "dropped": self.dropped,
                "by_status": {
                    str(k): v for k, v in sorted(self.by_status.items())
                },
                "wall_s": round(wall_s, 3),
                "p99_ms_by_priority": {
                    str(p): _pct(sorted(v), 99.0)
                    for p, v in sorted(self.lat_by_priority.items())
                },
            }


def fairness_ratio(
    per_tenant: Dict[str, Dict[str, Any]],
) -> Optional[float]:
    """Max/min ratio of per-tenant SERVICE rates (completed/submitted)
    over tenants that offered load: 1.0 = perfectly even service, large
    = somebody is starving. None when fewer than two tenants offered
    load, or when a tenant got NOTHING through (an infinite ratio is
    not a number a tolerance can judge — the per-tenant table carries
    the zero explicitly)."""
    rates = []
    for t in per_tenant.values():
        submitted = t.get("submitted") or 0
        if submitted > 0:
            rates.append((t.get("completed") or 0) / submitted)
    if len(rates) < 2:
        return None
    lo = min(rates)
    if lo <= 0.0:
        return None
    return round(max(rates) / lo, 4)


def slo_verdict(
    raw: Dict[str, Any],
    batcher_stats: Dict[str, Any],
    *,
    mode: str,
    rate: float,
    seed: int,
    provenance: Optional[Dict[str, Any]] = None,
    warmup_s: Optional[Dict[str, float]] = None,
    preempted: bool = False,
    drained_clean: bool = True,
    scenario: Optional[str] = None,
    per_priority: Optional[Dict[str, Dict[str, Any]]] = None,
    per_tenant: Optional[Dict[str, Dict[str, Any]]] = None,
    fairness: Optional[float] = None,
    client: Optional[Dict[str, Any]] = None,
    slo: Optional[Dict[str, Any]] = None,
    replicas: Optional[Dict[str, Any]] = None,
    scaling: Optional[Dict[str, Any]] = None,
    swap: Optional[Dict[str, Any]] = None,
    resident: Optional[Dict[str, Any]] = None,
    packed: Optional[Dict[str, Any]] = None,
    attribution: Optional[Dict[str, Any]] = None,
    canary: Optional[Dict[str, Any]] = None,
    fleet: Optional[Dict[str, Any]] = None,
    fleet_attribution: Optional[Dict[str, Any]] = None,
    capacity: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble the deterministic strict-JSON SLO verdict.

    The v1 aggregate block is unchanged; the serving front end
    (serve/http.py) adds the v2 blocks: ``scenario`` (arrival-process
    name), ``per_priority`` ({"0": {submitted/completed/shed_*/p50/
    p95/p99}, ...}), ``per_tenant`` (admission counters + shed_rate
    per tenant), ``fairness_ratio`` (max/min per-tenant service rate),
    ``client`` (the socket load generator's own observation — the
    zero-dropped cross-check) and ``slo`` (a target judged at verdict
    time). The replica pool (serve/pool.py) adds the v3 blocks:
    ``replicas`` (the per-replica table + completed-by-version
    ledger), ``scaling`` (the --replicas sweep summary) and ``swap``
    (blue/green rollout disposition). Packed residency (nn/packed.py)
    flattens two more nullable blocks into v3: ``resident`` (per-model
    resident device bytes + the model cache's LRU accounting — the
    number ``compare`` judges as ``serve_resident_bytes_per_model``)
    and ``packed`` (the packed-vs-dense A/B: resident squeeze ratio +
    the honest per-step time on each side, ``serve_packed_step_ms``).
    Both are null on pre-packed runs, so v1/v2/v3-without-packed
    verdicts skip the new metrics cleanly. Request-path tracing
    (obs/rtrace.py) adds the v4 ``attribution`` block: per-priority
    p50/p99 decomposed by lifecycle stage, the stage-sum-vs-e2e
    reconciliation identity and the tail-exemplar waterfalls — the
    block ``compare`` reads its stage-share metrics from. Null when
    tracing is off. The canary stage (serve/canary.py) adds the v5
    ``canary`` block: the rollout episode's evidence — decision,
    trigger, per-detector table, shadow-drift accounting — the source
    of ``compare``'s ``serve_canary_rollbacks`` /
    ``serve_shadow_logit_drift_max`` / ``serve_canary_promote_s``
    gates. Null when no canary stage ran. The fleet router
    (serve/fleet.py) adds the v6 ``fleet`` block: the per-host ledger
    table, the cross-host retry/relay accounting, the summed-across-
    hosts ``dropped`` and the per-host p99 spread — the source of
    ``compare``'s ``serve_fleet_dropped`` / ``serve_fleet_retry_rate``
    / ``serve_fleet_host_p99_spread`` gates. Null on single-host
    runs. The router's FleetTracer (obs/rtrace.py) adds the v7
    ``fleet_attribution`` block: the cross-host waterfall — router
    stages + network + stitched backend stages per priority, retry-hop
    share, per-host stage spread, the cross-hop reconciliation
    identity and the slowest-K exemplars naming host and stage — the
    source of ``compare``'s ``serve_fleet_p99_network_ms`` /
    ``serve_fleet_retry_hop_share`` / ``serve_fleet_stage_spread_max``
    gates. Null when router tracing is off. The capacity observatory
    (obs/capacity.py) adds the v8 ``capacity`` block: the per-(model,
    tenant, priority) demand table with the ledger identity, the
    utilization windows, burn-rate peaks + breach episodes per
    priority and the saturation-headroom estimate — the source of
    ``compare``'s ``serve_burn_rate_max`` / ``serve_headroom_rps`` /
    ``serve_demand_shed_ratio_max`` gates. Null when no capacity
    plane ran (pre-v8 producers and the in-process bench)."""
    lats = raw["latencies_ms"]
    wall = max(raw["wall_s"], 1e-9)
    submitted = max(raw["submitted"], 1)
    verdict = {
        "serve_verdict": VERDICT_SCHEMA_VERSION,
        "mode": mode,
        "rate_rps": rate if mode != "closed" else None,
        "seed": seed,
        "scenario": scenario,
        "requests_submitted": raw["submitted"],
        "requests_completed": raw["completed"],
        "requests_shed": raw["shed"],
        "requests_failed": raw.get("failed", 0),
        # malformed-body 400s (serve-http): the tenant's own bad
        # requests — neither completed nor shed nor failed, so the
        # ledger identity completed+shed+failed+rejected == submitted
        # survives bad clients
        "requests_rejected": raw.get("rejected", 0),
        "shed_rate": round(raw["shed"] / submitted, 6),
        "p50_ms": _pct(lats, 50.0),
        "p95_ms": _pct(lats, 95.0),
        "p99_ms": _pct(lats, 99.0),
        "throughput_rps": round(raw["completed"] / wall, 3),
        "wall_s": round(wall, 3),
        "mean_batch_occupancy": batcher_stats.get("mean_occupancy"),
        "batches": batcher_stats.get("batches"),
        "max_queue_depth_seen": batcher_stats.get("max_queue_depth_seen"),
        "max_queue": batcher_stats.get("max_queue"),
        "per_priority": per_priority,
        "per_tenant": per_tenant,
        "fairness_ratio": fairness,
        "client": client,
        "slo": slo,
        "replicas": replicas,
        "scaling": scaling,
        "swap": swap,
        "resident": resident,
        "packed": packed,
        "attribution": attribution,
        "canary": canary,
        "fleet": fleet,
        "fleet_attribution": fleet_attribution,
        "capacity": capacity,
        # bucket keys as strings: the verdict must survive a JSON
        # round trip unchanged (int dict keys would silently stringify)
        "warmup_compile_s": (
            {str(k): v for k, v in warmup_s.items()} if warmup_s else None
        ),
        "preempted": bool(preempted),
        "drained_clean": bool(drained_clean),
        "provenance": provenance or {},
    }
    from bdbnn_tpu.obs.events import jsonsafe

    return jsonsafe(verdict)


def http_slo_verdict(
    accounting: Dict[str, Any],
    batcher_stats: Dict[str, Any],
    admission_stats: Dict[str, Any],
    *,
    scenario: str,
    rate: float,
    seed: int,
    provenance: Optional[Dict[str, Any]] = None,
    warmup_s: Optional[Dict[str, float]] = None,
    preempted: bool = False,
    drained_clean: bool = True,
    client: Optional[Dict[str, Any]] = None,
    slo_p99_ms: float = 0.0,
    replicas: Optional[Dict[str, Any]] = None,
    swap: Optional[Dict[str, Any]] = None,
    resident: Optional[Dict[str, Any]] = None,
    packed: Optional[Dict[str, Any]] = None,
    attribution: Optional[Dict[str, Any]] = None,
    canary: Optional[Dict[str, Any]] = None,
    capacity: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Build the v2 verdict from the HTTP front end's request ledger
    (:meth:`serve.http.HttpFrontEnd.accounting`), the batcher's
    per-priority occupancy and the admission controller's per-tenant
    counters — the three sources of truth, joined exactly once."""
    lat_p = accounting["latencies_ms_by_priority"]
    counts_p = accounting["counts_by_priority"]
    per_priority: Dict[str, Dict[str, Any]] = {}
    all_lats: List[float] = []
    for p, (lats, counts) in enumerate(zip(lat_p, counts_p)):
        all_lats += lats
        shed = (
            counts["shed_draining"] + counts["shed_over_quota"]
            + counts["shed_queue_full"]
            + counts.get("shed_unavailable", 0)
        )
        per_priority[str(p)] = {
            "submitted": counts["submitted"],
            "completed": counts["completed"],
            "failed": counts["failed"],
            "rejected": counts.get("rejected", 0),
            "shed": shed,
            "shed_draining": counts["shed_draining"],
            "shed_over_quota": counts["shed_over_quota"],
            "shed_queue_full": counts["shed_queue_full"],
            "shed_unavailable": counts.get("shed_unavailable", 0),
            "shed_rate": round(
                shed / max(counts["submitted"], 1), 6
            ),
            "p50_ms": _pct(lats, 50.0),
            "p95_ms": _pct(lats, 95.0),
            "p99_ms": _pct(lats, 99.0),
        }
    per_tenant: Dict[str, Dict[str, Any]] = {}
    for tenant, c in (admission_stats.get("tenants") or {}).items():
        submitted = c["admitted"] + c["over_quota"]
        per_tenant[tenant] = {
            "submitted": submitted,
            "admitted": c["admitted"],
            "completed": c["completed"],
            "failed": c["failed"],
            "rejected": c.get("rejected", 0),
            "over_quota": c["over_quota"],
            "shed_queue": c["shed"],
            "shed_rate": c["shed_rate"],
            "quota_rate": c["quota_rate"],
            "quota_burst": c["quota_burst"],
        }
    submitted = sum(c["submitted"] for c in counts_p)
    completed = sum(c["completed"] for c in counts_p)
    failed = sum(c["failed"] for c in counts_p)
    rejected = sum(c.get("rejected", 0) for c in counts_p)
    shed = sum(v["shed"] for v in per_priority.values())
    all_lats.sort()
    slo = None
    if slo_p99_ms > 0:
        p0_p99 = per_priority.get("0", {}).get("p99_ms")
        slo = {
            "p99_ms_target_priority0": slo_p99_ms,
            "p99_ms_priority0": p0_p99,
            "met": bool(p0_p99 is not None and p0_p99 <= slo_p99_ms),
        }
    return slo_verdict(
        {
            "submitted": submitted,
            "completed": completed,
            "shed": shed,
            "failed": failed,
            "rejected": rejected,
            "wall_s": accounting["wall_s"],
            "latencies_ms": all_lats,
        },
        batcher_stats,
        mode="http",
        rate=rate,
        seed=seed,
        provenance=provenance,
        warmup_s=warmup_s,
        preempted=preempted,
        drained_clean=drained_clean,
        scenario=scenario,
        per_priority=per_priority,
        per_tenant=per_tenant,
        fairness=fairness_ratio(per_tenant),
        client=client,
        slo=slo,
        replicas=replicas,
        swap=swap,
        resident=resident,
        packed=packed,
        attribution=attribution,
        canary=canary,
        capacity=capacity,
    )


def run_serve_bench(cfg) -> Dict[str, Any]:
    """End-to-end serving benchmark over an export artifact (the
    ``serve-bench`` CLI body). ``cfg`` is a
    :class:`bdbnn_tpu.configs.config.ServeBenchConfig`. Returns
    ``{verdict, run_dir}``; the verdict is also written to
    ``<run_dir>/verdict.json`` (and ``cfg.out`` when set) and emitted as
    the final ``serve`` event."""
    from bdbnn_tpu.train.resilience import PreemptionHandler

    cfg = cfg.validate()
    # the SIGTERM latch covers the WHOLE bench — a preemption during
    # the multi-second AOT warmup must drain-and-report, not die with
    # the default disposition
    with PreemptionHandler() as handler:
        return _serve_bench_body(cfg, handler)


def _serve_bench_body(cfg, handler) -> Dict[str, Any]:
    """Route one serve-bench invocation: the classic single-engine path
    for the default config, the replica-pool path (optionally a
    multi-N scaling sweep) when ``--replicas`` asks for more than one
    replica — or for the paced fabric mode either way."""
    sweep = tuple(sorted({int(n) for n in cfg.replicas}))
    if sweep == (1,) and cfg.pace_ms == 0:
        return _serve_bench_single(cfg, handler)
    return _serve_bench_pool(cfg, handler, sweep)


class _ArtifactMeta:
    """Just the artifact metadata the pooled orchestrations need —
    arch/dataset/shape/buckets read from ``artifact.json``, with NO
    weight load and NO device placement (the serving weights live
    inside the replicas' own engines; paced fabric mode loads nothing
    at all). Duck-types the fields the manifest/provenance helpers
    read off a real engine."""

    def __init__(self, artifact_dir: str, buckets):
        from bdbnn_tpu.serve.export import read_artifact

        self.artifact = read_artifact(artifact_dir)
        self.arch = self.artifact["arch"]
        self.dataset = self.artifact["dataset"]
        self.image_size = int(self.artifact["image_size"])
        self.num_classes = int(self.artifact["num_classes"])
        self.buckets = tuple(sorted({int(b) for b in buckets}))


def _bench_manifest_fields(cfg, engine, prov, recipe) -> Dict[str, Any]:
    """The manifest fields both serve-bench paths (single-engine and
    replica-pool) share — one place for the provenance/knob surface, so
    a new field cannot land in one path and drift from the other."""
    return {
        "mode": "serve-bench",
        "artifact": os.path.abspath(cfg.artifact),
        # recipe fields flow through so `compare` aligns serving runs
        # on the same export provenance — None entries dropped and
        # spread FIRST, so a bare-checkpoint export's empty recipe can
        # never null out the arch/dataset the engine positively knows
        **{k: v for k, v in recipe.items() if v is not None},
        "arch": engine.arch,
        "dataset": engine.dataset,
        "export_config_hash": prov.get("config_hash"),
        "buckets": list(cfg.buckets),
        "queue_depth": cfg.queue_depth,
        "max_delay_ms": cfg.max_delay_ms,
        "load_mode": cfg.mode,
        "rate": cfg.rate,
        "requests": cfg.requests,
        "concurrency": cfg.concurrency,
        "seed": cfg.seed,
        "rtrace": cfg.rtrace,
        "rtrace_sample_every": cfg.rtrace_sample_every,
    }


def _serve_provenance(
    artifact_dir, engine, prov, recipe, manifest
) -> Dict[str, Any]:
    """The verdict's provenance block — shared by both bench paths and
    the HTTP front end (whose ``artifact_dir`` may be a
    registry-resolved version, not the raw CLI argument)."""
    return {
        "artifact": os.path.abspath(artifact_dir),
        "arch": engine.arch,
        "dataset": engine.dataset,
        "config_hash": prov.get("config_hash"),
        "recipe": recipe,
        "serve_config_hash": manifest.get("config_hash"),
    }


def write_verdict_files(
    verdict: Dict[str, Any], run_dir: str, out: str = ""
) -> None:
    """Atomically (tmp + rename) write the verdict to the run dir and,
    when set, the caller's ``--out`` path — the one write protocol all
    three serving orchestrations use."""
    for path in (os.path.join(run_dir, VERDICT_NAME), out or None):
        if path:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(verdict, f, indent=2, sort_keys=True)
            os.replace(tmp, path)


def _pool_replicas_block(
    pool_stats: Optional[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """The verdict's v3 ``replicas`` block from one pool's final
    :meth:`~bdbnn_tpu.serve.pool.ReplicaPool.stats` snapshot: the
    per-replica table (device, version, batches, completed requests,
    occupancy share, restarts) and the completed-by-version ledger the
    swap acceptance test pins."""
    if pool_stats is None:
        return None
    total = max(pool_stats["completed"], 1)
    return {
        "n": pool_stats["n_replicas"],
        "version": pool_stats["version"],
        "dispatched_batches": pool_stats["dispatched"],
        "pool_shed_batches": pool_stats["shed"],
        "restarts": pool_stats["restarts"],
        "completed_by_version": pool_stats["completed_by_version"],
        "per_replica": [
            {
                "replica": r["replica"],
                "device": r["device"],
                "version": r["version"],
                "state": r["state"],
                "batches": r["batches"],
                "completed": r["completed"],
                # occupancy share: this replica's slice of the served
                # requests — a wedged/unhealthy replica shows up as a
                # hole here even when the aggregate throughput held
                "share": round(r["completed"] / total, 4),
                "restarts": r["restarts"],
            }
            for r in pool_stats["replicas"]
        ],
    }


def _serve_bench_pool(cfg, handler, sweep) -> Dict[str, Any]:
    """The replica-pool serve-bench: for each N in ``sweep`` build an
    N-replica pool (one AOT-warmed engine per mesh device — or a paced
    stub per simulated device in fabric mode), drive the configured
    load through the front batcher's async dispatch, and drain. With
    more than one N the verdict carries the ``scaling`` block
    (throughput per N, monotonicity, efficiency at the largest N =
    throughput(N_max) / ((N_max/N_min) * throughput(N_min)) — the
    number ``compare`` judges as ``serve_scaling_efficiency``)."""
    import datetime

    import numpy as np

    from bdbnn_tpu.obs.events import EventWriter
    from bdbnn_tpu.obs.manifest import write_manifest
    from bdbnn_tpu.serve.pool import (
        ReplicaPool,
        first_warm_capture,
        make_engine_runner_factory,
        replica_stats_fields,
        resident_block,
    )

    paced = cfg.pace_ms > 0
    # metadata/shape source only (no weight load, no device_put) —
    # replica engines are built and AOT-warmed per device by the
    # factory; paced mode loads nothing at all
    engine = _ArtifactMeta(cfg.artifact, cfg.buckets)

    stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    run_dir = os.path.join(cfg.log_path, stamp)
    os.makedirs(run_dir, exist_ok=True)
    prov = engine.artifact.get("provenance", {})
    recipe = prov.get("recipe") or {}
    manifest = write_manifest(
        run_dir,
        {
            **_bench_manifest_fields(cfg, engine, prov, recipe),
            "replicas": list(sweep),
            "pace_ms": cfg.pace_ms,
            "packed_weights": cfg.packed_weights,
            "packed_impl": cfg.packed_impl,
        },
    )
    events = EventWriter(run_dir, max_bytes=int(cfg.events_max_mb * 2**20))
    events.emit(
        "serve",
        phase="start",
        artifact=os.path.abspath(cfg.artifact),
        arch=engine.arch,
        buckets=list(cfg.buckets),
        mode=cfg.mode,
        rate_rps=cfg.rate if cfg.mode == "open" else None,
        requests=cfg.requests,
        queue_depth=cfg.queue_depth,
        max_delay_ms=cfg.max_delay_ms,
        replicas=list(sweep),
        pace_ms=cfg.pace_ms if paced else None,
    )

    warm_compile, _on_engine = first_warm_capture()
    factory = make_engine_runner_factory(
        cfg.buckets,
        pace_ms=cfg.pace_ms,
        on_engine=_on_engine,
        packed=cfg.packed_weights == "on",
        packed_impl=cfg.packed_impl,
        on_event=lambda kind, **f: events.emit(kind, **f),
    )
    rng = np.random.default_rng(cfg.seed)
    img_pool = rng.standard_normal(
        (32, engine.image_size, engine.image_size, 3)
    ).astype(np.float32)
    sample_fn = lambda i: img_pool[i % len(img_pool)]

    throughput: Dict[str, float] = {}
    passes: Dict[int, Any] = {}
    caches_per_pass: Dict[int, Any] = {}
    tracers: Dict[int, Any] = {}
    for n in sweep:
        if handler.preempted:
            break
        # snapshot by IDENTITY, not index: the factory REPLACES a
        # re-used device's stale cache in place (removal shifts list
        # indices), so a tail slice would miss re-created caches for
        # devices earlier passes already used
        caches_before = {id(c) for c in factory.caches}
        if paced:
            devices: List[Any] = [f"paced:{i}" for i in range(n)]
        else:
            from bdbnn_tpu.parallel.mesh import replica_devices

            devices = list(replica_devices(n))
        pool = ReplicaPool(
            factory,
            devices,
            artifact_ref=cfg.artifact,
            version="v0001",
            max_queue_batches=cfg.replica_queue_batches,
            wedge_timeout_s=cfg.wedge_timeout_s,
            on_event=lambda kind, **f: events.emit(kind, **f),
        )

        # live telemetry parity with the single-engine path: rolling
        # per-batch `serve` stats (on_batch fires from the async
        # settle callback too) + the per-replica heartbeat `watch`
        # renders — a pooled bench must not go dark while it runs
        window: List[float] = []
        win_lock = threading.Lock()
        batch_counter = [0]
        emit_every = max(
            cfg.requests // (20 * max(engine.buckets[-1], 1)), 1
        )

        def on_batch(stats: Dict[str, Any], n=n) -> None:
            with win_lock:
                window.append(stats["oldest_wait_ms"] + stats["run_ms"])
                del window[:-256]
                rolling = sorted(window)
                batch_counter[0] += 1
                nb = batch_counter[0]
            if nb % emit_every == 0:
                events.emit(
                    "serve",
                    phase="stats",
                    replicas_n=n,
                    batch_size=stats["batch_size"],
                    occupancy=stats["occupancy"],
                    queue_depth=stats["queue_depth"],
                    rolling_p99_ms=_pct(rolling, 99.0),
                    completed=stats["completed"],
                    shed=stats["shed"],
                )

        # request-path tracing (obs/rtrace.py): every submission gets a
        # queue -> coalesce -> dispatch -> compute waterfall; sampled
        # exemplars + periodic stage histograms flow as rtrace events
        tracer = None
        if cfg.rtrace:
            from bdbnn_tpu.obs.rtrace import RequestTracer

            tracer = RequestTracer(
                seed=cfg.seed,
                sample_every=cfg.rtrace_sample_every,
                tail_k=cfg.rtrace_tail_k,
                on_sample=lambda wf: events.emit(
                    "rtrace", phase="request", **wf
                ),
            )
            tracers[n] = tracer

        pump_stop = threading.Event()

        def pump(pool=pool, tracer=tracer):
            while not pump_stop.wait(0.5):
                events.emit(
                    "replica", phase="stats",
                    **replica_stats_fields(pool.stats()),
                )
                if tracer is not None:
                    events.emit(
                        "rtrace", phase="stats", **tracer.stats()
                    )

        t_pump = threading.Thread(
            target=pump, name="bench-replica-stats", daemon=True
        )
        t_pump.start()

        batcher = MicroBatcher(
            pool.submit,
            max_batch=engine.buckets[-1],
            max_queue=cfg.queue_depth,
            max_delay_ms=cfg.max_delay_ms,
            on_batch=on_batch,
            # backpressure: ~1 executing + 1 queued batch per replica —
            # overload sheds at the front (priority-ordered), never by
            # failing accepted batches against full replica queues
            max_pending_batches=2 * n,
        )
        gen = LoadGenerator(
            tracer.bind(batcher.submit) if tracer is not None
            else batcher.submit,
            sample_fn,
            mode=cfg.mode,
            requests=cfg.requests,
            rate=cfg.rate,
            concurrency=cfg.concurrency,
            seed=cfg.seed,
            stop_fn=lambda: handler.preempted,
        )
        raw = gen.run()
        drained = batcher.drain(timeout=120.0)
        drained = pool.drain(timeout=60.0) and drained
        pump_stop.set()
        t_pump.join(timeout=5.0)
        thr = round(raw["completed"] / max(raw["wall_s"], 1e-9), 3)
        throughput[str(n)] = thr
        passes[n] = (raw, batcher.stats(), pool.stats(), drained)
        caches_per_pass[n] = [
            c for c in factory.caches if id(c) not in caches_before
        ]
        events.emit(
            "serve",
            phase="scaling",
            replicas_n=n,
            throughput_rps=thr,
            completed=raw["completed"],
            shed=raw["shed"],
            wall_s=round(raw["wall_s"], 3),
        )

    if passes:
        n_last = max(passes)
        raw, batcher_stats, pool_stats, drained_clean = passes[n_last]
        resident = resident_block(caches_per_pass.get(n_last, []))
        if resident is not None:
            events.emit(
                "memory",
                phase="serve_resident",
                available=True,
                devices=[],
                peak_bytes=None,
                limit_bytes=None,
                weights_mode=(
                    "packed" if cfg.packed_weights == "on" else "dense"
                ),
                resident_bytes=resident["bytes_per_model_max"],
                models=len(resident["models"]),
                replicas=resident["replicas"],
            )
    else:
        # preempted before the first pass could offer load: an honest
        # empty verdict, drained by construction
        raw = {"submitted": 0, "completed": 0, "shed": 0, "failed": 0,
               "wall_s": 0.0, "latencies_ms": []}
        batcher_stats, pool_stats, drained_clean = {}, None, True
        resident = None

    scaling = None
    if len(passes) > 1:
        ns = sorted(passes)
        n_min, n_max = ns[0], ns[-1]
        t_min, t_max = throughput[str(n_min)], throughput[str(n_max)]
        vals = [throughput[str(n)] for n in ns]
        scaling = {
            "replicas": ns,
            "throughput_rps": throughput,
            # ideal scaling from the smallest measured N: 1.0 = linear
            "efficiency": (
                round(t_max / ((n_max / n_min) * t_min), 4)
                if t_min else None
            ),
            "monotone": all(b >= a for a, b in zip(vals, vals[1:])),
            "paced_ms": cfg.pace_ms if paced else None,
        }

    verdict = slo_verdict(
        raw,
        batcher_stats,
        mode=cfg.mode,
        rate=cfg.rate,
        seed=cfg.seed,
        provenance=_serve_provenance(
            cfg.artifact, engine, prov, recipe, manifest
        ),
        warmup_s=dict(warm_compile) if warm_compile else None,
        preempted=handler.preempted,
        drained_clean=drained_clean,
        replicas=_pool_replicas_block(pool_stats),
        scaling=scaling,
        resident=resident,
        # attribution from the LARGEST measured pass — the same pass
        # every other aggregate in this verdict reports
        attribution=(
            tracers[max(passes)].attribution()
            if passes and max(passes) in tracers else None
        ),
    )
    events.emit("serve", phase="verdict", **verdict)
    events.close()
    write_verdict_files(verdict, run_dir, cfg.out)
    return {"verdict": verdict, "run_dir": run_dir}


def _serve_bench_single(cfg, handler) -> Dict[str, Any]:
    """The single-engine serve-bench, now residency-aware: with
    ``--packed-weights on`` the engine keeps its binary convs 1-bit
    resident (nn/packed.py); with ``ab`` the SAME load runs twice —
    dense first, then packed — and the verdict's ``packed`` block
    records the memory squeeze (resident bytes per side + ratio) and
    an honest per-step time delta, even when step time is a wash. The
    primary verdict aggregates come from the PACKED pass (the
    configuration being shipped); each pass emits a ``memory`` event
    (phase ``serve_resident``) recording resident-bytes before/after
    the squeeze."""
    import datetime

    import numpy as np

    from bdbnn_tpu.obs.events import EventWriter
    from bdbnn_tpu.obs.manifest import write_manifest
    from bdbnn_tpu.serve.engine import InferenceEngine

    mode_plan = {
        "off": (("dense", False),),
        "on": (("packed", True),),
        "ab": (("dense", False), ("packed", True)),
    }[cfg.packed_weights]

    run_dir = os.path.join(
        cfg.log_path,
        datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S"),
    )
    os.makedirs(run_dir, exist_ok=True)
    events = EventWriter(
        run_dir, max_bytes=int(cfg.events_max_mb * 2**20)
    )

    manifest = None
    prov: Dict[str, Any] = {}
    recipe: Dict[str, Any] = {}
    passes: Dict[str, Dict[str, Any]] = {}
    engine_meta: Any = None
    for label, is_packed in mode_plan:
        if handler.preempted and passes:
            break
        engine = InferenceEngine(
            cfg.artifact,
            buckets=cfg.buckets,
            packed=is_packed,
            packed_impl=cfg.packed_impl,
        )
        warmup_s = dict(engine.compile_seconds)
        if manifest is None:
            prov = engine.artifact.get("provenance", {})
            recipe = prov.get("recipe") or {}
            manifest = write_manifest(
                run_dir,
                {
                    **_bench_manifest_fields(cfg, engine, prov, recipe),
                    "packed_weights": cfg.packed_weights,
                    "packed_impl": cfg.packed_impl,
                },
            )
        residency = engine.residency()
        # the residency datapoint: what THIS mode keeps alive in device
        # memory vs what the other mode would — before/after on one
        # timeline, consumable by any `memory`-event reader
        events.emit(
            "memory",
            phase="serve_resident",
            available=True,
            devices=[],
            peak_bytes=None,
            limit_bytes=None,
            weights_mode=label,
            packed_impl=cfg.packed_impl if is_packed else None,
            resident_bytes=residency["resident_bytes"],
            dense_equiv_bytes=residency["dense_equiv_bytes"],
            packed_equiv_bytes=residency["packed_equiv_bytes"],
            ratio=residency["ratio"],
        )
        step_ms = engine.time_step(iters=5)
        events.emit(
            "serve",
            phase="start",
            artifact=os.path.abspath(cfg.artifact),
            arch=engine.arch,
            buckets=list(cfg.buckets),
            warmup_compile_s=warmup_s,
            mode=cfg.mode,
            # closed mode offers no Poisson load — null, like verdict
            rate_rps=cfg.rate if cfg.mode == "open" else None,
            requests=cfg.requests,
            queue_depth=cfg.queue_depth,
            max_delay_ms=cfg.max_delay_ms,
            weights_mode=label,
        )

        # rolling p99 over a sliding latency window for the live
        # `serve` stats events `watch` renders
        window: List[float] = []
        win_lock = threading.Lock()
        batch_counter = [0]
        emit_every = max(
            cfg.requests // (20 * max(engine.buckets[-1], 1)), 1
        )

        def on_batch(stats: Dict[str, Any]) -> None:
            # per-batch latency proxy: oldest request's wait + run
            with win_lock:
                window.append(stats["oldest_wait_ms"] + stats["run_ms"])
                del window[:-256]
                rolling = sorted(window)
                batch_counter[0] += 1
                n = batch_counter[0]
            if n % emit_every == 0:
                events.emit(
                    "serve",
                    phase="stats",
                    batch_size=stats["batch_size"],
                    occupancy=stats["occupancy"],
                    queue_depth=stats["queue_depth"],
                    rolling_p99_ms=_pct(rolling, 99.0),
                    completed=stats["completed"],
                    shed=stats["shed"],
                )

        def runner(samples: List[np.ndarray], engine=engine):
            return engine.predict_logits(np.stack(samples))

        batcher = MicroBatcher(
            runner,
            max_batch=engine.buckets[-1],
            max_queue=cfg.queue_depth,
            max_delay_ms=cfg.max_delay_ms,
            on_batch=on_batch,
        )

        # a small pregenerated pool of deterministic samples, cycled —
        # the offered traffic is seed-reproducible (and identical on
        # both A/B sides) without allocating thousands of images
        rng = np.random.default_rng(cfg.seed)
        pool = rng.standard_normal(
            (32, engine.image_size, engine.image_size, 3)
        ).astype(np.float32)
        sample_fn = lambda i: pool[i % len(pool)]

        # request-path tracing (obs/rtrace.py): queue -> coalesce ->
        # compute waterfalls per request (no socket, so no read/admit/
        # respond; no pool, so the dispatch stage stays empty -> null)
        tracer = None
        if cfg.rtrace:
            from bdbnn_tpu.obs.rtrace import RequestTracer

            tracer = RequestTracer(
                seed=cfg.seed,
                sample_every=cfg.rtrace_sample_every,
                tail_k=cfg.rtrace_tail_k,
                on_sample=lambda wf, label=label: events.emit(
                    "rtrace", phase="request", weights_mode=label, **wf
                ),
            )

        gen = LoadGenerator(
            tracer.bind(batcher.submit) if tracer is not None
            else batcher.submit,
            sample_fn,
            mode=cfg.mode,
            requests=cfg.requests,
            rate=cfg.rate,
            concurrency=cfg.concurrency,
            seed=cfg.seed,
            stop_fn=lambda: handler.preempted,
        )
        raw = gen.run()
        # graceful drain: accepted requests are all answered before
        # the verdict is written — on SIGTERM this is the whole point
        drained_clean = batcher.drain(timeout=120.0)
        if tracer is not None:
            events.emit(
                "rtrace", phase="stats", weights_mode=label,
                **tracer.stats(),
            )
        wall = max(raw["wall_s"], 1e-9)
        passes[label] = {
            "raw": raw,
            "batcher_stats": batcher.stats(),
            "drained_clean": drained_clean,
            "warmup_s": warmup_s,
            "residency": residency,
            "step_ms": step_ms,
            # the engine's own blocked-compute window under the real
            # interleave — the compute-stage cross-check attribution
            # cites next to the idle time_step calibration
            "step_stats": engine.step_stats(),
            "tracer": tracer,
            "throughput_rps": round(raw["completed"] / wall, 3),
            "p99_ms": _pct(raw["latencies_ms"], 99.0),
        }
        # keep only the provenance scalars, then drop EVERY reference
        # that reaches the engine — the engine local, the runner whose
        # default arg captured it, and the batcher/gen that hold the
        # runner: the next pass builds its own engine, and an A/B must
        # not hold both resident sets at once (a surviving reference
        # would pin the dense weights through the packed pass's
        # construction and warmup — that overlap is the bug the A/B
        # exists to measure)
        engine_meta = _EngineMeta(engine.arch, engine.dataset)
        del engine, runner, batcher, gen

    primary = passes.get("packed") or passes["dense"]

    packed_block = None
    if cfg.packed_weights != "off":
        sides = {}
        for label in ("dense", "packed"):
            p = passes.get(label)
            if p is None:
                # a side that never ran (packed-only mode, or an ab
                # run preempted between passes) still records its
                # resident footprint computed from the OTHER side's
                # tensor index — dense-equivalent for a missing dense
                # pass, packed-equivalent for a missing packed pass
                # (filling the packed side with dense bytes would
                # report resident_ratio ~1.0, as if packing bought
                # nothing) — so the squeeze stays visible without the
                # double run
                pr = primary["residency"]
                equiv_key = (
                    "dense_equiv_bytes" if label == "dense"
                    else "packed_equiv_bytes"
                )
                sides[label] = {
                    "resident_bytes": pr[equiv_key],
                    "step_ms": None,
                    "throughput_rps": None,
                    "p99_ms": None,
                }
                continue
            sides[label] = {
                "resident_bytes": p["residency"]["resident_bytes"],
                "step_ms": p["step_ms"],
                "throughput_rps": p["throughput_rps"],
                "p99_ms": p["p99_ms"],
            }
        d_bytes = sides["dense"]["resident_bytes"]
        p_bytes = sides["packed"]["resident_bytes"]
        d_ms, p_ms = sides["dense"]["step_ms"], sides["packed"]["step_ms"]
        packed_block = {
            "mode": cfg.packed_weights,
            "impl": cfg.packed_impl,
            "dense": sides["dense"],
            "packed": sides["packed"],
            "resident_ratio": (
                round(d_bytes / max(p_bytes, 1), 3)
                if d_bytes is not None and p_bytes is not None else None
            ),
            "step_ms_delta_pct": (
                round((p_ms - d_ms) / d_ms * 100.0, 2)
                if d_ms and p_ms is not None else None
            ),
        }

    from bdbnn_tpu.serve.pool import single_engine_resident_block

    resident = single_engine_resident_block(
        primary["residency"], completed=primary["raw"]["completed"]
    )

    attribution = None
    if primary.get("tracer") is not None:
        attribution = primary["tracer"].attribution(
            device={
                # blocked-compute cross-check: idle calibration (the
                # time_step mean) next to the window measured under
                # the real serving interleave
                "time_step_ms": primary["step_ms"],
                **primary["step_stats"],
            }
        )

    verdict = slo_verdict(
        primary["raw"],
        primary["batcher_stats"],
        mode=cfg.mode,
        rate=cfg.rate,
        seed=cfg.seed,
        provenance=_serve_provenance(
            cfg.artifact, engine_meta, prov, recipe, manifest
        ),
        warmup_s=primary["warmup_s"],
        preempted=handler.preempted,
        drained_clean=all(p["drained_clean"] for p in passes.values()),
        resident=resident,
        packed=packed_block,
        attribution=attribution,
    )
    events.emit("serve", phase="verdict", **verdict)
    events.close()
    write_verdict_files(verdict, run_dir, cfg.out)
    return {"verdict": verdict, "run_dir": run_dir}


__all__ = [
    "SCENARIOS",
    "VERDICT_NAME",
    "VERDICT_SCHEMA_VERSION",
    "Arrival",
    "HttpLoadGenerator",
    "LoadGenerator",
    "build_schedule",
    "fairness_ratio",
    "http_slo_verdict",
    "percentile",
    "recv_response",
    "run_serve_bench",
    "slo_verdict",
    "write_verdict_files",
]
