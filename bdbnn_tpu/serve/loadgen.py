"""SLO load generator + the ``serve-bench`` orchestration.

Two canonical load models (Schroeder et al.'s open-vs-closed
distinction):

- **closed loop** — ``concurrency`` workers each keep exactly one
  request in flight (submit, wait, repeat). Measures the system's
  sustainable throughput; latency is flow-controlled by the system
  itself.
- **open loop** — requests arrive on a Poisson process at ``rate``
  req/s regardless of completions (arrivals are pre-scheduled from a
  seeded ``random.Random``, so the offered load is deterministic per
  seed). This is what production traffic looks like: an overloaded
  server keeps receiving requests, which is exactly what exercises the
  bounded queue + load shedding path.

The output is a deterministic-schema strict-JSON **SLO verdict**:
p50/p95/p99 latency, throughput, mean batch occupancy, shed rate,
drain/preemption disposition — the serving analogue of the training
side's BENCH/ACCURACY artifacts, and what ``compare`` judges across
builds (``--tol-rel``, exit 3 on regression).

``run_serve_bench`` wires the whole serving stack together: engine
(AOT-warmed buckets) → micro-batcher (bounded queue) → load generator,
with a run directory (manifest + ``events.jsonl`` carrying ``serve``
events) so ``summarize``/``watch``/``compare`` see serving runs through
the same pipeline as training runs. SIGTERM/SIGINT latches a
``PreemptionHandler`` flag (train/resilience.py); the generator stops
offering load, the batcher drains, and every accepted request is
answered before the verdict is written.
"""

from __future__ import annotations

import json
import math
import os
import random
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

from bdbnn_tpu.serve.batching import LoadShedError, MicroBatcher

VERDICT_NAME = "verdict.json"
VERDICT_SCHEMA_VERSION = 1


def percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over an ASCENDING list (q in [0, 100]);
    None on empty input. Nearest-rank (not interpolated) so the verdict
    is reproducible across numpy versions and needs no numpy at all."""
    if not sorted_vals:
        return None
    rank = max(int(math.ceil(q / 100.0 * len(sorted_vals))), 1)
    return sorted_vals[rank - 1]


class LoadGenerator:
    """Offer load to a submit callable; collect per-request latency.

    ``submit_fn(payload) -> Future`` (the micro-batcher's ``submit``);
    ``sample_fn(i) -> payload`` supplies request payloads (cycled from a
    small pregenerated pool in serve-bench). ``stop_fn()`` polled
    between arrivals — the SIGTERM latch."""

    def __init__(
        self,
        submit_fn: Callable[[Any], Future],
        sample_fn: Callable[[int], Any],
        *,
        mode: str = "open",
        requests: int = 200,
        rate: float = 100.0,
        concurrency: int = 4,
        seed: int = 0,
        stop_fn: Callable[[], bool] = lambda: False,
    ):
        if mode not in ("open", "closed"):
            raise ValueError(f"mode must be open|closed, got {mode!r}")
        self.submit_fn = submit_fn
        self.sample_fn = sample_fn
        self.mode = mode
        self.requests = int(requests)
        self.rate = float(rate)
        self.concurrency = max(int(concurrency), 1)
        self.seed = int(seed)
        self.stop_fn = stop_fn
        self._lock = threading.Lock()
        self.latencies_ms: List[float] = []
        self.shed = 0
        self.failed = 0  # accepted but errored (NOT load shedding)
        self.submitted = 0
        # accepted-Future accounting: _done callbacks may run a beat
        # AFTER result() wakes its waiter (Future resolves waiters
        # first), so run() must wait for _processed to catch up with
        # _accepted before snapshotting counters into the verdict
        self._accepted = 0
        self._processed = 0
        self._inflight: List[Future] = []

    # -- submission ----------------------------------------------------

    def _one(
        self, i: int, wait: bool, t0: Optional[float] = None
    ) -> Optional[Future]:
        """Submit request ``i``; latency is measured from ``t0`` when
        given — open-loop mode passes the SCHEDULED arrival time, so a
        generator that falls behind under overload charges the backlog
        delay to the requests that suffered it (no coordinated
        omission) instead of under-reporting the tail."""
        if t0 is None:
            t0 = time.perf_counter()
        try:
            fut = self.submit_fn(self.sample_fn(i))
        except LoadShedError:
            with self._lock:
                self.shed += 1
                self.submitted += 1
            return None
        with self._lock:
            self.submitted += 1
            self._accepted += 1

        def _done(f: Future, t0=t0):
            lat = (time.perf_counter() - t0) * 1000.0
            exc = None if f.cancelled() else f.exception()
            with self._lock:
                if not f.cancelled() and exc is None:
                    self.latencies_ms.append(lat)
                elif isinstance(exc, LoadShedError):
                    # accepted but shed by a racing drain: still load
                    # shedding, still part of the accounting identity
                    self.shed += 1
                else:
                    # engine/runner breakage is NOT shedding — an
                    # operator must not read a broken artifact as queue
                    # overload
                    self.failed += 1
                self._processed += 1

        fut.add_done_callback(_done)
        if wait:
            try:
                fut.result()
            except Exception:
                pass  # recorded as not-completed; the verdict shows it
        return fut

    def _run_closed(self) -> None:
        per_worker = self.requests // self.concurrency
        extra = self.requests % self.concurrency

        def worker(wid: int, n: int):
            # each worker owns a disjoint id range; min(wid, extra)
            # accounts for the +1 requests handed to workers < extra,
            # so ids cover exactly 0..requests-1 with no overlap
            base = wid * per_worker + min(wid, extra)
            for j in range(n):
                if self.stop_fn():
                    return
                self._one(base + j, wait=True)

        threads = [
            threading.Thread(
                target=worker, args=(w, per_worker + (1 if w < extra else 0))
            )
            for w in range(self.concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _run_open(self) -> None:
        # the arrival schedule is drawn up front from the seed —
        # deterministic offered load, independent of service times
        rng = random.Random(self.seed)
        gaps = [rng.expovariate(self.rate) for _ in range(self.requests)]
        t_next = time.perf_counter()
        for i, gap in enumerate(gaps):
            t_next += gap
            delay = t_next - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if self.stop_fn():
                return
            # latency clock starts at the SCHEDULED arrival, not the
            # (possibly late) submit — see _one
            fut = self._one(i, wait=False, t0=t_next)
            if fut is not None:
                self._inflight.append(fut)

    def run(self) -> Dict[str, Any]:
        """Offer the configured load; returns raw counters (the caller
        builds the verdict after the batcher drains)."""
        t0 = time.perf_counter()
        if self.mode == "closed":
            self._run_closed()
        else:
            self._run_open()
        # answered-before-verdict: wait for whatever is still in flight
        # (the batcher keeps consuming; on drain it answers everything)
        for fut in self._inflight:
            try:
                fut.result(timeout=60.0)
            except Exception:
                pass
        wall_s = time.perf_counter() - t0
        # settle: every accepted Future's _done callback must have
        # landed, or the last request's latency/shed increment could be
        # missing from the snapshot
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with self._lock:
                if self._processed >= self._accepted:
                    break
            time.sleep(0.001)
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": len(self.latencies_ms),
                "shed": self.shed,
                "failed": self.failed,
                "wall_s": wall_s,
                "latencies_ms": sorted(self.latencies_ms),
            }


def slo_verdict(
    raw: Dict[str, Any],
    batcher_stats: Dict[str, Any],
    *,
    mode: str,
    rate: float,
    seed: int,
    provenance: Optional[Dict[str, Any]] = None,
    warmup_s: Optional[Dict[str, float]] = None,
    preempted: bool = False,
    drained_clean: bool = True,
) -> Dict[str, Any]:
    """Assemble the deterministic strict-JSON SLO verdict."""
    lats = raw["latencies_ms"]
    wall = max(raw["wall_s"], 1e-9)
    submitted = max(raw["submitted"], 1)
    verdict = {
        "serve_verdict": VERDICT_SCHEMA_VERSION,
        "mode": mode,
        "rate_rps": rate if mode == "open" else None,
        "seed": seed,
        "requests_submitted": raw["submitted"],
        "requests_completed": raw["completed"],
        "requests_shed": raw["shed"],
        "requests_failed": raw.get("failed", 0),
        "shed_rate": round(raw["shed"] / submitted, 6),
        "p50_ms": round(percentile(lats, 50.0), 3) if lats else None,
        "p95_ms": round(percentile(lats, 95.0), 3) if lats else None,
        "p99_ms": round(percentile(lats, 99.0), 3) if lats else None,
        "throughput_rps": round(raw["completed"] / wall, 3),
        "wall_s": round(wall, 3),
        "mean_batch_occupancy": batcher_stats.get("mean_occupancy"),
        "batches": batcher_stats.get("batches"),
        "max_queue_depth_seen": batcher_stats.get("max_queue_depth_seen"),
        "max_queue": batcher_stats.get("max_queue"),
        # bucket keys as strings: the verdict must survive a JSON
        # round trip unchanged (int dict keys would silently stringify)
        "warmup_compile_s": (
            {str(k): v for k, v in warmup_s.items()} if warmup_s else None
        ),
        "preempted": bool(preempted),
        "drained_clean": bool(drained_clean),
        "provenance": provenance or {},
    }
    from bdbnn_tpu.obs.events import jsonsafe

    return jsonsafe(verdict)


def run_serve_bench(cfg) -> Dict[str, Any]:
    """End-to-end serving benchmark over an export artifact (the
    ``serve-bench`` CLI body). ``cfg`` is a
    :class:`bdbnn_tpu.configs.config.ServeBenchConfig`. Returns
    ``{verdict, run_dir}``; the verdict is also written to
    ``<run_dir>/verdict.json`` (and ``cfg.out`` when set) and emitted as
    the final ``serve`` event."""
    from bdbnn_tpu.train.resilience import PreemptionHandler

    cfg = cfg.validate()
    # the SIGTERM latch covers the WHOLE bench — a preemption during
    # the multi-second AOT warmup must drain-and-report, not die with
    # the default disposition
    with PreemptionHandler() as handler:
        return _serve_bench_body(cfg, handler)


def _serve_bench_body(cfg, handler) -> Dict[str, Any]:
    import datetime

    import numpy as np

    from bdbnn_tpu.obs.events import EventWriter
    from bdbnn_tpu.obs.manifest import write_manifest
    from bdbnn_tpu.serve.engine import InferenceEngine

    engine = InferenceEngine(cfg.artifact, buckets=cfg.buckets)
    warmup_s = dict(engine.compile_seconds)

    stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    run_dir = os.path.join(cfg.log_path, stamp)
    os.makedirs(run_dir, exist_ok=True)
    prov = engine.artifact.get("provenance", {})
    recipe = prov.get("recipe") or {}
    manifest = write_manifest(
        run_dir,
        {
            "mode": "serve-bench",
            "artifact": os.path.abspath(cfg.artifact),
            # recipe fields flow through so `compare` aligns serving
            # runs on the same export provenance — None entries dropped
            # and spread FIRST, so a bare-checkpoint export's empty
            # recipe can never null out the arch/dataset the engine
            # positively knows
            **{k: v for k, v in recipe.items() if v is not None},
            "arch": engine.arch,
            "dataset": engine.dataset,
            "export_config_hash": prov.get("config_hash"),
            "buckets": list(cfg.buckets),
            "queue_depth": cfg.queue_depth,
            "max_delay_ms": cfg.max_delay_ms,
            "load_mode": cfg.mode,
            "rate": cfg.rate,
            "requests": cfg.requests,
            "concurrency": cfg.concurrency,
            "seed": cfg.seed,
        },
    )
    events = EventWriter(
        run_dir, max_bytes=int(cfg.events_max_mb * 2**20)
    )
    events.emit(
        "serve",
        phase="start",
        artifact=os.path.abspath(cfg.artifact),
        arch=engine.arch,
        buckets=list(cfg.buckets),
        warmup_compile_s=warmup_s,
        mode=cfg.mode,
        # closed mode offers no Poisson load — null, like the verdict
        rate_rps=cfg.rate if cfg.mode == "open" else None,
        requests=cfg.requests,
        queue_depth=cfg.queue_depth,
        max_delay_ms=cfg.max_delay_ms,
    )

    # rolling p99 over a sliding latency window for the live `serve`
    # stats events `watch` renders
    window: List[float] = []
    win_lock = threading.Lock()
    batch_counter = [0]
    emit_every = max(cfg.requests // (20 * max(engine.buckets[-1], 1)), 1)

    def on_batch(stats: Dict[str, Any]) -> None:
        # per-batch latency proxy: oldest request's queue wait + run
        with win_lock:
            window.append(stats["oldest_wait_ms"] + stats["run_ms"])
            del window[:-256]
            rolling = sorted(window)
            batch_counter[0] += 1
            n = batch_counter[0]
        if n % emit_every == 0:
            events.emit(
                "serve",
                phase="stats",
                batch_size=stats["batch_size"],
                occupancy=stats["occupancy"],
                queue_depth=stats["queue_depth"],
                rolling_p99_ms=round(percentile(rolling, 99.0), 3),
                completed=stats["completed"],
                shed=stats["shed"],
            )

    def runner(samples: List[np.ndarray]):
        return engine.predict_logits(np.stack(samples))

    batcher = MicroBatcher(
        runner,
        max_batch=engine.buckets[-1],
        max_queue=cfg.queue_depth,
        max_delay_ms=cfg.max_delay_ms,
        on_batch=on_batch,
    )

    # a small pregenerated pool of deterministic samples, cycled — the
    # offered traffic is seed-reproducible without allocating thousands
    # of images
    rng = np.random.default_rng(cfg.seed)
    pool = rng.standard_normal(
        (32, engine.image_size, engine.image_size, 3)
    ).astype(np.float32)
    sample_fn = lambda i: pool[i % len(pool)]

    gen = LoadGenerator(
        batcher.submit,
        sample_fn,
        mode=cfg.mode,
        requests=cfg.requests,
        rate=cfg.rate,
        concurrency=cfg.concurrency,
        seed=cfg.seed,
        stop_fn=lambda: handler.preempted,
    )
    raw = gen.run()
    preempted = handler.preempted
    # graceful drain: accepted requests are all answered before the
    # verdict is written — on SIGTERM this is the whole point
    drained_clean = batcher.drain(timeout=120.0)

    verdict = slo_verdict(
        raw,
        batcher.stats(),
        mode=cfg.mode,
        rate=cfg.rate,
        seed=cfg.seed,
        provenance={
            "artifact": os.path.abspath(cfg.artifact),
            "arch": engine.arch,
            "dataset": engine.dataset,
            "config_hash": prov.get("config_hash"),
            "recipe": recipe,
            "serve_config_hash": manifest.get("config_hash"),
        },
        warmup_s=warmup_s,
        preempted=preempted,
        drained_clean=drained_clean,
    )
    events.emit("serve", phase="verdict", **verdict)
    events.close()
    for out in (os.path.join(run_dir, VERDICT_NAME), cfg.out or None):
        if out:
            tmp = out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(verdict, f, indent=2, sort_keys=True)
            os.replace(tmp, out)
    return {"verdict": verdict, "run_dir": run_dir}


__all__ = [
    "VERDICT_NAME",
    "VERDICT_SCHEMA_VERSION",
    "LoadGenerator",
    "percentile",
    "run_serve_bench",
    "slo_verdict",
]
