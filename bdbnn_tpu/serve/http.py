"""Network front end: stdlib asyncio HTTP/1.1 over the serving stack.

Until now the serving stack spoke in-process Python calls — no sockets,
no tenants, no request priorities. This module puts a real (if
deliberately minimal) HTTP/1.1 server in front of the
``MicroBatcher`` → AOT engine path, keeping the zero-dependency stance:
``asyncio.start_server`` + a hand-rolled request parser, no aiohttp.

Endpoints:

==================  ====================================================
``POST /v1/predict``  one inference request. Headers: ``x-priority``
                      (int class, 0 = most important; out-of-range →
                      400) and ``x-tenant`` (quota key; default
                      ``anon``). Body: the payload ``decode`` accepts
                      (the CLI wires raw float32 image bytes or a JSON
                      list). 200 + logits JSON, or an explicit shed:
                      **429** ``over_quota`` (THIS tenant's bucket is
                      empty — its fault, retry later) vs **503**
                      ``draining`` / ``queue full`` (the SERVER is
                      going away or overloaded — retry elsewhere);
                      both carry ``retry-after``.
``GET /healthz``      liveness: 200 as soon as the process serves
                      sockets (load balancer: don't kill me).
``GET /readyz``       readiness: 200 only when the engine's AOT warmup
                      has finished AND the drain latch is clear
                      (load balancer: you may route to me). SIGTERM →
                      flips to 503 ``draining`` BEFORE in-flight
                      requests finish — new traffic moves away while
                      accepted requests are answered.
``GET /statsz``       live stats JSON: per-priority queue occupancy
                      (one source of truth: ``MicroBatcher.stats()``),
                      per-tenant admission counters, in-flight count,
                      readiness state, and — when tracing is on — the
                      live request-path stage histograms
                      (obs/rtrace.py: per-stage p99, queue share).
==================  ====================================================

**Drain contract (the PR 5 semantics extended over sockets).** SIGTERM
latches: ``/readyz`` goes 503 immediately, ``admit()`` starts
returning ``draining`` (503), and every request ALREADY accepted is
answered before the server closes — ``drain()`` waits for the
in-flight count to reach zero, then drains the batcher (whose queues
empty into answered futures, never dropped ones), then closes the
listener. An accepted request is never dropped; the verdict is written
after the last response.

The engine is injected as the batcher's runner callable, so this
module (and its socket tests) never needs a JAX backend.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from bdbnn_tpu.obs.capacity import CapacityPlane
from bdbnn_tpu.obs.events import jsonsafe
from bdbnn_tpu.obs.rtrace import (
    STAGE_HEADER,
    TRACE_HEADER,
    encode_stage_header,
    parse_trace_context,
    pop_future_answered_by,
)
from bdbnn_tpu.serve.admission import (
    ADMIT,
    DEFAULT_TENANT,
    DRAINING,
    OVER_QUOTA,
    AdmissionController,
)
from bdbnn_tpu.serve.batching import LoadShedError, MicroBatcher
from bdbnn_tpu.serve.pool import DEFAULT_MODEL

_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}

PREDICT_PATH = "/v1/predict"


def _default_decode(body: bytes, content_type: str) -> Any:
    return json.loads(body) if body else None


def _shed_key(reason: str) -> str:
    """Ledger column for a LoadShedError reason — the taxonomy every
    shed site (submit-time and future-delivered alike) buckets through,
    so a total pool outage ("no healthy replica") is never misfiled as
    queue-full backpressure and triage reads the right layer."""
    if reason == "draining":
        return "shed_draining"
    if reason == "no healthy replica":
        return "shed_unavailable"
    return "shed_queue_full"


def _default_encode(result: Any) -> Any:
    return jsonsafe(result)


class HttpFrontEnd:
    """The asyncio server, run on its own thread so synchronous callers
    (CLI main loop, tests, the thread-based load generator) can drive
    it with plain calls: ``start()`` → (host, port), ``drain()``,
    ``stats()``, ``accounting()``.

    ``ready_fn`` reports the engine's AOT warmup state (``/readyz``
    gates on it); ``decode``/``encode`` translate HTTP bodies to/from
    batcher payloads, so the server itself stays numpy-free.
    """

    def __init__(
        self,
        batcher: MicroBatcher,
        admission: AdmissionController,
        *,
        ready_fn: Callable[[], bool] = lambda: True,
        decode: Callable[[bytes, str], Any] = _default_decode,
        encode: Callable[[Any], Any] = _default_encode,
        host: str = "127.0.0.1",
        port: int = 0,
        max_body_bytes: int = 16 * 2**20,
        default_priority: Optional[int] = None,
        retry_after_s: int = 1,
        admin: Optional[Any] = None,
        model_router: Optional[Callable[[str], str]] = None,
        tracer: Optional[Any] = None,
        canary: Optional[Any] = None,
        server_id: Optional[str] = None,
        capacity: Optional[CapacityPlane] = None,
    ):
        self.batcher = batcher
        self.admission = admission
        # capacity observatory (obs/capacity.py): every request feeds
        # the per-(model, tenant, priority) demand ledger and — on its
        # terminal disposition — the SLO budget plane. Always present
        # so the feed sites never branch; the orchestration passes a
        # plane configured with the run's objectives, a bare default
        # otherwise (demand + utilization still measured, no
        # detectors armed).
        self.capacity = (
            capacity if capacity is not None
            else CapacityPlane(priorities=batcher.priorities)
        )
        # canary monitor (serve/canary.py): when wired, every served
        # request's (priority, latency, answered-by version) feeds the
        # per-cohort latency windows the rollout verdict judges. The
        # monitor ignores feeds outside an armed episode, so this
        # costs one attribute read per request when no rollout runs.
        self.canary = canary
        # request-lifecycle tracer (obs/rtrace.py): when wired, every
        # served request gets read/admit/queue/coalesce/dispatch/
        # compute/respond spans, /statsz exposes the live stage
        # histograms and the verdict carries the attribution block.
        # None = zero per-request cost beyond one attribute read.
        self.tracer = tracer
        self.ready_fn = ready_fn
        self.decode = decode
        self.encode = encode
        self.host = host
        self.port = int(port)
        self.max_body_bytes = int(max_body_bytes)
        # an absent x-priority header lands in the LOWEST class: best
        # effort by default, priority is something a client asks for
        self.default_priority = (
            batcher.priorities - 1
            if default_priority is None
            else int(default_priority)
        )
        self.retry_after_s = int(retry_after_s)
        # the replica-pool operator surface (serve/pool.py:PoolAdmin):
        # GET /admin/replicas, GET/POST /admin/swap. None = the admin
        # routes 404 (single-engine serving has no pool to administer).
        self.admin = admin
        # multi-model residency (serve/pool.py ResidentModelCache):
        # maps an ``x-model`` header value to a model key the batcher
        # payloads carry — requests route to co-resident packed
        # versions without a reload. Raises KeyError on an unknown or
        # unverifiable model -> 404, ledgered as `rejected` (the
        # client named something unservable; neither completed nor
        # shed). None = the header is rejected outright: a server not
        # configured for multi-model must not silently ignore a
        # routing request and answer from the wrong model.
        self.model_router = model_router
        # fleet identity (serve/fleet.py): when a router fronts several
        # hosts, each host advertises a stable id on /healthz//statsz
        # and stamps its 200 responses with ``served_by``, so the
        # router's host table and the client's answered-by accounting
        # can be cross-checked against what the HOST says it is. None =
        # single-host serving, responses unchanged.
        self.server_id = server_id
        self._completed_by_model: Dict[str, int] = {}
        self._draining = threading.Event()
        # in-flight = /v1/predict handlers between request-parsed and
        # response-written; open connections additionally tracked in
        # _conns so drain can give still-reading (e.g. slow-dribble)
        # clients a grace to finish and collect their 503
        self._inflight = 0
        self._conns = 0
        self._inflight_cv = threading.Condition()
        # accounting (mutated only on the loop thread; snapshotted from
        # others — int/list appends are atomic enough under the GIL)
        self._lat_by_priority: List[List[float]] = [
            [] for _ in range(batcher.priorities)
        ]
        # observed /v1/predict arrival stamps (perf_counter): the
        # MEASURED offered-rate figure serve-mode verdicts report —
        # derived from what actually arrived, never from a config knob
        self._arrival_stamps: List[float] = []
        self._counts_by_priority: List[Dict[str, int]] = [
            {"submitted": 0, "completed": 0, "failed": 0,
             "rejected": 0, "shed_draining": 0, "shed_over_quota": 0,
             "shed_queue_full": 0, "shed_unavailable": 0}
            for _ in range(batcher.priorities)
        ]
        self._requests_seen = 0
        self._t_started: Optional[float] = None
        self._t_drained: Optional[float] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------

    def start(self, timeout: float = 10.0) -> Tuple[str, int]:
        """Bind + serve on a dedicated event-loop thread; returns the
        bound (host, port) — port 0 resolves to the kernel's pick."""
        self._thread = threading.Thread(
            target=self._serve_thread, name="http-front-end", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout):
            raise RuntimeError("HTTP front end failed to start in time")
        if self._start_error is not None:
            raise RuntimeError(
                f"HTTP front end failed to bind: {self._start_error}"
            )
        return self.host, self.port

    def _serve_thread(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def _main():
            try:
                self._server = await asyncio.start_server(
                    self._client, self.host, self.port
                )
            except OSError as e:
                self._start_error = e
                self._started.set()
                return
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()
            async with self._server:
                await self._server.serve_forever()

        try:
            loop.run_until_complete(_main())
        except asyncio.CancelledError:
            pass
        finally:
            # let pending handler callbacks (already-scheduled 503s)
            # settle before tearing the loop down
            try:
                pending = [
                    t for t in asyncio.all_tasks(loop)
                    if t is not asyncio.current_task(loop)
                ]
                for t in pending:
                    t.cancel()
                if pending:
                    loop.run_until_complete(
                        asyncio.gather(*pending, return_exceptions=True)
                    )
            except Exception:
                pass
            loop.close()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout: float = 120.0) -> bool:
        """The SIGTERM path, callable from any thread. Latches the
        drain flag (readyz flips 503, new requests shed), waits for
        every ACCEPTED request's response to be written, drains the
        batcher, then closes the listener. Returns True when everything
        wound down inside ``timeout``. Idempotent."""
        already = self._draining.is_set()
        self._draining.set()
        self.admission.drain()
        deadline = time.monotonic() + timeout
        # 1. every accepted request answered (the socket-level extension
        #    of the batcher's no-unresolved-Future guarantee)
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._inflight_cv.wait(remaining)
            clean = self._inflight == 0
        # 2. the batcher's own drain (queues empty by now: nothing new
        #    could enter after the latch)
        clean = self.batcher.drain(
            timeout=max(deadline - time.monotonic(), 0.1)
        ) and clean
        if self._t_drained is None:
            self._t_drained = time.perf_counter()
        # 2b. grace for connections still mid-request — a slow client
        #     dribbling its body is parked in readexactly and not yet
        #     in-flight; give it a moment to finish the read and
        #     collect its explicit 503 instead of a torn connection
        #     (handlers close their connection at the next boundary
        #     once the latch is set, so this converges fast)
        grace_deadline = min(time.monotonic() + 2.0, deadline)
        with self._inflight_cv:
            while self._conns > 0:
                remaining = grace_deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._inflight_cv.wait(remaining)
        # 3. stop serving sockets and wind the loop down
        if not already and self._loop is not None:
            loop = self._loop

            def _shutdown():
                if self._server is not None:
                    self._server.close()
                # cancel serve_forever -> run_until_complete returns
                for task in asyncio.all_tasks(loop):
                    task.cancel()

            try:
                loop.call_soon_threadsafe(_shutdown)
            except RuntimeError:
                pass  # loop already closed
        if self._thread is not None:
            self._thread.join(max(deadline - time.monotonic(), 0.1))
            clean = clean and not self._thread.is_alive()
        return clean

    # -- request plumbing ----------------------------------------------

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line:
            return None
        # the read-stage clock starts when the request LINE lands, not
        # when the connection went readable: an idle keep-alive
        # connection parked in readline must not charge its idle wait
        # to the next request's read span
        t_recv = time.perf_counter()
        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3:
            raise ValueError(f"malformed request line: {line!r}")
        method, path, _version = parts
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1").partition(":")
            name = name.strip().lower()
            value = value.strip()
            if name == TRACE_HEADER and name in headers:
                # a DUPLICATED trace context is ambiguous (which hop
                # minted it?) — poison it so the adopt path falls
                # back to a fresh local trace instead of guessing
                value = ""
            headers[name] = value
        n = int(headers.get("content-length", 0) or 0)
        if n > self.max_body_bytes:
            return method, path, headers, None, t_recv  # signals 413
        body = await reader.readexactly(n) if n else b""
        return method, path, headers, body, t_recv

    def _respond(
        self, writer, status: int, obj: Any, *,
        retry_after: bool = False, close: bool = False,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(jsonsafe(obj)).encode()
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
            f"content-type: application/json\r\n"
            f"content-length: {len(body)}\r\n"
        )
        if retry_after:
            head += f"retry-after: {self.retry_after_s}\r\n"
        for name, value in (extra_headers or {}).items():
            head += f"{name}: {value}\r\n"
        if close:
            head += "connection: close\r\n"
        writer.write(head.encode("latin-1") + b"\r\n" + body)

    async def _client(self, reader, writer) -> None:
        with self._inflight_cv:
            self._conns += 1
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except (
                    asyncio.IncompleteReadError, ValueError,
                    ConnectionError,
                ):
                    break
                if req is None:
                    break
                method, path, headers, body, t_recv = req
                close = (
                    headers.get("connection", "").lower() == "close"
                )
                if body is None:
                    self._respond(
                        writer, 413, {"error": "payload too large"},
                        close=True,
                    )
                    break
                await self._route(
                    writer, method, path, headers, body, t_recv
                )
                await writer.drain()
                if close or self._draining.is_set():
                    # draining: close at the request boundary so the
                    # drain grace converges instead of waiting out
                    # idle keep-alive connections
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away mid-exchange: nothing to answer
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass
            with self._inflight_cv:
                self._conns -= 1
                self._inflight_cv.notify_all()

    async def _route(
        self, writer, method, path, headers, body, t_recv=None
    ) -> None:
        if method == "GET" and path == "/healthz":
            self._respond(writer, 200, {
                "status": "ok",
                "ready": bool(self.ready_fn()) and not self.draining,
                "server_id": self.server_id,
            })
        elif method == "GET" and path == "/readyz":
            if self.draining:
                self._respond(
                    writer, 503, {"state": "draining"}, retry_after=True
                )
            elif not self.ready_fn():
                self._respond(
                    writer, 503, {"state": "warming"}, retry_after=True
                )
            else:
                self._respond(writer, 200, {"state": "ready"})
        elif method == "GET" and path == "/statsz":
            self._respond(writer, 200, self.stats())
        elif path in ("/admin/replicas", "/admin/swap"):
            await self._admin(writer, method, path, body)
        elif method == "POST" and path == PREDICT_PATH:
            await self._predict(writer, headers, body, t_recv)
        else:
            self._respond(
                writer, 404, {"error": f"no route {method} {path}"}
            )

    async def _admin(self, writer, method, path, body) -> None:
        """The replica-pool operator routes. ``GET /admin/replicas`` =
        the live per-replica table (device, version, health, queue
        depth, completed); ``GET /admin/swap`` = the swap state
        machine's status; ``POST /admin/swap`` with ``{"version": N}``
        (registry, digest-verified) or ``{"artifact": "/dir"}`` starts
        a blue/green rollout and returns 202 while traffic keeps
        flowing — the zero-downtime contract is the pool's, the route
        only triggers it."""
        if self.admin is None:
            self._respond(writer, 404, {
                "error": "no replica pool behind this server "
                "(started without --replicas/--registry)",
            })
            return
        if method == "GET" and path == "/admin/replicas":
            self._respond(writer, 200, self.admin.replicas())
        elif method == "GET" and path == "/admin/swap":
            self._respond(writer, 200, self.admin.swap_status())
        elif method == "POST" and path == "/admin/swap":
            try:
                spec = json.loads(body) if body else {}
            except Exception as e:
                self._respond(
                    writer, 400, {"error": f"undecodable body: {e}"}
                )
                return
            if not isinstance(spec, dict):
                self._respond(
                    writer, 400,
                    {"error": "swap body must be a JSON object"},
                )
                return
            # off the event loop: start_swap digest-verifies the target
            # (hashes the weights payload) before spawning the rollout
            # thread — run inline it would stall every in-flight
            # connection for the duration, spiking p99 exactly at the
            # "zero-downtime" trigger
            status, payload = await asyncio.get_event_loop(
            ).run_in_executor(None, self.admin.start_swap, spec)
            self._respond(writer, status, payload)
        else:
            self._respond(
                writer, 404, {"error": f"no route {method} {path}"}
            )

    async def _predict(self, writer, headers, body, t_recv=None) -> None:
        t0 = time.perf_counter()
        if self._t_started is None:
            # the verdict's wall clock starts at the FIRST request, not
            # at socket bind: AOT warmup (seconds on CPU, minutes on a
            # real chip) and pre-load idle must not dilute
            # throughput_rps, or compare would flag compile-time
            # variance as a serving regression
            self._t_started = t0
        self._requests_seen += 1
        tenant = headers.get("x-tenant") or DEFAULT_TENANT
        raw_p = headers.get("x-priority")
        if raw_p is None:
            priority = self.default_priority
        else:
            try:
                priority = int(raw_p)
            except ValueError:
                priority = -1
            if not 0 <= priority < self.batcher.priorities:
                self._respond(writer, 400, {
                    "error": "bad x-priority",
                    "want": f"int in [0, {self.batcher.priorities})",
                    "got": raw_p,
                })
                return
        trace = None
        if self.tracer is not None:
            # the span timeline starts at request receipt (the request
            # line's arrival when known); the first stamp charges the
            # socket read + parse that already happened
            trace = self.tracer.begin(
                priority, tenant,
                t_start=t_recv if t_recv is not None else t0,
            )
            # adopt an inbound fleet trace context (x-rtrace from the
            # FleetRouter) so the local waterfall continues the SAME
            # trace; the hardened parser maps ANY malformed header —
            # garbage, oversized, junk from a non-fleet client — to
            # None, i.e. a fresh local trace, never a 500
            trace.ctx = parse_trace_context(headers.get(TRACE_HEADER))
            trace.stamp("read")
        # in-flight covers the WHOLE predict — admission through the
        # written response — so drain's inflight-zero wait cannot race
        # a request between submit and accounting
        with self._inflight_cv:
            self._inflight += 1
        try:
            await self._predict_body(
                writer, headers, body, t0, tenant, priority, trace
            )
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def _abort_trace(self, trace) -> None:
        """A request that ends without a served response (shed /
        rejected / failed) leaves the stage statistics untouched — a
        503 written in microseconds must never read as a fast serve."""
        if trace is not None and self.tracer is not None:
            self.tracer.abort(trace)

    async def _predict_body(
        self, writer, headers, body, t0, tenant: str, priority: int,
        trace=None,
    ) -> None:
        counts = self._counts_by_priority[priority]
        counts["submitted"] += 1
        self._arrival_stamps.append(t0)
        # demand-ledger key: the model the CLIENT asked for (resolved
        # or not — a request 404ing on an unknown model is still
        # demand for it), so offered and its disposition always land
        # under the same key and the ledger identity holds per key
        ledger_model = headers.get("x-model") or DEFAULT_MODEL
        cap = self.capacity
        cap.ledger.offered(ledger_model, tenant, priority)
        decision = self.admission.admit(tenant, trace=trace)
        if decision == DRAINING:
            self._abort_trace(trace)
            counts["shed_draining"] += 1
            cap.ledger.shed(ledger_model, tenant, priority)
            cap.budget.feed(priority, shed=True)
            self._respond(
                writer, 503,
                {"error": "draining", "tenant": tenant},
                retry_after=True,
            )
            return
        if decision == OVER_QUOTA:
            self._abort_trace(trace)
            counts["shed_over_quota"] += 1
            # the tenant's own budget ran out — `rejected` in the
            # demand ledger (with 400s/404s), and NOT fed to the shed
            # SLO: a 429 is the quota working, not capacity failing
            cap.ledger.rejected(ledger_model, tenant, priority)
            self._respond(
                writer, 429,
                {"error": "over_quota", "tenant": tenant},
                retry_after=True,
            )
            return
        assert decision == ADMIT
        raw_model = headers.get("x-model")
        model_key = None
        if raw_model is not None and self.model_router is None:
            # no router configured: answering from the (only) resident
            # model while the client asked for a specific one would be
            # silently wrong — explicit 404, ledgered like a bad body
            self._abort_trace(trace)
            counts["rejected"] += 1
            self.admission.record_rejected(tenant)
            cap.ledger.rejected(ledger_model, tenant, priority)
            self._respond(writer, 404, {
                "error": "multi-model routing disabled "
                "(start serve-http with --resident-models >= 2)",
                "model": raw_model,
            })
            return
        if self.model_router is not None:
            try:
                # off-loop: the first request naming an unseen model
                # pays a full registry digest walk (sha256 over
                # weights.npz) inside the router — on the event loop
                # that would stall every other connection for the
                # duration (the admin swap handler makes the same
                # move for the same reason); memoized hits return in
                # microseconds either way
                model_key = await asyncio.get_running_loop(
                ).run_in_executor(None, self.model_router, raw_model)
            except KeyError as e:
                self._abort_trace(trace)
                counts["rejected"] += 1
                self.admission.record_rejected(tenant)
                cap.ledger.rejected(ledger_model, tenant, priority)
                self._respond(writer, 404, {
                    "error": f"unknown model: {e.args[0] if e.args else raw_model}",
                    "model": raw_model,
                })
                return
        try:
            payload = self.decode(
                body, headers.get("content-type", "")
            )
        except Exception as e:
            # a malformed body is neither completed nor shed — its own
            # ledger column, so `completed + shed + failed + rejected
            # == submitted` survives bad clients
            self._abort_trace(trace)
            counts["rejected"] += 1
            self.admission.record_rejected(tenant)
            cap.ledger.rejected(ledger_model, tenant, priority)
            self._respond(
                writer, 400, {"error": f"undecodable body: {e}"}
            )
            return
        if self.model_router is not None:
            # the batcher payload carries the routing decision; the
            # pool runner groups each coalesced batch by model key
            payload = (model_key, payload)
        try:
            fut = self.batcher.submit(payload, priority=priority, trace=trace)
        except LoadShedError as e:
            self._abort_trace(trace)
            self.admission.record_shed(tenant)
            counts[_shed_key(e.reason)] += 1
            cap.ledger.shed(ledger_model, tenant, priority)
            cap.budget.feed(priority, shed=True)
            self._respond(
                writer, 503,
                {"error": e.reason, "tenant": tenant},
                retry_after=True,
            )
            return
        try:
            result = await asyncio.wrap_future(fut)
        except LoadShedError as e:
            # a shed can land on the FUTURE too: the pooled runner
            # raises inside the batcher worker when every replica
            # queue is full (or none is healthy), and a drain latched
            # between submit and execution is the belt-and-braces
            # case — either way an explicit shed, never a dropped
            # connection, ledgered under its real reason
            self._abort_trace(trace)
            self.admission.record_shed(tenant)
            counts[_shed_key(e.reason)] += 1
            # a future-delivered shed never really entered service —
            # the ledger's entry disposition is `shed`, same as a
            # submit-time shed (admitted is bumped only at terminal
            # served/failed, so identity never double-counts this)
            cap.ledger.shed(ledger_model, tenant, priority)
            cap.budget.feed(priority, shed=True)
            self._respond(
                writer, 503,
                {"error": e.reason, "tenant": tenant},
                retry_after=True,
            )
            return
        except Exception as e:
            self._abort_trace(trace)
            self.admission.record_failed(tenant)
            counts["failed"] += 1
            cap.ledger.admitted(ledger_model, tenant, priority)
            cap.ledger.failed(ledger_model, tenant, priority)
            self._respond(
                writer, 500, {"error": f"inference failed: {e}"}
            )
            return
        lat_ms = (time.perf_counter() - t0) * 1000.0
        self._lat_by_priority[priority].append(lat_ms)
        counts["completed"] += 1
        self.admission.record_completed(tenant)
        cap.ledger.admitted(ledger_model, tenant, priority)
        cap.ledger.completed(ledger_model, tenant, priority)
        cap.budget.feed(priority, latency_ms=lat_ms)
        if self.canary is not None:
            # cohort truth is who ANSWERED: the version label rides
            # the request future (obs/rtrace.py), so a canary-assigned
            # batch that fell back to the incumbent feeds the
            # incumbent's window
            self.canary.record_served(
                priority, lat_ms, pop_future_answered_by(fut)
            )
        if self.model_router is not None:
            # keyed by pool.DEFAULT_MODEL so resident_block can merge
            # this ledger into the cache-stats rows it keys the same
            key = model_key or DEFAULT_MODEL
            self._completed_by_model[key] = (
                self._completed_by_model.get(key, 0) + 1
            )
        payload_out = {
            "result": self.encode(result),
            "priority": priority,
            "tenant": tenant,
            "model": model_key,
            "latency_ms": round(lat_ms, 3),
        }
        if self.server_id is not None:
            # fleet cross-check: WHO answered rides the response, so
            # the router's per-host completed ledger can be audited
            # against the hosts' own claims
            payload_out["served_by"] = self.server_id
        extra_headers = None
        if trace is not None and trace.ctx is not None:
            # fleet-traced request: return the server-side stage
            # decomposition in the response header the router stitches.
            # The self-reported span ends HERE (at header build) — the
            # final socket write is on the far side of the bytes, so
            # the router's `network` stage absorbs it by construction;
            # the pre-write gap since the last stamp (future wakeup +
            # encode) is charged to `respond` so the header's stage sum
            # equals its total exactly
            total_ms = (time.perf_counter() - trace.t0) * 1000.0
            stages = dict(trace.stages)
            pre_write = total_ms - sum(stages.values())
            if pre_write > 0:
                stages["respond"] = (
                    stages.get("respond", 0.0) + pre_write
                )
            extra_headers = {
                STAGE_HEADER: encode_stage_header(
                    trace.ctx["id"], total_ms, stages
                ),
            }
        self._respond(
            writer, 200, payload_out, extra_headers=extra_headers
        )
        await writer.drain()
        if trace is not None:
            # respond span: future wakeup + encode + socket write; the
            # waterfall is complete once the bytes are flushed
            trace.stamp("respond")
            self.tracer.finish(trace)

    # -- reporting -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The live snapshot ``/statsz`` serves and the periodic
        ``http`` stats events carry: readiness, in-flight, per-priority
        queue occupancy (straight from ``MicroBatcher.stats()`` — one
        source of truth) and per-tenant admission counters."""
        ready = bool(self.ready_fn()) and not self.draining
        with self._inflight_cv:
            inflight = self._inflight
        return jsonsafe({
            "ready": ready,
            "state": (
                "draining" if self.draining
                else "ready" if ready else "warming"
            ),
            "server_id": self.server_id,
            "inflight": inflight,
            "requests_seen": self._requests_seen,
            "batcher": self.batcher.stats(),
            "admission": self.admission.stats(),
            "completed_by_priority": [
                c["completed"] for c in self._counts_by_priority
            ],
            "shed_by_priority": [
                c["shed_draining"] + c["shed_over_quota"]
                + c["shed_queue_full"] + c["shed_unavailable"]
                for c in self._counts_by_priority
            ],
            # live request-path stage histograms (obs/rtrace.py): the
            # per-stage p99s /statsz clients and `watch` read to tell
            # queue-bound from device-bound WHILE it happens
            "rtrace": (
                self.tracer.stats() if self.tracer is not None else None
            ),
            # the live canary view while a rollout observes: per-
            # detector status, cohort served counts, drift so far
            "canary": (
                self.canary.live() if self.canary is not None else None
            ),
            # the live capacity block (obs/capacity.py): demand table,
            # utilization gauges, burn-rate peek + headroom estimate —
            # what the fleet router scrapes and merges
            "capacity": self.capacity.live_block(),
        })

    def accounting(self) -> Dict[str, Any]:
        """The post-drain request ledger the SLO verdict is built from:
        per-priority latency samples + disposition counts, wall time."""
        t_end = self._t_drained or time.perf_counter()
        wall_s = (
            t_end - self._t_started if self._t_started is not None else 0.0
        )
        stamps = self._arrival_stamps
        measured_rate = None
        if len(stamps) >= 2:
            span = stamps[-1] - stamps[0]
            if span > 0:
                # offered rate over the observed arrival span: (n-1)
                # inter-arrival gaps over their total duration — what
                # actually hit the socket, not what any config claims
                measured_rate = round((len(stamps) - 1) / span, 4)
        return {
            "wall_s": wall_s,
            "latencies_ms_by_priority": [
                sorted(l) for l in self._lat_by_priority
            ],
            "counts_by_priority": [
                dict(c) for c in self._counts_by_priority
            ],
            "completed_by_model": dict(self._completed_by_model),
            "requests_seen": self._requests_seen,
            "measured_rate_rps": measured_rate,
        }


# ---------------------------------------------------------------------------
# serve-http orchestration (the CLI body)
# ---------------------------------------------------------------------------


def run_serve_http(cfg, degrade=None) -> Dict[str, Any]:
    """End-to-end HTTP serving over an export artifact (the
    ``serve-http`` CLI body). ``cfg`` is a
    :class:`bdbnn_tpu.configs.config.ServeHttpConfig`. ``degrade``
    (tests and canary drills only — never a CLI flag) is the
    fault-injection spec threaded into the pool's runner factory
    (serve/pool.py ``_apply_degradation``): injectable per-version
    latency inflation, error rate, or logit perturbation, so the
    auto-rollback path can be proven against a genuinely degraded
    vN+1 through the REAL orchestration.

    Two modes sharing one server lifecycle:

    - ``cfg.scenario == ""`` — **serve**: bind, warm up, answer until
      SIGTERM/SIGINT latches, then drain and write the verdict from
      the server-side ledger.
    - ``cfg.scenario`` set — **bench**: same server, plus the
      scenario's socket load generator (serve/loadgen.py) driving real
      HTTP against it; the verdict additionally carries the client's
      own observation (the zero-dropped cross-check).

    Either way the run dir carries the same manifest/events/verdict
    artifacts as ``serve-bench``, so ``watch``/``summarize``/
    ``compare`` consume it unchanged."""
    from bdbnn_tpu.train.resilience import PreemptionHandler

    cfg = cfg.validate()
    # the SIGTERM latch covers the WHOLE run — a preemption during the
    # multi-second AOT warmup must drain-and-report, not die with the
    # default disposition
    with PreemptionHandler() as handler:
        return _serve_http_body(cfg, handler, degrade)


def _serve_http_body(cfg, handler, degrade=None) -> Dict[str, Any]:
    import datetime

    import numpy as np

    from bdbnn_tpu.obs.events import EventWriter
    from bdbnn_tpu.obs.manifest import write_manifest
    from bdbnn_tpu.serve.admission import parse_quota, parse_tenant_quotas
    from bdbnn_tpu.serve.engine import InferenceEngine
    from bdbnn_tpu.serve.loadgen import (
        HttpLoadGenerator,
        _ArtifactMeta,
        _pct,
        _pool_replicas_block,
        _serve_provenance,
        build_schedule,
        http_slo_verdict,
        write_verdict_files,
    )

    # registry resolution: with --registry, the ARTIFACT argument may
    # name a published version (v0003 / 3) — resolved with digest
    # verification instead of trusted as a path
    registry = None
    artifact_dir = cfg.artifact
    version_label = None
    if cfg.registry:
        from bdbnn_tpu.serve.registry import (
            ArtifactRegistry,
            looks_like_version,
            parse_version,
        )

        registry = ArtifactRegistry(cfg.registry)
        if looks_like_version(cfg.artifact or ""):
            version = parse_version(cfg.artifact)
            artifact_dir = registry.resolve(version)
            version_label = registry.label(version)
    if version_label is None:
        version_label = (
            os.path.basename(artifact_dir.rstrip(os.sep)) or "live"
        )

    # engine cold: the server comes up immediately with /healthz 200 +
    # /readyz 503 "warming", flipping ready only when the AOT buckets
    # are compiled — the load balancer sees the real warmup state. The
    # pooled path needs METADATA only (per-device replica engines are
    # built and warmed after the listener binds) — loading a full
    # weight copy here would pin a dead resident set on the default
    # device for the server's whole life.
    if cfg.pooled:
        engine: Any = _ArtifactMeta(artifact_dir, cfg.buckets)
    else:
        engine = InferenceEngine(
            artifact_dir, buckets=cfg.buckets, warm=False,
            packed=cfg.packed_weights, packed_impl=cfg.packed_impl,
        )

    stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    run_dir = os.path.join(cfg.log_path, stamp)
    os.makedirs(run_dir, exist_ok=True)
    prov = engine.artifact.get("provenance", {})
    recipe = prov.get("recipe") or {}
    manifest = write_manifest(
        run_dir,
        {
            "mode": "serve-http",
            "artifact": os.path.abspath(artifact_dir),
            # recipe fields flow through so `compare` aligns serving
            # runs on the same export provenance (None entries dropped,
            # spread FIRST — see serve-bench)
            **{k: v for k, v in recipe.items() if v is not None},
            "arch": engine.arch,
            "dataset": engine.dataset,
            "export_config_hash": prov.get("config_hash"),
            "buckets": list(cfg.buckets),
            "priorities": cfg.priorities,
            "queue_depth": cfg.queue_depth,
            "max_delay_ms": cfg.max_delay_ms,
            "scenario": cfg.scenario or None,
            "rate": cfg.rate,
            "requests": cfg.requests,
            "seed": cfg.seed,
            "default_quota": cfg.default_quota,
            "tenant_quotas": list(cfg.tenant_quotas),
            "replicas": cfg.replicas,
            "registry": os.path.abspath(cfg.registry) if cfg.registry
            else None,
            "swap_to": cfg.swap_to or None,
            "swap_at": cfg.swap_at or None,
            "canary_fraction": cfg.canary_fraction or None,
            "canary_replicas": (
                cfg.canary_replicas if cfg.canary_fraction else None
            ),
            "shadow_every": (
                cfg.shadow_every if cfg.canary_fraction else None
            ),
            "packed_weights": cfg.packed_weights,
            "packed_impl": cfg.packed_impl,
            "resident_models": cfg.resident_models,
            "models": list(cfg.models) or None,
            "rtrace": cfg.rtrace,
            "rtrace_sample_every": cfg.rtrace_sample_every,
        },
    )
    events = EventWriter(run_dir, max_bytes=int(cfg.events_max_mb * 2**20))

    # request-path tracing (obs/rtrace.py): full socket-to-socket
    # waterfalls — read/admit/queue/coalesce/dispatch/compute/respond —
    # with deterministic sampling + always-kept tail exemplars; sampled
    # waterfalls and periodic stage histograms flow as rtrace events
    tracer = None
    if cfg.rtrace:
        from bdbnn_tpu.obs.rtrace import RequestTracer

        tracer = RequestTracer(
            seed=cfg.seed,
            sample_every=cfg.rtrace_sample_every,
            tail_k=cfg.rtrace_tail_k,
            on_sample=lambda wf: events.emit(
                "rtrace", phase="request", **wf
            ),
        )

    default_rate, default_burst = parse_quota(cfg.default_quota)
    admission = AdmissionController(
        default_rate=default_rate,
        default_burst=default_burst,
        quotas=parse_tenant_quotas(cfg.tenant_quotas),
    )
    events.emit(
        "admission",
        phase="config",
        default_rate=default_rate,
        default_burst=default_burst,
        tenant_quotas={
            t: {"rate": r, "burst": b}
            for t, (r, b) in parse_tenant_quotas(cfg.tenant_quotas).items()
        },
    )

    # rolling p99 over a sliding latency window for the live `serve`
    # stats events `watch` renders (same shape as serve-bench, plus
    # the per-priority queue depths)
    window: List[float] = []
    win_lock = threading.Lock()
    batch_counter = [0]
    emit_every = max(
        cfg.requests // (20 * max(engine.buckets[-1], 1)), 1
    )

    def on_batch(stats: Dict[str, Any]) -> None:
        with win_lock:
            window.append(stats["oldest_wait_ms"] + stats["run_ms"])
            del window[:-256]
            rolling = sorted(window)
            batch_counter[0] += 1
            n = batch_counter[0]
        if n % emit_every == 0:
            events.emit(
                "serve",
                phase="stats",
                batch_size=stats["batch_size"],
                occupancy=stats["occupancy"],
                queue_depth=stats["queue_depth"],
                queue_depth_by_priority=stats["queue_depth_by_priority"],
                rolling_p99_ms=_pct(rolling, 99.0),
                completed=stats["completed"],
                shed=stats["shed"],
            )

    # one runner slot, two shapes: the classic single engine (blocking
    # call) or the replica pool's async dispatch (the runner returns
    # the batch Future; the batcher chains it and keeps collecting, so
    # N replicas execute concurrently). The pool is built AFTER the
    # listener binds — pool_ref carries it in.
    pool_ref: List[Any] = []

    if cfg.pooled:

        def runner(samples: List[np.ndarray]):
            if not pool_ref:
                # a predict raced the replica warmup (readyz is still
                # 503 "warming" — a well-behaved LB isn't routing yet):
                # explicit shed, never a hang. "no healthy replica" so
                # the ledger files it as shed_unavailable — a server
                # with zero load must not read as queue-full overload
                raise LoadShedError("no healthy replica")
            return pool_ref[0].submit(samples)

    else:

        def runner(samples: List[np.ndarray]):
            return engine.predict_logits(np.stack(samples))

    batcher = MicroBatcher(
        runner,
        max_batch=engine.buckets[-1],
        max_queue=cfg.queue_depth,
        max_delay_ms=cfg.max_delay_ms,
        on_batch=on_batch,
        priorities=cfg.priorities,
        # async backpressure on the pooled path (~1 executing + 1
        # queued batch per replica): overload sheds at the front's
        # per-priority queues, and priority inversion is bounded to
        # the batches already dispatched
        max_pending_batches=2 * cfg.replicas if cfg.pooled else None,
    )

    shape = (engine.image_size, engine.image_size, 3)
    nbytes = int(np.prod(shape)) * 4

    def decode(body: bytes, content_type: str):
        # raw float32 little-endian pixels (the loadgen's wire format),
        # or a JSON-encoded nested list for hand-rolled curl clients
        if content_type.startswith("application/octet-stream"):
            if len(body) != nbytes:
                raise ValueError(
                    f"want {nbytes} bytes of float32 {shape}, got "
                    f"{len(body)}"
                )
            return np.frombuffer(body, np.float32).reshape(shape).copy()
        arr = np.asarray(json.loads(body), np.float32)
        if arr.shape != shape:
            raise ValueError(f"want shape {shape}, got {arr.shape}")
        return arr

    def encode(logits: np.ndarray):
        return {
            "pred": int(np.argmax(logits)),
            "logits": [round(float(x), 4) for x in np.asarray(logits)],
        }

    ready_fn = (
        (lambda: bool(pool_ref)) if cfg.pooled
        else (lambda: engine.warmed)
    )
    # multi-model routing: x-model names a digest-verified registry
    # version; resolution (the sha256 chain walk) is memoized into
    # model_dirs, which the replica caches' loader also reads — one
    # verification per model per server life, never per request
    model_dirs: Dict[str, str] = {}
    model_router = None
    if cfg.resident_models > 1:
        from bdbnn_tpu.serve.registry import parse_version

        def model_router(header):
            if header is None:
                return None
            try:
                version = parse_version(header)
            except ValueError as e:
                raise KeyError(str(e))
            label = registry.label(version)
            # x-model naming the server's CURRENT default version
            # routes to the default resident engine — never a second
            # copy of the same weights in the cache. The default is
            # read from the live pool, not captured at startup: after
            # a blue/green swap the old default label must cache-route
            # to its own (old-version) engine, and the NEW version's
            # label must short-circuit — a startup capture would
            # silently answer old-label requests with new weights
            current_default = (
                pool_ref[0].version if pool_ref else version_label
            )
            if label == current_default:
                return None
            if label not in model_dirs:
                try:
                    model_dirs[label] = registry.resolve(version)
                except Exception as e:
                    # unknown version, torn dir, digest mismatch — all
                    # 404 to the client, none may reach an engine
                    raise KeyError(str(e))
            return label

        # the scenario's model mix resolves BEFORE the listener binds:
        # a well-formed but unpublished/torn version must fail here as
        # a startup error, not crash the eager warm loop after the
        # socket is bound and the run dir is open (config validation
        # can only check the NAME shape; only the registry can check
        # existence and digests). Resolution is memoized into
        # model_dirs, so the warm loop and request path reuse it.
        for label in cfg.models:
            try:
                model_router(label)
            except KeyError as e:
                raise ValueError(
                    f"--models entry {label!r} cannot be served: "
                    f"{e.args[0] if e.args else e}"
                )

    # the canary monitor (serve/canary.py): one long-lived instance,
    # armed per rollout episode by the pool — the front end feeds it
    # served latencies, the replica workers feed it batch splits, and
    # its live verdict decides promote vs auto-rollback
    canary_monitor = None
    if cfg.canary_fraction > 0:
        from bdbnn_tpu.serve.canary import (
            CanaryConfig,
            CanaryMonitor,
            apply_canary_overrides,
        )

        canary_monitor = CanaryMonitor(
            apply_canary_overrides(CanaryConfig(), cfg.canary_thresholds),
            priorities=cfg.priorities,
            on_event=lambda kind, **f: events.emit(kind, **f),
        )

    # the capacity observatory (obs/capacity.py): burn-rate windows
    # scale with the stats cadence — the pump is the only detector
    # clock, so ~5 ticks of fast window / ~30 of slow keeps the
    # warmup->debounce->hysteresis semantics stable whether the pump
    # runs at the production default or a test's tight interval
    from bdbnn_tpu.obs.capacity import CapacityPlane

    cap_fast_s = max(5 * cfg.stats_interval_s, 1.0)
    cap_slow_s = max(30 * cfg.stats_interval_s, 3 * cap_fast_s)
    cap_window_s = max(20 * cfg.stats_interval_s, 2.0)
    capacity_plane = CapacityPlane(
        slo_p99_ms=cfg.slo_p99_ms,
        slo_shed_rate=cfg.slo_shed_rate,
        priorities=cfg.priorities,
        window_s=cap_window_s,
        fast_window_s=cap_fast_s,
        slow_window_s=cap_slow_s,
        # busy-fraction samples arrive once per pump tick; sizing the
        # gauge window to span the SAME wall-clock stretch as the
        # demand window keeps capacity_rps_est (completed over busy
        # mean) and offered_rps measured over the same interval — a
        # whole-run busy mean would dilute the estimate and hide the
        # negative headroom a flash crowd must expose
        util_window=max(10, int(round(cap_window_s / cfg.stats_interval_s))),
    )

    front = HttpFrontEnd(
        batcher,
        admission,
        ready_fn=ready_fn,
        decode=decode,
        encode=encode,
        host=cfg.host,
        port=cfg.port,
        max_body_bytes=int(cfg.max_body_mb * 2**20),
        model_router=model_router,
        tracer=tracer,
        canary=canary_monitor,
        server_id=cfg.server_id or None,
        capacity=capacity_plane,
    )
    host, port = front.start()
    events.emit(
        "http",
        phase="start",
        host=host,
        port=port,
        server_id=cfg.server_id or None,
        artifact=os.path.abspath(artifact_dir),
        arch=engine.arch,
        buckets=list(engine.buckets),
        priorities=cfg.priorities,
        queue_depth=cfg.queue_depth,
        max_delay_ms=cfg.max_delay_ms,
        scenario=cfg.scenario or None,
        rate_rps=cfg.rate if cfg.scenario else None,
        requests=cfg.requests if cfg.scenario else None,
        replicas=cfg.replicas if cfg.pooled else None,
        version=version_label if cfg.pooled else None,
    )
    admin = None
    if cfg.pooled:
        # build the replica set: one engine per mesh device, AOT-warmed
        # by the factory — readyz stays 503 "warming" until the whole
        # set is resident, then flips
        from bdbnn_tpu.parallel.mesh import replica_devices
        from bdbnn_tpu.serve.pool import (
            PoolAdmin,
            ReplicaPool,
            first_warm_capture,
            make_engine_runner_factory,
            replica_stats_fields,
        )

        warm_compile, _on_engine = first_warm_capture()
        factory = make_engine_runner_factory(
            cfg.buckets,
            on_engine=_on_engine,
            packed=cfg.packed_weights,
            packed_impl=cfg.packed_impl,
            resident_models=cfg.resident_models,
            model_dirs=model_dirs,
            on_event=lambda kind, **f: events.emit(kind, **f),
            degrade=degrade,
        )
        pool = ReplicaPool(
            factory,
            list(replica_devices(cfg.replicas)),
            artifact_ref=artifact_dir,
            version=version_label,
            max_queue_batches=cfg.replica_queue_batches,
            wedge_timeout_s=cfg.wedge_timeout_s,
            on_event=lambda kind, **f: events.emit(kind, **f),
        )
        if cfg.models and model_router is not None:
            # the scenario's model mix is KNOWN up front: warm every
            # named co-resident model on every replica BEFORE readyz
            # flips, so no scheduled request pays a cold load+compile
            # mid-bench (an UNNAMED x-model still cold-loads lazily —
            # that latency is the client's explicit choice). A model
            # key of None is the default version — already resident.
            keys = {model_router(label) for label in cfg.models}
            for cache in factory.caches:
                for key in sorted(k for k in keys if k is not None):
                    cache.get(key)
        pool_ref.append(pool)  # readyz flips 200 from here
        admin = PoolAdmin(
            pool,
            registry=registry,
            # "shed caused during the swap window" across BOTH layers:
            # the front batcher's per-class queues and the pool's
            # replica queues — both in REQUEST units (the pool also
            # counts shed batches, a different unit)
            shed_counter=lambda: (
                batcher.stats()["shed"] + pool.stats()["shed_requests"]
            ),
            # --canary-fraction > 0 turns every triggered rollout into
            # a canary rollout: the monitor's live verdict promotes or
            # auto-rolls-back instead of an unconditional full shift
            canary=(
                {
                    "monitor": canary_monitor,
                    "fraction": cfg.canary_fraction,
                    "replicas": cfg.canary_replicas,
                    "shadow_every": cfg.shadow_every,
                    "seed": cfg.seed,
                }
                if canary_monitor is not None else None
            ),
        )
        front.admin = admin
        warmup_s = dict(warm_compile)
    else:
        pool = None
        warmup_s = engine.warmup()  # readyz flips 200 when this returns
    events.emit(
        "http", phase="ready", warmup_compile_s=dict(warmup_s),
        host=host, port=port,
        replicas=cfg.replicas if cfg.pooled else None,
    )

    from bdbnn_tpu.serve.pool import (
        resident_block,
        single_engine_resident_block,
    )

    def _resident_snapshot():
        """The verdict's ``resident`` block (and the serve_resident
        memory event's source): per-model resident device bytes from
        the replica caches — or, on the single-engine path, the one
        engine's own residency report (shared shape, pool.py)."""
        if cfg.pooled:
            return resident_block(
                getattr(factory, "caches", []),
                completed_by_model=(
                    front.accounting()["completed_by_model"] or None
                ),
            )
        return single_engine_resident_block(engine.residency())

    resident_now = _resident_snapshot()
    # per-bucket residency bytes are static after warmup: captured once
    # into the utilization windows (single-engine: the engine's own
    # report; pooled: the cache summary — per-replica bytes live in
    # the resident block already)
    if cfg.pooled:
        capacity_plane.utilization.set_residency(
            {
                "resident_bytes_per_model_max": resident_now[
                    "bytes_per_model_max"
                ],
                "models": len(resident_now["models"]),
                "replicas": resident_now["replicas"],
            }
            if resident_now is not None else None
        )
    else:
        capacity_plane.utilization.set_residency(engine.residency())
    if resident_now is not None:
        events.emit(
            "memory",
            phase="serve_resident",
            available=True,
            devices=[],
            peak_bytes=None,
            limit_bytes=None,
            weights_mode="packed" if cfg.packed_weights else "dense",
            packed_impl=cfg.packed_impl if cfg.packed_weights else None,
            resident_bytes=resident_now["bytes_per_model_max"],
            models=len(resident_now["models"]),
            replicas=resident_now["replicas"],
        )

    # periodic live-state events: per-priority depths, per-tenant
    # sheds, readiness — what `watch` renders for a serving run
    stats_stop = threading.Event()

    def stats_pump():
        while not stats_stop.wait(cfg.stats_interval_s):
            s = front.stats()
            events.emit(
                "http",
                phase="stats",
                state=s["state"],
                inflight=s["inflight"],
                requests_seen=s["requests_seen"],
                queue_depth_by_priority=[
                    q["queue_depth"] for q in s["batcher"]["per_priority"]
                ],
                completed_by_priority=s["completed_by_priority"],
                shed_by_priority=s["shed_by_priority"],
                tenants={
                    t: {
                        "admitted": c["admitted"],
                        "over_quota": c["over_quota"],
                        "shed": c["shed"],
                    }
                    for t, c in s["admission"]["tenants"].items()
                },
            )
            if pool is not None:
                # the live per-replica heartbeat `watch` renders
                events.emit(
                    "replica", phase="stats",
                    **replica_stats_fields(pool.stats()),
                )
            if tracer is not None:
                # the live stage histograms: `watch` renders the
                # per-stage p99 waterfall from this heartbeat
                events.emit("rtrace", phase="stats", **tracer.stats())
            # capacity tick: sample the utilization gauges from the
            # snapshots already in hand, then advance the burn-rate
            # detectors — the pump is the ONLY detector clock
            busy_fraction = None
            if pool is not None:
                reps = pool.stats()["replicas"]
                if reps:
                    busy_fraction = sum(
                        1 for r in reps if r["busy"]
                    ) / len(reps)
            rtr = s.get("rtrace") or {}
            capacity_plane.sample(
                busy_fraction=busy_fraction,
                occupancy=s["batcher"].get("mean_occupancy"),
                queue_share=rtr.get("queue_share"),
                admission_headroom=admission.token_headroom(),
            )
            cap_tick = capacity_plane.evaluate()
            for row in cap_tick["fired"]:
                events.emit("capacity", phase="breach", **row)
            for row in cap_tick["recovered"]:
                events.emit("capacity", phase="recovered", **row)
            # re-snapshot AFTER sampling so the emitted gauges and the
            # headroom estimate reflect THIS tick, not the previous one
            cap_live = capacity_plane.live_block()
            events.emit(
                "capacity",
                phase="stats",
                offered_rps=cap_live["demand"]["offered_rps"],
                in_flight=cap_live["demand"]["in_flight_decisions"],
                demand_shed_ratio_max=cap_live["demand"][
                    "demand_shed_ratio_max"
                ],
                headroom=cap_live["headroom"],
                utilization={
                    g: cap_live["utilization"][g]["last"]
                    for g in ("busy_fraction", "occupancy",
                              "queue_share", "admission_headroom")
                },
                detectors=cap_tick["detectors"],
            )

    pump = threading.Thread(target=stats_pump, daemon=True)
    pump.start()

    client_raw = None
    try:
        if cfg.scenario:
            rng = np.random.default_rng(cfg.seed)
            img_pool = rng.standard_normal((32, *shape)).astype(np.float32)
            bodies = [np.ascontiguousarray(x).tobytes() for x in img_pool]
            schedule = build_schedule(
                cfg.scenario,
                requests=cfg.requests,
                rate=cfg.rate,
                seed=cfg.seed,
                priorities=cfg.priorities,
                priority_weights=(
                    list(cfg.priority_weights)
                    if cfg.priority_weights else None
                ),
                tenants=cfg.tenants,
                tenant_weights=(
                    list(cfg.tenant_weights)
                    if cfg.tenant_weights else None
                ),
                flash_factor=cfg.flash_factor,
                diurnal_amp=cfg.diurnal_amp,
                heavy_sigma=cfg.heavy_sigma,
                slow_fraction=cfg.slow_fraction,
                models=list(cfg.models) or None,
                model_weights=(
                    list(cfg.model_weights)
                    if cfg.model_weights else None
                ),
            )
            # swap-under-load: after --swap-at of the schedule has been
            # OFFERED, fire the same blue/green rollout the admin
            # endpoint exposes — the bench then proves zero dropped and
            # zero shed-due-to-swap under this scenario's pressure
            on_arrival = None
            if cfg.swap_at > 0 and admin is not None:
                threshold = max(int(cfg.swap_at * len(schedule)), 1)
                swap_fired: List[bool] = []
                from bdbnn_tpu.serve.registry import (
                    looks_like_version,
                    parse_version,
                )

                if registry is not None and looks_like_version(
                    cfg.swap_to
                ):
                    swap_spec: Dict[str, Any] = {
                        "version": parse_version(cfg.swap_to)
                    }
                else:
                    swap_spec = {"artifact": cfg.swap_to}

                def on_arrival(i: int) -> None:
                    if not swap_fired and i + 1 >= threshold:
                        swap_fired.append(True)

                        # fire OFF the arrival-scheduling thread:
                        # start_swap digest-verifies the target
                        # (hashes weights.npz) before returning, and a
                        # stall here would offer every later arrival
                        # late — inflating exactly the latencies the
                        # swap-under-load bench exists to measure
                        def _fire(at=i + 1):
                            status, payload = admin.start_swap(
                                swap_spec
                            )
                            if status != 202:
                                # a rejected SCHEDULED swap must land
                                # in the verdict as not-performed — a
                                # bad --swap-to exiting 0 would read
                                # as a met rollout contract
                                admin.note_request_failed(
                                    cfg.swap_to, payload.get("error")
                                )
                            events.emit(
                                "swap",
                                phase="trigger",
                                at_request=at,
                                of=len(schedule),
                                status=status,
                                **payload,
                            )

                        threading.Thread(
                            target=_fire, name="swap-trigger",
                            daemon=True,
                        ).start()

            gen = HttpLoadGenerator(
                host,
                port,
                schedule,
                body_fn=lambda i: bodies[i % len(bodies)],
                concurrency=cfg.concurrency,
                stop_fn=lambda: handler.preempted,
                slow_chunks=cfg.slow_chunks,
                slow_gap_s=cfg.slow_gap_ms / 1000.0,
                on_arrival=on_arrival,
            )
            client_raw = gen.run()
        else:
            while not handler.preempted:
                time.sleep(0.1)
    finally:
        preempted = handler.preempted
        events.emit(
            "http",
            phase="drain",
            signum=handler.signum,
            preempted=preempted,
        )
        drained_clean = front.drain(timeout=120.0)
        if admin is not None:
            # let an in-flight rollout settle before the pool winds
            # down — its report belongs in the verdict either way
            admin.wait(timeout=30.0)
        if pool is not None:
            drained_clean = pool.drain(timeout=60.0) and drained_clean
        stats_stop.set()
        pump.join(timeout=5.0)

    admission_stats = admission.stats()
    events.emit("admission", phase="summary", **admission_stats)
    resident_final = _resident_snapshot()
    packed_block = None
    if cfg.packed_weights and resident_final is not None:
        rows = list(resident_final["models"].values())
        p_bytes = max(
            (m["resident_bytes"] for m in rows
             if m.get("resident_bytes") is not None),
            default=None,
        )
        d_bytes = max(
            (m["dense_equiv_bytes"] for m in rows
             if m.get("dense_equiv_bytes") is not None),
            default=None,
        )
        packed_block = {
            "mode": "on",
            "impl": cfg.packed_impl,
            # serve-http measures no dense side (that A/B is
            # serve-bench's job); the dense resident figure is the
            # computed equivalent, honest about what was NOT measured
            "dense": {
                "resident_bytes": d_bytes, "step_ms": None,
                "throughput_rps": None, "p99_ms": None,
            },
            "packed": {
                "resident_bytes": p_bytes, "step_ms": None,
                "throughput_rps": None, "p99_ms": None,
            },
            "resident_ratio": (
                round(d_bytes / max(p_bytes, 1), 3)
                if d_bytes is not None and p_bytes is not None else None
            ),
            "step_ms_delta_pct": None,
        }
    accounting = front.accounting()
    verdict = http_slo_verdict(
        accounting,
        batcher.stats(),
        admission_stats,
        scenario=cfg.scenario or "serve",
        # scenario mode records the SCHEDULED rate (the knob the bench
        # was asked to drive); serve mode records the MEASURED offered
        # rate derived from observed arrival stamps — cfg.rate there
        # would fabricate an offered-load figure nothing measured
        rate=(
            cfg.rate if cfg.scenario
            else accounting["measured_rate_rps"]
        ),
        seed=cfg.seed,
        provenance=_serve_provenance(
            artifact_dir, engine, prov, recipe, manifest
        ),
        warmup_s=warmup_s,
        preempted=preempted,
        drained_clean=drained_clean,
        client=client_raw,
        slo_p99_ms=cfg.slo_p99_ms,
        replicas=(
            _pool_replicas_block(pool.stats()) if pool is not None
            else None
        ),
        swap=admin.swap_report() if admin is not None else None,
        resident=resident_final,
        packed=packed_block,
        attribution=(
            tracer.attribution() if tracer is not None else None
        ),
        canary=(
            admin.canary_report() if admin is not None else None
        ),
        capacity=capacity_plane.verdict_block(),
    )
    events.emit("serve", phase="verdict", **verdict)
    events.emit("http", phase="stop", host=host, port=port)
    events.close()
    write_verdict_files(verdict, run_dir, cfg.out)
    return {
        "verdict": verdict,
        "run_dir": run_dir,
        "host": host,
        "port": port,
    }


__all__ = ["HttpFrontEnd", "PREDICT_PATH", "run_serve_http"]
