"""Batched inference runtime over a frozen export artifact.

The training stack compiles ONE train step and feeds it fixed-shape
batches; serving inverts the problem — request batches arrive at
arbitrary sizes, and XLA compiles per shape. The engine resolves that
with **batch-size buckets**: a small ladder of batch sizes, each
AOT-compiled at startup (``jax.jit(...).lower(...).compile()``), so no
request ever pays a compile stall. A batch of n rows is padded up to
the smallest bucket >= n (oversize batches are chunked through the
largest bucket first); padding rows are sliced off before the caller
sees logits.

The model is the SAME flax module the run trained
(``models.registry.create_model``) applied in eval mode. Two residency
modes for the weights:

- **dense** (default) — the artifact's reconstructed ``float_weight =
  sign * alpha`` tensors (exact fixed point of the training binarizer)
  are placed on device, so serve logits match the training run's eval
  logits to fp32 rounding.
- **packed** (``packed=True``) — binary convs stay 1-bit in device
  memory (``np.packbits`` sign + f32 alpha, the artifact's own
  representation); the jitted forward unpacks them transiently per
  step (nn/packed.py), so dense weights never become resident. Logits
  are BITWISE-equal to dense mode (the unpack is exact and feeds the
  identical subgraph; pinned per arch in tests/test_packed.py), while
  the resident weight footprint shrinks ~16-32x on the binary convs —
  the unlock for multi-model residency (serve/pool.py
  ``ResidentModelCache``). ``packed_impl="popcount"`` reroutes wide
  binary convs through the XNOR-popcount dot instead of unpack+conv
  (also exact in f32).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 8, 32)


class InferenceEngine:
    """Frozen-artifact inference with AOT-compiled batch buckets.

    ``warmup()`` (called by ``__init__`` unless ``warm=False``) compiles
    every bucket up front; ``predict_logits`` then never traces.
    """

    def __init__(
        self,
        artifact_dir: str,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        warm: bool = True,
        device: Optional[Any] = None,
        packed: bool = False,
        packed_impl: str = "unpack",
    ):
        from bdbnn_tpu.models.registry import create_model
        from bdbnn_tpu.nn.packed import PACKED_IMPLS
        from bdbnn_tpu.serve.export import (
            load_artifact_packed,
            load_artifact_variables,
            read_artifact,
        )

        if not buckets or any(int(b) <= 0 for b in buckets):
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        if packed_impl not in PACKED_IMPLS:
            raise ValueError(
                f"packed_impl must be one of {PACKED_IMPLS}, got "
                f"{packed_impl!r}"
            )
        self.artifact_dir = artifact_dir
        self.artifact = read_artifact(artifact_dir)
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        self.image_size = int(self.artifact["image_size"])
        self.num_classes = int(self.artifact["num_classes"])
        self.arch = self.artifact["arch"]
        self.dataset = self.artifact["dataset"]
        self.packed = bool(packed)
        self.packed_impl = packed_impl

        import jax

        model_dtype = self.artifact.get("model", {}).get("dtype", "float32")
        if self.packed and packed_impl == "popcount" and (
            model_dtype == "bfloat16"
        ):
            # bf16 conv accumulation rounds past 256 terms; the popcount
            # dot is exact integers — they would silently diverge
            raise ValueError(
                "packed_impl='popcount' needs a float32 artifact; this "
                "one records dtype=bfloat16 — use packed_impl='unpack'"
            )
        self._model = create_model(
            self.arch,
            self.dataset,
            dtype=model_dtype,
            twoblock=bool(
                self.artifact.get("model", {}).get("twoblock", False)
            ),
        )
        # weights go to device once; every compiled bucket closes over
        # the same placed copies. An explicit device pins this engine
        # to ONE mesh device — the replica-pool path (serve/pool.py)
        # places one engine per device so N replicas execute on N chips
        # instead of contending for the default one. In packed mode the
        # device_put ships the 1-bit payload, never the dense
        # reconstruction — THAT is the residency win.
        self.device = device
        if self.packed:
            host_vars, self._packed_spec = load_artifact_packed(
                artifact_dir
            )
        else:
            host_vars, self._packed_spec = (
                load_artifact_variables(artifact_dir), None
            )
        self._variables = jax.device_put(host_vars, device)
        self._compiled: Dict[int, Any] = {}
        self.compile_seconds: Dict[int, float] = {}
        # rolling host-observed blocked ms per predict_logits call —
        # the compute-stage cross-check the request tracer's
        # attribution block cites (obs/rtrace.py): time_step() is the
        # idle calibration, this window is the same quantity under
        # real serving interleave
        from collections import deque

        self._step_ms_window: Any = deque(maxlen=512)
        # per-bucket activation working-set cache (residency(); the
        # layer table behind it costs one eval_shape per bucket)
        self._act_ws: Dict[int, Any] = {}
        if warm:
            self.warmup()

    # -- compilation ---------------------------------------------------

    @property
    def warmed(self) -> bool:
        """True once EVERY bucket is AOT-compiled — what the HTTP front
        end's ``/readyz`` gates on (serve/http.py): a replica must not
        receive traffic that would stall on a first-request compile."""
        return all(b in self._compiled for b in self.buckets)

    def _apply(self, variables, images):
        return self._model.apply(variables, images, train=False)

    def warmup(self) -> Dict[int, float]:
        """AOT-compile every bucket; returns per-bucket compile seconds.
        Idempotent — already-compiled buckets are skipped. In packed
        mode the unpack/popcount impl is bound at trace time (the same
        process-global pattern as nn.kernels.default_impl), so the
        compiled executables fuse the reconstruction into the forward
        and XLA materializes dense weights only transiently per step."""
        import jax

        from bdbnn_tpu.nn.packed import packed_impl as _packed_impl_ctx

        for b in self.buckets:
            if b in self._compiled:
                continue
            t0 = time.perf_counter()
            # a device-pinned engine lowers its input spec with the
            # device's sharding, so the compiled executable lives on
            # (and accepts numpy inputs transferred to) THAT device
            if self.device is not None:
                from jax.sharding import SingleDeviceSharding

                zeros = jax.ShapeDtypeStruct(
                    (b, self.image_size, self.image_size, 3), np.float32,
                    sharding=SingleDeviceSharding(self.device),
                )
            else:
                zeros = jax.ShapeDtypeStruct(
                    (b, self.image_size, self.image_size, 3), np.float32
                )
            with _packed_impl_ctx(self.packed_impl):
                self._compiled[b] = (
                    jax.jit(self._apply)
                    .lower(self._variables, zeros)
                    .compile()
                )
            self.compile_seconds[b] = round(time.perf_counter() - t0, 3)
        return dict(self.compile_seconds)

    # -- residency accounting ------------------------------------------

    def _activation_working_set(self) -> Dict[str, Any]:
        """Per-bucket activation working-set estimate: f32 bytes in/out
        of every conv at each bucket's batch size (plus the fc row),
        from the roofline layer table — the gate metric the ROADMAP's
        end-to-end activation-packing item names. Cached per bucket
        (one ``eval_shape`` each, no device work). Never raises: an
        arch the shape tracer cannot walk reports an ``error`` string
        instead of breaking residency for serving callers."""
        from bdbnn_tpu.obs.roofline import model_layer_table

        out: Dict[str, Any] = {}
        for b in self.buckets:
            if b not in self._act_ws:
                try:
                    rows = model_layer_table(
                        self.arch,
                        self.dataset,
                        b,
                        image_size=self.image_size,
                        dtype=self.artifact.get("model", {}).get(
                            "dtype", "float32"
                        ),
                        twoblock=bool(
                            self.artifact.get("model", {}).get(
                                "twoblock", False
                            )
                        ),
                    )
                    per_conv = {
                        r["name"]: {
                            "in": int(r["act_in_bytes"]),
                            "out": int(r["act_out_bytes"]),
                        }
                        for r in rows
                    }
                    self._act_ws[b] = {
                        "bytes_in": sum(
                            v["in"] for v in per_conv.values()
                        ),
                        "bytes_out": sum(
                            v["out"] for v in per_conv.values()
                        ),
                        "per_conv": per_conv,
                    }
                except Exception as e:  # pragma: no cover - defensive
                    self._act_ws[b] = {"error": str(e)}
            out[str(b)] = self._act_ws[b]
        return out

    def residency(self) -> Dict[str, Any]:
        """Resident weight-memory report: the bytes this engine keeps
        alive in device memory, the bytes the OTHER mode would keep for
        the same artifact, their ratio — what the ``memory`` serve
        events and the A/B verdict's ``packed`` block record — plus the
        per-bucket activation working set (``activations``), the
        counterpart number activation packing would shrink."""
        import jax

        from bdbnn_tpu.nn.packed import (
            dense_weight_bytes,
            packed_weight_bytes,
        )

        resident = int(
            sum(
                int(x.nbytes)
                for x in jax.tree_util.tree_leaves(self._variables)
            )
        )
        activations = self._activation_working_set()
        if self.packed:
            dense_equiv = int(self._packed_spec["dense_equiv_bytes"])
        else:
            # what load_artifact_packed would keep resident: swap each
            # binary conv's dense f32 tensor for packbits sign + alpha
            # (the shared byte hooks in nn/packed.py — the same math
            # the roofline's packed-weight regime prices)
            dense_equiv = resident
            packed_equiv = resident
            for t in self.artifact.get("tensors", []):
                if t["kind"] != "binary":
                    continue
                packed_equiv += packed_weight_bytes(
                    t["shape"]
                ) - dense_weight_bytes(t["shape"])
            return {
                "packed": False,
                "resident_bytes": resident,
                "dense_equiv_bytes": dense_equiv,
                "packed_equiv_bytes": packed_equiv,
                "ratio": round(resident / max(packed_equiv, 1), 3),
                "activations": activations,
            }
        return {
            "packed": True,
            "resident_bytes": resident,
            "dense_equiv_bytes": dense_equiv,
            "packed_equiv_bytes": resident,
            "ratio": round(dense_equiv / max(resident, 1), 3),
            "activations": activations,
        }

    def time_step(
        self, bucket: Optional[int] = None, iters: int = 10
    ) -> float:
        """Mean wall ms per compiled forward on ``bucket`` (default:
        the largest) — the ``serve_packed_step_ms`` /
        ``serve_dense_step_ms`` number the A/B verdict records. One
        unmeasured call first so allocator warmup never taints the
        mean; every measured call blocks until the result is ready."""
        b = self.buckets[-1] if bucket is None else int(bucket)
        if b not in self._compiled:
            self.warmup()
        x = np.zeros((b, self.image_size, self.image_size, 3), np.float32)
        self._compiled[b](self._variables, x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(max(int(iters), 1)):
            self._compiled[b](self._variables, x).block_until_ready()
        return round(
            (time.perf_counter() - t0) * 1000.0 / max(int(iters), 1), 3
        )

    def hlo_text(self, bucket: Optional[int] = None) -> str:
        """Optimized HLO text of a bucket's compiled executable — the
        per-instruction ``op_name`` scope metadata in here is what
        joins profiler op events back to model layers on backends
        whose trace events carry no ``tf_op`` (CPU); see
        ``obs.trace.hlo_op_scopes``."""
        b = self.buckets[-1] if bucket is None else int(bucket)
        if b not in self._compiled:
            self.warmup()
        return self._compiled[b].as_text()

    def trace_step(
        self,
        trace_dir: str,
        bucket: Optional[int] = None,
        iters: int = 10,
    ) -> Dict[str, Any]:
        """``time_step`` with a profiler window around the timed loop:
        same input recipe, same one unmeasured warmup call (OUTSIDE the
        window, so allocator warmup taints neither the mean nor the
        trace), then ``iters`` measured steps inside
        ``jax.profiler.trace``. Returns the wall mean alongside the
        trace dir so the roofline harness can reconcile per-op trace
        time against the very wall it was captured under."""
        import jax

        b = self.buckets[-1] if bucket is None else int(bucket)
        if b not in self._compiled:
            self.warmup()
        n = max(int(iters), 1)
        x = np.zeros((b, self.image_size, self.image_size, 3), np.float32)
        self._compiled[b](self._variables, x).block_until_ready()
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        try:
            t0 = time.perf_counter()
            for _ in range(n):
                self._compiled[b](self._variables, x).block_until_ready()
            wall_ms = (time.perf_counter() - t0) * 1000.0 / n
        finally:
            jax.profiler.stop_trace()
        return {
            "bucket": b,
            "iters": n,
            "wall_ms": round(wall_ms, 3),
            "trace_dir": trace_dir,
        }

    def step_stats(self) -> Dict[str, Any]:
        """Percentiles of the rolling blocked-compute window (host
        wall per ``predict_logits`` call) — the device side of the
        request tracer's ``compute`` stage, measured where the engine
        owns it. Empty window lands every percentile as None (the
        verdict renders null, never a TypeError)."""
        from bdbnn_tpu.serve.loadgen import _pct

        window = sorted(self._step_ms_window)
        return {
            "calls": len(window),
            "p50_ms": _pct(window, 50.0),
            "p99_ms": _pct(window, 99.0),
        }

    # -- inference -----------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def predict_logits(self, images: np.ndarray) -> np.ndarray:
        """Logits for ``images`` (n, H, W, 3) float32, any n >= 1.

        One loop over ``max_bucket``-sized chunks: every chunk —
        including the final short one — pads up to its own bucket and
        slices the padding back off, so an oversize batch is plain
        iteration, not a recursive re-entry whose final chunk replays
        the whole dispatch. Chunk-boundary logit equality (n = big+1,
        2*big+3) is pinned in tests/test_serve.py; the packed path
        inherits this seam unchanged."""
        images = np.asarray(images, np.float32)
        if images.ndim == 3:
            images = images[None]
        n = len(images)
        if n == 0:
            return np.zeros((0, self.num_classes), np.float32)
        t0 = time.perf_counter()
        big = self.buckets[-1]
        out = []
        for i in range(0, n, big):
            chunk = images[i : i + big]
            m = len(chunk)
            b = self._bucket_for(m)
            if m < b:
                pad = np.zeros((b - m, *chunk.shape[1:]), np.float32)
                chunk = np.concatenate([chunk, pad])
            logits = self._compiled[b](self._variables, chunk)
            out.append(np.asarray(logits)[:m])
        # np.asarray on the device result blocks until ready, so this
        # wall IS the blocked device compute the host paid
        self._step_ms_window.append(
            (time.perf_counter() - t0) * 1000.0
        )
        return out[0] if len(out) == 1 else np.concatenate(out)

    def predict(self, images: np.ndarray) -> np.ndarray:
        """Top-1 class indices for ``images``."""
        return np.argmax(self.predict_logits(images), axis=-1)


def max_abs_logit_drift(a, b) -> Optional[float]:
    """Max absolute element-wise difference between two engines'
    results for the SAME payloads — the shadow-mirroring probe's
    comparator (serve/canary.py).

    This number is meaningful as a zero-tolerance quality gate only
    because packed 1-bit inference is deterministic and bitwise-exact:
    the exported artifact is a fixed point of the training binarizer
    (serve/export.py), the on-device unpack reproduces the host packing
    bit-for-bit (nn/packed.py), and the engine's AOT-compiled buckets
    run the identical subgraph on every call. Two engines serving the
    same artifact therefore return BITWISE-identical logits — any
    nonzero drift between an incumbent and a republished-identical
    canary is a real defect (torn publish, wrong artifact, silent
    dtype change, a degraded runner), never float noise. Float-serving
    stacks cannot gate this cheaply; a 1-bit stack gets it for free.

    ``a``/``b`` are whatever the replica runner returned (a stacked
    logits array or a list of per-payload rows). Returns None when the
    shapes cannot be aligned — an incomparable pair must be surfaced
    as "no measurement", never as drift 0.0."""
    try:
        ra = [np.asarray(x, np.float64) for x in list(a)]
        rb = [np.asarray(x, np.float64) for x in list(b)]
        if len(ra) != len(rb) or any(
            xa.shape != xb.shape for xa, xb in zip(ra, rb)
        ):
            return None
        if not ra:
            return 0.0
        return float(
            max(float(np.max(np.abs(xa - xb))) for xa, xb in zip(ra, rb))
        )
    except Exception:
        return None


def evaluate_split(engine: InferenceEngine, pipe) -> Dict[str, Any]:
    """Offline batch inference over a pipeline's split: top-1 over every
    example, computed with the same ``100 * correct / count`` arithmetic
    the training loop's ``_validate`` records — so an exported
    checkpoint's accuracy can be checked for EXACT equality against the
    run's recorded eval top-1."""
    correct = 0
    count = 0
    batches = 0
    for x, y in pipe.epoch(0):
        pred = engine.predict(np.asarray(x))
        correct += int(np.sum(pred == np.asarray(y)))
        count += len(pred)
        batches += 1
    acc1 = 100.0 * correct / max(count, 1)
    return {
        "top1": acc1,
        "correct": correct,
        "count": count,
        "batches": batches,
    }


__all__ = [
    "DEFAULT_BUCKETS",
    "InferenceEngine",
    "evaluate_split",
    "max_abs_logit_drift",
]
