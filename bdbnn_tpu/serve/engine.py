"""Batched inference runtime over a frozen export artifact.

The training stack compiles ONE train step and feeds it fixed-shape
batches; serving inverts the problem — request batches arrive at
arbitrary sizes, and XLA compiles per shape. The engine resolves that
with **batch-size buckets**: a small ladder of batch sizes, each
AOT-compiled at startup (``jax.jit(...).lower(...).compile()``), so no
request ever pays a compile stall. A batch of n rows is padded up to
the smallest bucket >= n (oversize batches are chunked through the
largest bucket first); padding rows are sliced off before the caller
sees logits.

The model is the SAME flax module the run trained
(``models.registry.create_model``) applied in eval mode — the artifact
supplies reconstructed ``float_weight = sign * alpha`` tensors (exact
fixed point of the training binarizer) and folded-BN identity stats, so
serve logits match the training run's eval logits to fp32 rounding.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 8, 32)


class InferenceEngine:
    """Frozen-artifact inference with AOT-compiled batch buckets.

    ``warmup()`` (called by ``__init__`` unless ``warm=False``) compiles
    every bucket up front; ``predict_logits`` then never traces.
    """

    def __init__(
        self,
        artifact_dir: str,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        warm: bool = True,
        device: Optional[Any] = None,
    ):
        from bdbnn_tpu.models.registry import create_model
        from bdbnn_tpu.serve.export import (
            load_artifact_variables,
            read_artifact,
        )

        if not buckets or any(int(b) <= 0 for b in buckets):
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        self.artifact_dir = artifact_dir
        self.artifact = read_artifact(artifact_dir)
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        self.image_size = int(self.artifact["image_size"])
        self.num_classes = int(self.artifact["num_classes"])
        self.arch = self.artifact["arch"]
        self.dataset = self.artifact["dataset"]

        import jax

        self._model = create_model(
            self.arch,
            self.dataset,
            dtype=self.artifact.get("model", {}).get("dtype", "float32"),
            twoblock=bool(
                self.artifact.get("model", {}).get("twoblock", False)
            ),
        )
        # weights go to device once; every compiled bucket closes over
        # the same placed copies. An explicit device pins this engine
        # to ONE mesh device — the replica-pool path (serve/pool.py)
        # places one engine per device so N replicas execute on N chips
        # instead of contending for the default one.
        self.device = device
        self._variables = jax.device_put(
            load_artifact_variables(artifact_dir), device
        )
        self._compiled: Dict[int, Any] = {}
        self.compile_seconds: Dict[int, float] = {}
        if warm:
            self.warmup()

    # -- compilation ---------------------------------------------------

    @property
    def warmed(self) -> bool:
        """True once EVERY bucket is AOT-compiled — what the HTTP front
        end's ``/readyz`` gates on (serve/http.py): a replica must not
        receive traffic that would stall on a first-request compile."""
        return all(b in self._compiled for b in self.buckets)

    def _apply(self, variables, images):
        return self._model.apply(variables, images, train=False)

    def warmup(self) -> Dict[int, float]:
        """AOT-compile every bucket; returns per-bucket compile seconds.
        Idempotent — already-compiled buckets are skipped."""
        import jax

        for b in self.buckets:
            if b in self._compiled:
                continue
            t0 = time.perf_counter()
            # a device-pinned engine lowers its input spec with the
            # device's sharding, so the compiled executable lives on
            # (and accepts numpy inputs transferred to) THAT device
            if self.device is not None:
                from jax.sharding import SingleDeviceSharding

                zeros = jax.ShapeDtypeStruct(
                    (b, self.image_size, self.image_size, 3), np.float32,
                    sharding=SingleDeviceSharding(self.device),
                )
            else:
                zeros = jax.ShapeDtypeStruct(
                    (b, self.image_size, self.image_size, 3), np.float32
                )
            self._compiled[b] = (
                jax.jit(self._apply).lower(self._variables, zeros).compile()
            )
            self.compile_seconds[b] = round(time.perf_counter() - t0, 3)
        return dict(self.compile_seconds)

    # -- inference -----------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def predict_logits(self, images: np.ndarray) -> np.ndarray:
        """Logits for ``images`` (n, H, W, 3) float32, any n >= 1.
        Pads up to the bucket (chunking through the largest bucket when
        n exceeds it); callers only ever see the n real rows."""
        images = np.asarray(images, np.float32)
        if images.ndim == 3:
            images = images[None]
        n = len(images)
        if n == 0:
            return np.zeros((0, self.num_classes), np.float32)
        big = self.buckets[-1]
        if n > big:
            return np.concatenate(
                [
                    self.predict_logits(images[i : i + big])
                    for i in range(0, n, big)
                ]
            )
        b = self._bucket_for(n)
        if n < b:
            pad = np.zeros((b - n, *images.shape[1:]), np.float32)
            images = np.concatenate([images, pad])
        logits = self._compiled[b](self._variables, images)
        return np.asarray(logits)[:n]

    def predict(self, images: np.ndarray) -> np.ndarray:
        """Top-1 class indices for ``images``."""
        return np.argmax(self.predict_logits(images), axis=-1)


def evaluate_split(engine: InferenceEngine, pipe) -> Dict[str, Any]:
    """Offline batch inference over a pipeline's split: top-1 over every
    example, computed with the same ``100 * correct / count`` arithmetic
    the training loop's ``_validate`` records — so an exported
    checkpoint's accuracy can be checked for EXACT equality against the
    run's recorded eval top-1."""
    correct = 0
    count = 0
    batches = 0
    for x, y in pipe.epoch(0):
        pred = engine.predict(np.asarray(x))
        correct += int(np.sum(pred == np.asarray(y)))
        count += len(pred)
        batches += 1
    acc1 = 100.0 * correct / max(count, 1)
    return {
        "top1": acc1,
        "correct": correct,
        "count": count,
        "batches": batches,
    }


__all__ = ["DEFAULT_BUCKETS", "InferenceEngine", "evaluate_split"]
