"""Freeze a training checkpoint into a deployment artifact.

Training checkpoints drag the whole QAT apparatus along: latent float
master weights, optimizer moments, the EDE (t, k) anneal, host RNG —
none of which inference needs (XNOR-Net, arXiv:1603.05279, motivates
binarization entirely by inference cost). ``export_artifact`` strips a
checkpoint down to what the serve-time forward actually reads:

- every binary conv's latent ``float_weight`` is binarized ONCE:
  ``sign(W)`` bit-packed (1 bit/weight via ``np.packbits``) plus the
  per-output-channel scale ``alpha = mean|W|`` in float32 — the exact
  fixed point of the training-time binarizer, so reconstructing
  ``sign * alpha`` and running the normal eval forward reproduces the
  checkpoint's logits (``sign(sign·alpha) == sign``, ``mean|sign·alpha|
  == alpha``);
- every BatchNorm is folded into a per-channel scale/bias affine
  (:func:`bdbnn_tpu.models.resnet.fold_batch_norm`) — running stats are
  not shipped;
- optimizer state, EDE schedule, resume cursors and host RNG are simply
  never read (``load_export_payload`` returns weights only); the test
  suite asserts no ``float_weight``/optimizer/EDE key survives into the
  artifact;
- a strict-JSON ``artifact.json`` manifest carries the model recipe and
  run provenance (config, config hash, device kind, checkpoint
  integrity verdict) copied from the run's ``manifest.json``, plus the
  recorded eval top-1 the artifact claims to reproduce and a full
  tensor index (path, kind, shape, dtype) for the ``weights.npz``
  payload.

The export is recorded as an ``export`` event in the source run's
``events.jsonl``, so ``summarize``/``watch``/``compare`` see the
training→serving hand-off on the same timeline as the run itself.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

ARTIFACT_NAME = "artifact.json"
WEIGHTS_NAME = "weights.npz"
ARTIFACT_SCHEMA_VERSION = 1

# substrings that must never appear in an artifact's tensor index —
# training-only state the export exists to strip (asserted by
# tests/test_serve.py on a real exported artifact)
FORBIDDEN_STATE = ("float_weight", "opt_state", "ede", "momentum", "rng")


def _flat_leaves(tree, prefix=()) -> List[Tuple[Tuple[str, ...], Any]]:
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree.keys()):
            out += _flat_leaves(tree[k], prefix + (k,))
    else:
        out.append((prefix, tree))
    return out


def _file_sha256(path: str) -> str:
    """Chunked sha256 of a file — the one hashing scheme both the
    export (write) and load (verify) sides of the weights payload use."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _pack_sign(w: np.ndarray) -> np.ndarray:
    """sign(w) with sign(0) := +1 (the binarizer's convention,
    nn/binarize.py) packed to 1 bit/weight: bit 1 == +1."""
    return np.packbits((w >= 0).reshape(-1))


def unpack_sign(packed: np.ndarray, shape) -> np.ndarray:
    """Inverse of :func:`_pack_sign`: ±1 float32 of ``shape``."""
    n = int(np.prod(shape))
    bits = np.unpackbits(packed)[:n].reshape(shape)
    return (bits.astype(np.float32) * 2.0) - 1.0


def _recipe_provenance(config: Dict[str, Any]) -> Dict[str, Any]:
    from bdbnn_tpu.obs.compare import RECIPE_FIELDS

    return {k: config.get(k) for k in RECIPE_FIELDS}


def export_artifact(
    source: str,
    out_dir: str,
    *,
    arch: Optional[str] = None,
    dataset: Optional[str] = None,
) -> Dict[str, Any]:
    """Freeze ``source`` (a run dir or checkpoint dir) into ``out_dir``
    (``artifact.json`` + ``weights.npz``); returns the artifact
    manifest. ``arch``/``dataset`` override what the run manifest or
    checkpoint payload recorded (needed when exporting a bare
    checkpoint dir with no manifest)."""
    from bdbnn_tpu.models.resnet import fold_batch_norm
    from bdbnn_tpu.obs.events import EventWriter, jsonsafe, read_events
    from bdbnn_tpu.obs.manifest import read_manifest
    from bdbnn_tpu.utils.checkpoint import load_export_payload

    payload = load_export_payload(source)

    # provenance: the run manifest lives in the source dir or its parent
    # (source may point at the checkpoint dir itself)
    run_dir = None
    manifest = None
    for cand in (source, os.path.dirname(source.rstrip(os.sep))):
        if cand and os.path.isdir(cand):
            m = read_manifest(cand)
            if m is not None:
                manifest, run_dir = m, cand
                break
    config = (manifest or {}).get("config") or {}

    arch = arch or config.get("arch") or payload["arch"]
    dataset = dataset or config.get("dataset")
    if not arch:
        raise ValueError(
            "checkpoint records no arch and none was passed; use --arch"
        )
    if not dataset:
        # a silent default would bake the wrong num_classes/image_size
        # into the artifact and serve garbage without an error
        raise ValueError(
            "checkpoint records no dataset (bare checkpoint dir with no "
            "run manifest) and none was passed; use --dataset"
        )
    num_classes = {"cifar10": 10, "cifar100": 100, "imagenet": 1000}[dataset]
    image_size = 224 if dataset == "imagenet" else 32

    # host numpy trees (orbax restores numpy on the local path already;
    # normalize defensively so the fold/pack math never traces)
    to_np = lambda t: {
        k: to_np(v) if isinstance(v, dict) else np.asarray(v)
        for k, v in t.items()
    }
    variables = fold_batch_norm(
        {
            "params": to_np(payload["params"]),
            "batch_stats": to_np(payload["batch_stats"]),
        }
    )

    tensors: List[Dict[str, Any]] = []
    arrays: Dict[str, np.ndarray] = {}
    bn_paths: List[str] = []
    dense_bytes = 0
    packed_bytes = 0
    binarized = 0

    # the per-channel alpha is the FAMILY's scale (nn/binarize.py
    # registry — the run's manifest records which family trained these
    # weights): mean|W| for the default lineage, the loss-aware
    # ΣW²/Σ|W| for `lab`. The serving fixed point is family-invariant
    # (mean|sign·alpha| == alpha for any positive per-channel alpha),
    # but the STORED alpha must be the training one or the artifact
    # would not reproduce the checkpoint's eval logits.
    from bdbnn_tpu.nn.binarize import resolve_family, weight_alpha_np

    family_name = resolve_family(
        config.get("binarizer", ""), ede=bool(config.get("ede"))
    ).name

    for path, leaf in _flat_leaves(variables["params"]):
        name = "/".join(path)
        leaf = np.asarray(leaf)
        if path[-1] == "float_weight" and leaf.ndim == 4:
            # binarize ONCE: packed sign + per-out-channel alpha
            alpha = weight_alpha_np(family_name, leaf)
            packed = _pack_sign(leaf)
            base = "/".join(path[:-1])
            arrays[f"sign:{base}"] = packed
            arrays[f"alpha:{base}"] = alpha
            tensors.append({
                "path": base,
                "kind": "binary",
                "shape": list(leaf.shape),
                "dtype": "1bit+f32alpha",
            })
            binarized += 1
            dense_bytes += leaf.astype(np.float32).nbytes
            packed_bytes += packed.nbytes + alpha.nbytes
        else:
            arr = leaf.astype(np.float32) if leaf.dtype != np.float32 else leaf
            arrays[f"dense:{name}"] = arr
            tensors.append({
                "path": name,
                "kind": "dense",
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            })
            dense_bytes += arr.nbytes
            packed_bytes += arr.nbytes
    # folded BN stats are NOT shipped — only their module paths, so the
    # engine can rebuild the identity stats (bn_identity_stats)
    for path, leaf in _flat_leaves(variables["batch_stats"]):
        if path[-1] == "mean":
            bn_paths.append("/".join(path[:-1]))

    for t in tensors:
        low = t["path"].lower()
        if any(f in low for f in FORBIDDEN_STATE):
            raise AssertionError(
                f"training-only state leaked into the artifact: {t['path']}"
            )

    # the eval accuracy this artifact claims to reproduce: ONLY a
    # model_best payload's best_acc1 is the exported weights' own
    # recorded top-1. A rolling-checkpoint export (run preempted before
    # any model_best landed, or a bare checkpoint dir) carries weights
    # whose accuracy was never evaluated — claiming best-so-far there
    # would make `predict --check` judge the weights against a number
    # they never produced, so checkpoint_acc1 stays None and the
    # best-seen value is recorded separately for context.
    from bdbnn_tpu.utils.checkpoint import BEST_NAME

    src_base = os.path.basename(payload["source"].rstrip(os.sep))
    from_best = src_base.startswith(BEST_NAME)
    eval_events = read_events(run_dir, "eval") if run_dir else []
    recorded = {
        "source": "model_best" if from_best else "checkpoint",
        "checkpoint_acc1": payload["best_acc1"] if from_best else None,
        "best_seen_acc1": payload["best_acc1"],
        "checkpoint_epoch": payload["epoch"],
        "final_eval_acc1": (
            eval_events[-1].get("acc1") if eval_events else None
        ),
        "evals_recorded": len(eval_events),
    }

    artifact = {
        "schema": ARTIFACT_SCHEMA_VERSION,
        "created_unix": round(time.time(), 3),
        "arch": arch,
        "dataset": dataset,
        "num_classes": num_classes,
        "image_size": image_size,
        "model": {
            "dtype": config.get("dtype", "float32"),
            "twoblock": bool(config.get("twoblock", False)),
        },
        "eval": recorded,
        "checkpoint": {
            "source": payload["source"],
            "integrity": payload["integrity"],
            "fallback": payload["fallback"],
        },
        "provenance": {
            "run_dir": os.path.abspath(run_dir) if run_dir else None,
            "config_hash": (manifest or {}).get("config_hash"),
            "device_kind": (manifest or {}).get("device_kind"),
            "recipe": _recipe_provenance(config),
            "config": config,
        },
        "tensors": tensors,
        "bn_folded": sorted(bn_paths),
        "stats": {
            "binarized_convs": binarized,
            "dense_bytes": dense_bytes,
            "artifact_bytes": packed_bytes,
            "compression_ratio": round(
                dense_bytes / max(packed_bytes, 1), 3
            ),
        },
    }

    os.makedirs(out_dir, exist_ok=True)
    # atomic pair: weights land via tmp+rename, and artifact.json
    # records their sha256 — load_artifact_variables verifies it, so a
    # crash between the two renames (new weights, stale manifest — or
    # the reverse) reads as a loud digest mismatch, never as a silently
    # wrong artifact
    wtmp = os.path.join(out_dir, WEIGHTS_NAME + ".tmp")
    with open(wtmp, "wb") as f:
        np.savez(f, **arrays)
    artifact["weights_sha256"] = _file_sha256(wtmp)
    os.replace(wtmp, os.path.join(out_dir, WEIGHTS_NAME))
    tmp = os.path.join(out_dir, ARTIFACT_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(jsonsafe(artifact), f, indent=2, sort_keys=True)
    os.replace(tmp, os.path.join(out_dir, ARTIFACT_NAME))

    if run_dir is not None:
        # the export lands on the run's own timeline
        ev = EventWriter(run_dir)
        ev.emit(
            "export",
            artifact=os.path.abspath(out_dir),
            arch=arch,
            dataset=dataset,
            checkpoint=payload["source"],
            integrity=payload["integrity"],
            binarized_convs=binarized,
            compression_ratio=artifact["stats"]["compression_ratio"],
            checkpoint_acc1=recorded["checkpoint_acc1"],
        )
        ev.close()
    return artifact


def read_artifact(artifact_dir: str) -> Dict[str, Any]:
    """Load ``artifact.json``; raises with a pointed message when the
    dir is not an export artifact."""
    path = os.path.join(artifact_dir, ARTIFACT_NAME)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{artifact_dir!r} holds no {ARTIFACT_NAME} — not an export "
            "artifact (run `python -m bdbnn_tpu.cli export` first)"
        )
    with open(path) as f:
        return json.load(f)


def _verified_npz(artifact_dir: str, artifact: Dict[str, Any]):
    """Open ``weights.npz`` after verifying it against the manifest's
    recorded sha256: a torn re-export (new weights under a stale
    manifest, or vice versa) fails loudly here instead of serving the
    wrong checkpoint. The one verify-then-open both loaders use."""
    wpath = os.path.join(artifact_dir, WEIGHTS_NAME)
    want = artifact.get("weights_sha256")
    if want:
        if _file_sha256(wpath) != want:
            raise RuntimeError(
                f"{wpath} does not match the sha256 recorded in "
                f"{ARTIFACT_NAME} — torn or mixed re-export; re-run "
                "`export` into a fresh directory"
            )
    return np.load(wpath)


def _set_path(tree, path, leaf):
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = leaf


def load_artifact_variables(artifact_dir: str) -> Dict[str, Any]:
    """Rebuild the eval-apply ``{params, batch_stats}`` trees from an
    artifact: binary convs get ``float_weight = sign * alpha`` (the
    exact fixed point of the training binarizer — re-binarizing it
    yields the same sign and the same per-channel alpha), folded BNs get
    identity running stats. This is the DENSE loader: the reconstructed
    float tensors stay resident; :func:`load_artifact_packed` is the
    1-bit-resident alternative."""
    from bdbnn_tpu.models.resnet import bn_identity_stats

    artifact = read_artifact(artifact_dir)
    z = _verified_npz(artifact_dir, artifact)

    params: Dict[str, Any] = {}
    for t in artifact["tensors"]:
        path = tuple(t["path"].split("/"))
        if t["kind"] == "binary":
            sign = unpack_sign(z[f"sign:{t['path']}"], t["shape"])
            alpha = z[f"alpha:{t['path']}"]
            _set_path(params, path + ("float_weight",), sign * alpha)
        else:
            _set_path(params, path, z[f"dense:{t['path']}"])

    batch_stats: Dict[str, Any] = {}
    for bn in artifact["bn_folded"]:
        path = tuple(bn.split("/"))
        node = params
        for k in path:
            node = node[k]
        _set_path(batch_stats, path, bn_identity_stats(len(node["scale"])))
    return {"params": params, "batch_stats": batch_stats}


def load_artifact_packed(
    artifact_dir: str,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Rebuild the eval-apply variables with binary convs kept PACKED:
    returns ``(variables, spec)`` where ``variables`` carries the usual
    ``params``/``batch_stats`` trees (dense leaves only — no
    ``float_weight`` for binary convs) plus a ``packed`` collection of
    per-conv ``{sign: uint8 packbits, alpha: f32}`` that the model's
    packed-apply path (nn/layers.py + nn/packed.py) unpacks transiently
    inside the jitted forward. The whole tree is device-ready: one
    ``jax.device_put`` keeps the 1-bit payload — not the 16-32x larger
    dense reconstruction — resident in HBM.

    ``spec`` is the unpack spec the engine's residency accounting and
    the A/B verdict read: per binary conv the module path, dense shape,
    packed bytes and dense-equivalent bytes, plus the tree-wide totals.
    Same digest verification as the dense loader."""
    from bdbnn_tpu.models.resnet import bn_identity_stats

    artifact = read_artifact(artifact_dir)
    z = _verified_npz(artifact_dir, artifact)

    params: Dict[str, Any] = {}
    packed: Dict[str, Any] = {}
    binary = []
    packed_bytes = 0
    dense_equiv = 0
    for t in artifact["tensors"]:
        path = tuple(t["path"].split("/"))
        if t["kind"] == "binary":
            sign = z[f"sign:{t['path']}"]
            alpha = np.asarray(z[f"alpha:{t['path']}"], np.float32)
            _set_path(packed, path + ("sign",), sign)
            _set_path(packed, path + ("alpha",), alpha)
            n_dense = int(np.prod(t["shape"])) * 4
            binary.append({
                "path": t["path"],
                "shape": list(t["shape"]),
                "packed_bytes": int(sign.nbytes + alpha.nbytes),
                "dense_bytes": n_dense,
            })
            packed_bytes += int(sign.nbytes + alpha.nbytes)
            dense_equiv += n_dense
        else:
            arr = z[f"dense:{t['path']}"]
            _set_path(params, path, arr)
            packed_bytes += int(arr.nbytes)
            dense_equiv += int(arr.nbytes)

    batch_stats: Dict[str, Any] = {}
    for bn in artifact["bn_folded"]:
        path = tuple(bn.split("/"))
        node = params
        for k in path:
            node = node[k]
        stats = bn_identity_stats(len(node["scale"]))
        _set_path(batch_stats, path, stats)
        nb = sum(int(v.nbytes) for v in stats.values())
        packed_bytes += nb
        dense_equiv += nb
    spec = {
        "binary": binary,
        "packed_resident_bytes": packed_bytes,
        "dense_equiv_bytes": dense_equiv,
        "ratio": round(dense_equiv / max(packed_bytes, 1), 3),
    }
    return (
        {"params": params, "batch_stats": batch_stats, "packed": packed},
        spec,
    )


__all__ = [
    "ARTIFACT_NAME",
    "ARTIFACT_SCHEMA_VERSION",
    "FORBIDDEN_STATE",
    "WEIGHTS_NAME",
    "export_artifact",
    "load_artifact_packed",
    "load_artifact_variables",
    "read_artifact",
    "unpack_sign",
]
