"""Canary analysis: live-verdict health judgment for a staged rollout.

A blue/green swap (serve/pool.py, PR 8) is all-or-nothing: once the
shift starts, every replica ends up on vN+1 whether or not vN+1 is any
good. This module closes the loop the ROADMAP calls "self-driving
rollouts": a **canary stage** routes a configurable traffic fraction
to vN+1 on a subset of replicas, a :class:`CanaryMonitor` compares the
canary's live request windows against the incumbent's, and the verdict
drives the state machine automatically — promote to the full
replica-by-replica shift, or auto-rollback with the registry left
untouched. No human watches ``compare`` output during the rollout; the
comparison IS the rollout gate.

**Detector discipline.** Every detector runs the training-side
warmup→debounce→hysteresis state machine (:class:`obs.health.
DetectorState` — the PR 4 pattern, reused verbatim so the semantics of
"a breach must persist, then latch" cannot drift between the training
and serving health stacks). Eligibility gates on window sizes instead
of a warmup count: a detector never judges cohorts it has too few
samples to compare, and a smoke-scale canary ends before eligibility
rather than alerting on being small.

==================  ====================================================
``p99_p<P>``          per-priority tail latency: the canary cohort's
                      p99 over its rolling window vs the incumbent's,
                      judged as a ratio with an absolute floor (two
                      sub-ms p99s differing 3x are noise, not a
                      regression). THIS is the detector that catches a
                      canary degrading *only* the premium class while
                      the aggregate p99 stays flat — the exact
                      blindness the PR 10 attribution work exposed.
``unabsorbed``        the canary could not hold its assigned traffic
                      fraction: (sheds + incumbent fallbacks) over the
                      batches assigned to the canary cohort.
``error_rate``        canary engine-failure rate minus the
                      incumbent's (a broken artifact fails requests;
                      that is not load shedding and must not hide).
``fairness``          the canary degrades priorities UNEVENLY: max/min
                      over per-priority canary/incumbent p99 ratios.
``queue_share``       the canary turned queue-bound: its
                      dispatch/(dispatch+compute) share minus the
                      incumbent's, from the replica workers' measured
                      batch splits (obs/rtrace.py future timing).
``logit_drift``       the shadow-mirroring probe: sampled incumbent
                      batches are ALSO executed on the canary, the
                      incumbent's answer goes to the client, and the
                      logits are diffed off the hot path. Because
                      packed 1-bit inference is deterministic and
                      bitwise-exact (serve/engine.py
                      :func:`~bdbnn_tpu.serve.engine.
                      max_abs_logit_drift`), the threshold defaults to
                      EXACTLY ZERO — any drift is a real defect, a
                      quality gate no float-serving stack gets this
                      cheaply.
==================  ====================================================

**Decision rule.** Any fired (debounced) detector → ``rollback``, with
the detector as the recorded trigger. ``healthy_evals`` consecutive
clean evaluations with the canary having served at least
``min_samples`` requests → ``promote``. The observation budget
(``max_wait_s``) expiring first resolves conservatively: a canary that
never produced enough evidence is rolled back with trigger
``inconclusive`` — insufficient data is not a green light.

Stdlib-only (serving obs rule): the monitor consumes host floats and
counters; numpy appears only inside the pool's shadow comparator. The
whole episode — trigger, observation windows, per-detector evidence,
decision, rollback disposition — flows as ``canary`` events through
the injected ``on_event`` hook, and :meth:`CanaryMonitor.report` is
the nullable ``canary`` block of SLO verdict v5 that ``compare``
judges (``serve_canary_rollbacks``, ``serve_shadow_logit_drift_max``,
``serve_canary_promote_s``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from bdbnn_tpu.obs.health import DetectorState

# cohort labels — the monitor's windows and the pool's counters key on
# these, and the verdict block renders them verbatim
INCUMBENT = "incumbent"
CANARY = "canary"

# decision values evaluate()/conclude() return and report() records
OBSERVE = "observe"
PROMOTE = "promote"
ROLLBACK = "rollback"

# the non-detector trigger for a rollback forced by an expired
# observation budget: not enough evidence to promote is a rollback,
# never a default-open
INCONCLUSIVE = "inconclusive"


@dataclasses.dataclass(frozen=True)
class CanaryConfig:
    """Canary detector thresholds + the observation-loop knobs. Every
    field can be overridden from the CLI via
    ``--canary-threshold NAME=VALUE`` (the obs/health.py override
    pattern, validated at config time)."""

    # observation loop: how often the monitor evaluates, how many
    # consecutive clean evaluations promote, and the hard budget after
    # which an undecided canary rolls back as inconclusive
    eval_interval_s: float = 0.25
    healthy_evals: int = 4
    max_wait_s: float = 60.0
    # eligibility: a detector never compares windows with fewer than
    # this many samples on EITHER side (smoke-scale traffic ends the
    # canary as inconclusive instead of judging noise)
    min_samples: int = 20
    # debounce: a breach must persist this many consecutive eligible
    # evaluations before the detector fires (logit_drift is exempt —
    # the comparison is exact, one drifted sample is a real defect)
    debounce: int = 2
    # rolling window size per (cohort, priority) latency / batch-split
    # deque
    window: int = 512
    # p99_p<P>: canary p99 > ratio x incumbent p99 AND the absolute gap
    # exceeds the floor (sub-ms percentiles differing 3x are noise)
    p99_ratio: float = 2.0
    p99_floor_ms: float = 5.0
    # unabsorbed: (canary sheds + fallbacks) / canary-assigned batches
    unabsorbed_rate: float = 0.5
    # error_rate: canary failure rate minus incumbent failure rate
    error_rate_abs: float = 0.02
    # fairness: max/min over per-priority canary/incumbent p99 ratios
    fairness_ratio_max: float = 3.0
    # queue_share: canary dispatch share minus incumbent dispatch share
    queue_share_abs: float = 0.25
    # logit_drift: max |canary - incumbent| logit difference. ZERO by
    # default on purpose: packed inference is deterministic and
    # bitwise-exact, so any drift is a real defect (see
    # serve/engine.py max_abs_logit_drift)
    logit_drift_abs: float = 0.0


def apply_canary_overrides(
    cfg: CanaryConfig, specs: Sequence[str]
) -> CanaryConfig:
    """``("p99_ratio=3", "min_samples=10", ...)`` -> a new
    CanaryConfig. Unknown names and unparseable values raise
    ValueError at config time, not mid-rollout."""
    if not specs:
        return cfg
    fields = {f.name: f for f in dataclasses.fields(CanaryConfig)}
    updates: Dict[str, Any] = {}
    for spec in specs:
        name, sep, raw = spec.partition("=")
        name = name.strip()
        if not sep or name not in fields:
            raise ValueError(
                f"bad --canary-threshold {spec!r}: want NAME=VALUE "
                f"with NAME one of {sorted(fields)}"
            )
        typ = fields[name].type
        try:
            updates[name] = (
                int(raw) if typ in (int, "int") else float(raw)
            )
        except ValueError as e:
            raise ValueError(
                f"bad --canary-threshold {spec!r}: {e}"
            ) from None
    return dataclasses.replace(cfg, **updates)


def _p99(window) -> Optional[float]:
    # lazy: loadgen imports batching which imports rtrace; by any call
    # time the cycle is long resolved (the rtrace precedent)
    from bdbnn_tpu.serve.loadgen import _pct

    return _pct(sorted(window), 99.0)


class CanaryMonitor:
    """Live-verdict comparison of a canary cohort against the
    incumbent, driving the pool's canary state machine.

    Feeds (all thread-safe; writers are the HTTP handler, the replica
    workers and the shadow comparator thread):

    - :meth:`record_served` — one completed request's (priority,
      latency, answered-by version) from the front end; the version
      label rides the request future (obs/rtrace.py
      ``set_future_answered_by``), so a canary-assigned batch that
      FELL BACK to the incumbent counts as incumbent — cohort truth is
      who answered, never who was asked.
    - :meth:`record_batch` — one executed batch's measured
      (dispatch_ms, compute_ms) split from the replica worker.
    - :meth:`record_drift` — one shadow comparison's max-abs logit
      difference.

    :meth:`evaluate` (called by the pool's observation loop with its
    cohort counters) runs every detector through its
    :class:`~bdbnn_tpu.obs.health.DetectorState` and returns the
    decision; :meth:`report` is the verdict's ``canary`` block.
    """

    def __init__(
        self,
        cfg: Optional[CanaryConfig] = None,
        *,
        priorities: int = 3,
        on_event: Optional[Callable[..., Any]] = None,
    ):
        self.cfg = cfg or CanaryConfig()
        self.priorities = max(int(priorities), 1)
        self.on_event = on_event
        self._lock = threading.Lock()
        # episode identity + every rolling window and accumulator the
        # concurrent feeds write:
        # guarded-by: _lock: active, version_from, version_to, fraction,
        # guarded-by: _lock: canary_replicas, _lat, _disp, _comp, served,
        # guarded-by: _lock: drift_n, drift_max, evaluations, _clean_streak,
        # guarded-by: _lock: decision, trigger, _last_detectors, _t_armed,
        # guarded-by: _lock: _t_decided, _states
        self.active = False
        self.version_from: Optional[str] = None
        self.version_to: Optional[str] = None
        self.fraction: Optional[float] = None
        self.canary_replicas: Optional[List[int]] = None
        self._reset()

    def _reset(self) -> None:  # requires-lock: _lock
        cfg = self.cfg
        self._lat: Dict[Any, Any] = {}
        self._disp: Dict[str, Any] = {
            c: deque(maxlen=cfg.window) for c in (INCUMBENT, CANARY)
        }
        self._comp: Dict[str, Any] = {
            c: deque(maxlen=cfg.window) for c in (INCUMBENT, CANARY)
        }
        self.served = {INCUMBENT: 0, CANARY: 0}
        self.drift_n = 0
        self.drift_max: Optional[float] = None
        self.evaluations = 0
        self._clean_streak = 0
        self.decision: Optional[str] = None
        self.trigger: Optional[str] = None
        self._last_detectors: Dict[str, Dict[str, Any]] = {}
        self._t_armed: Optional[float] = None
        self._t_decided: Optional[float] = None
        names = [f"p99_p{p}" for p in range(self.priorities)] + [
            "unabsorbed", "error_rate", "fairness", "queue_share",
            "logit_drift",
        ]
        self._states = {
            name: DetectorState(
                0,
                # the drift comparison is exact — one drifted sample
                # is a real defect, never debounced away
                1 if name == "logit_drift" else cfg.debounce,
            )
            for name in names
        }

    def _emit(self, kind: str, **fields) -> None:
        if self.on_event is not None:
            try:
                self.on_event(kind, **fields)
            except Exception:
                pass  # telemetry must never take the rollout down

    # -- lifecycle -----------------------------------------------------

    def arm(
        self,
        *,
        version_from: str,
        version_to: str,
        fraction: float,
        replicas: Sequence[int],
    ) -> None:
        """Start one canary episode: clears every window and detector,
        records the cohort identity the feeds key on."""
        with self._lock:
            self._reset()
            self.active = True
            self.version_from = str(version_from)
            self.version_to = str(version_to)
            self.fraction = float(fraction)
            self.canary_replicas = [int(r) for r in replicas]
            self._t_armed = time.monotonic()

    def disarm(self) -> None:
        with self._lock:
            self.active = False

    # -- feeds ---------------------------------------------------------

    def _cohort(self, version: Optional[str]) -> Optional[str]:  # requires-lock: _lock
        if version is None:
            return None
        return CANARY if str(version) == self.version_to else INCUMBENT

    def record_served(
        self, priority: int, lat_ms: float, version: Optional[str]
    ) -> None:
        """One completed request. ``version`` is who ANSWERED (the
        future's answered-by label); None (single-engine path, no
        label) is ignored rather than guessed."""
        cohort = None
        with self._lock:
            if self.active:
                cohort = self._cohort(version)
            if cohort is None:
                return
            key = (cohort, int(priority))
            win = self._lat.get(key)
            if win is None:
                win = self._lat[key] = deque(maxlen=self.cfg.window)
            win.append(float(lat_ms))
            self.served[cohort] += 1

    def record_batch(
        self,
        version: Optional[str],
        dispatch_ms: float,
        compute_ms: float,
    ) -> None:
        """One executed batch's measured dispatch/compute split from
        the replica worker — the queue-share detector's feed. One
        sample per BATCH on purpose: the dispatch wait is a batch-level
        quantity (every request in the batch waited it together), so
        weighting by batch size would double-count the same wall."""
        with self._lock:
            if not self.active:
                return
            cohort = self._cohort(version)
            if cohort is None:
                return
            self._disp[cohort].append(float(dispatch_ms))
            self._comp[cohort].append(float(compute_ms))

    def record_drift(self, drift: Optional[float]) -> None:
        """One shadow comparison's max-abs logit difference (None =
        the pair was incomparable and is NOT a measurement)."""
        if drift is None:
            return
        with self._lock:
            if not self.active:
                return
            self.drift_n += 1
            d = float(drift)
            if self.drift_max is None or d > self.drift_max:
                self.drift_max = d

    # -- judgment ------------------------------------------------------

    def _detector_rows(  # requires-lock: _lock
        self, pool_counters: Optional[Dict[str, Dict[str, Any]]]
    ) -> Dict[str, Dict[str, Any]]:
        """One evidence row per detector: value, threshold, breach,
        eligible, recovered (the hysteresis re-arm signal) + the raw
        window evidence. Caller holds the lock
        (``# requires-lock: _lock`` on the def line above)."""
        cfg = self.cfg
        rows: Dict[str, Dict[str, Any]] = {}
        ratios: Dict[int, float] = {}
        for p in range(self.priorities):
            c_win = self._lat.get((CANARY, p)) or ()
            i_win = self._lat.get((INCUMBENT, p)) or ()
            eligible = (
                len(c_win) >= cfg.min_samples
                and len(i_win) >= cfg.min_samples
            )
            c99 = _p99(c_win)
            i99 = _p99(i_win)
            ratio = None
            breach = recovered = False
            if eligible and c99 is not None and i99 is not None:
                ratio = round(c99 / max(i99, 1e-9), 4)
                ratios[p] = ratio
                breach = (
                    ratio > cfg.p99_ratio
                    and (c99 - i99) > cfg.p99_floor_ms
                )
                recovered = ratio < 0.5 * cfg.p99_ratio
            rows[f"p99_p{p}"] = {
                "value": ratio,
                "threshold": cfg.p99_ratio,
                "breach": breach,
                "recovered": recovered,
                "eligible": eligible,
                "canary_p99_ms": c99,
                "incumbent_p99_ms": i99,
                "canary_n": len(c_win),
                "incumbent_n": len(i_win),
            }

        can_counts = (pool_counters or {}).get(CANARY) or {}
        assigned = int(can_counts.get("assigned_batches") or 0)
        unabsorbed = int(can_counts.get("sheds") or 0) + int(
            can_counts.get("fallbacks") or 0
        )
        eligible = assigned >= max(cfg.min_samples // 4, 4)
        value = round(unabsorbed / assigned, 4) if assigned else None
        rows["unabsorbed"] = {
            "value": value,
            "threshold": cfg.unabsorbed_rate,
            "breach": bool(
                eligible and value is not None
                and value > cfg.unabsorbed_rate
            ),
            "recovered": bool(
                value is not None and value < 0.5 * cfg.unabsorbed_rate
            ),
            "eligible": eligible,
            "assigned_batches": assigned,
            "unabsorbed_batches": unabsorbed,
        }

        def _cohort_total(cohort: str) -> int:
            counts = (pool_counters or {}).get(cohort) or {}
            return self.served[cohort] + int(
                counts.get("failed_requests") or 0
            )

        def _fail_rate(cohort: str) -> Optional[float]:
            counts = (pool_counters or {}).get(cohort) or {}
            failed = int(counts.get("failed_requests") or 0)
            total = self.served[cohort] + failed
            return failed / total if total else None

        c_rate = _fail_rate(CANARY)
        i_rate = _fail_rate(INCUMBENT)
        # BOTH cohorts need min_samples: a failure rate over a handful
        # of incumbent requests is not a comparison, and eligibility is
        # what keeps "promote" meaning "positively compared clean"
        eligible = (
            _cohort_total(CANARY) >= cfg.min_samples
            and _cohort_total(INCUMBENT) >= cfg.min_samples
        )
        value = (
            round(c_rate - i_rate, 4)
            if c_rate is not None and i_rate is not None
            else None
        )
        rows["error_rate"] = {
            "value": value,
            "threshold": cfg.error_rate_abs,
            "breach": bool(
                eligible and value is not None
                and value > cfg.error_rate_abs
            ),
            "recovered": bool(
                value is not None and value < 0.5 * cfg.error_rate_abs
            ),
            "eligible": eligible,
            "canary_fail_rate": c_rate,
            "incumbent_fail_rate": i_rate,
        }

        eligible = len(ratios) >= 2
        value = None
        if eligible:
            value = round(
                max(ratios.values()) / max(min(ratios.values()), 1e-9),
                4,
            )
        rows["fairness"] = {
            "value": value,
            "threshold": cfg.fairness_ratio_max,
            "breach": bool(
                eligible and value is not None
                and value > cfg.fairness_ratio_max
            ),
            "recovered": bool(
                value is not None
                and value < 0.5 * cfg.fairness_ratio_max
            ),
            "eligible": eligible,
            "per_priority_ratio": {
                str(p): r for p, r in sorted(ratios.items())
            },
        }

        def _share(cohort: str) -> Optional[float]:
            disp, comp = self._disp[cohort], self._comp[cohort]
            if len(disp) < max(cfg.min_samples // 4, 4):
                return None
            d = sum(disp) / len(disp)
            c = sum(comp) / max(len(comp), 1)
            total = d + c
            return d / total if total > 0 else None

        c_share = _share(CANARY)
        i_share = _share(INCUMBENT)
        eligible = c_share is not None and i_share is not None
        value = round(c_share - i_share, 4) if eligible else None
        rows["queue_share"] = {
            "value": value,
            "threshold": cfg.queue_share_abs,
            "breach": bool(
                eligible and value is not None
                and value > cfg.queue_share_abs
            ),
            "recovered": bool(
                value is not None and value < 0.5 * cfg.queue_share_abs
            ),
            "eligible": eligible,
            "canary_share": c_share,
            "incumbent_share": i_share,
        }

        rows["logit_drift"] = {
            "value": self.drift_max,
            "threshold": cfg.logit_drift_abs,
            "breach": bool(
                self.drift_n
                and self.drift_max is not None
                and self.drift_max > cfg.logit_drift_abs
            ),
            "recovered": False,  # exact comparisons never "recover"
            "eligible": self.drift_n > 0,
            "compared": self.drift_n,
        }
        return rows

    def evaluate(
        self,
        pool_counters: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> Dict[str, Any]:
        """One observation-loop tick: run every detector through its
        state machine and return ``{"decision", "trigger",
        "detectors"}``. The decision latches — once promoted or rolled
        back the monitor keeps answering the same verdict."""
        with self._lock:
            if self.decision is not None:
                return {
                    "decision": self.decision,
                    "trigger": self.trigger,
                    "detectors": dict(self._last_detectors),
                }
            self.evaluations += 1
            rows = self._detector_rows(pool_counters)
            fired: List[str] = []
            any_breach = False
            any_eligible = False
            for name, row in rows.items():
                st = self._states[name]
                row["fired"] = False
                if not row["eligible"]:
                    continue
                any_eligible = True
                any_breach = any_breach or row["breach"]
                if st.update(row["breach"], row["recovered"]):
                    row["fired"] = True
                    fired.append(name)
            decision = OBSERVE
            trigger = None
            if fired:
                decision, trigger = ROLLBACK, fired[0]
            else:
                # the promote streak counts only evaluations where at
                # least one detector actually COMPARED the cohorts —
                # "nothing was eligible" is absence of evidence, and
                # insufficient data must never accumulate into a green
                # light. A raw (undebounced) breach resets the streak:
                # "clean" means clean, not "not yet persistent".
                if any_breach:
                    self._clean_streak = 0
                elif any_eligible:
                    self._clean_streak += 1
                if (
                    self._clean_streak >= self.cfg.healthy_evals
                    and self.served[CANARY] >= self.cfg.min_samples
                ):
                    decision = PROMOTE
            self._last_detectors = rows
            if decision != OBSERVE:
                self.decision, self.trigger = decision, trigger
                self._t_decided = time.monotonic()
            evaluation = self.evaluations
            clean = self._clean_streak
        self._emit(
            "canary",
            phase="evaluate",
            evaluation=evaluation,
            decision=decision,
            trigger=trigger,
            clean_streak=clean,
            canary_served=self.served[CANARY],
            incumbent_served=self.served[INCUMBENT],
            detectors={
                name: {
                    k: row.get(k)
                    for k in (
                        "value", "threshold", "breach", "fired",
                        "eligible",
                    )
                }
                for name, row in rows.items()
            },
        )
        return {
            "decision": decision,
            "trigger": trigger,
            "detectors": rows,
        }

    def conclude(self, reason: str = "timeout") -> Dict[str, Any]:
        """Resolve an undecided canary at the observation budget:
        promote only when the evidence is positively sufficient (the
        canary served enough and the latest evaluation was clean);
        anything less rolls back as ``inconclusive`` — insufficient
        data is not a green light."""
        with self._lock:
            if self.decision is None:
                healthy = (
                    self._clean_streak >= 1
                    and self.served[CANARY] >= self.cfg.min_samples
                )
                self.decision = PROMOTE if healthy else ROLLBACK
                self.trigger = None if healthy else INCONCLUSIVE
                self._t_decided = time.monotonic()
            decision, trigger = self.decision, self.trigger
        self._emit(
            "canary", phase="decision", decision=decision,
            trigger=trigger, reason=reason,
            evaluations=self.evaluations,
        )
        return {
            "decision": decision,
            "trigger": trigger,
            "detectors": dict(self._last_detectors),
        }

    # -- reporting -----------------------------------------------------

    def live(self) -> Optional[Dict[str, Any]]:
        """The compact live snapshot ``/statsz`` serves while an
        episode is armed (None otherwise): state, fraction, served
        counts, drift so far, and each detector's latest status."""
        with self._lock:
            if not self.active:
                return None
            return {
                "state": self.decision or OBSERVE,
                "trigger": self.trigger,
                "fraction": self.fraction,
                "version_from": self.version_from,
                "version_to": self.version_to,
                "evaluations": self.evaluations,
                "served": dict(self.served),
                "drift_compared": self.drift_n,
                "max_abs_drift": self.drift_max,
                "detectors": {
                    name: {
                        k: row.get(k)
                        for k in ("value", "breach", "fired", "eligible")
                    }
                    for name, row in self._last_detectors.items()
                },
            }

    def shadow_block(
        self, pool_shadow: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """The ``shadow`` sub-block: mirror/compare accounting from the
        pool merged with the drift the monitor actually judged."""
        with self._lock:
            block = {
                "mirrored": (pool_shadow or {}).get("mirrored", 0),
                "compared": self.drift_n,
                "skipped": (pool_shadow or {}).get("skipped", 0),
                "failed": (pool_shadow or {}).get("failed", 0),
                "max_abs_drift": self.drift_max,
            }
        return block

    def report(
        self, pool_shadow: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """The verdict's nullable ``canary`` block (SLO verdict v5):
        the whole episode's evidence in one strict-JSON dict."""
        with self._lock:
            observe_s = None
            if self._t_armed is not None:
                end = self._t_decided or time.monotonic()
                observe_s = round(end - self._t_armed, 3)
            # the verdict carries the FULL evidence rows (window
            # percentiles, sample counts, cohort rates) — the episode
            # must be auditable from the verdict alone; the compact
            # value/threshold/fired view is the evaluate events' job
            detectors = {
                name: {k: v for k, v in row.items() if k != "recovered"}
                for name, row in self._last_detectors.items()
            }
            rec = {
                "fraction": self.fraction,
                "replicas_canary": self.canary_replicas,
                "version_from": self.version_from,
                "version_to": self.version_to,
                "decision": self.decision,
                "trigger": self.trigger,
                "rollbacks": 1 if self.decision == ROLLBACK else 0,
                "evaluations": self.evaluations,
                "observe_s": observe_s,
                "served": dict(self.served),
                "detectors": detectors,
            }
        rec["shadow"] = self.shadow_block(pool_shadow)
        return rec


def assign_canary(seed: int, seq: int, fraction: float) -> bool:
    """Deterministic seeded cohort assignment for batch ``seq``: the
    same (seed, seq) always lands in the same cohort — splitmix64 over
    the batch sequence number, the rtrace sampling construction — so a
    canary episode's traffic split is reproducible and contention-free
    (no RNG state in the dispatch path)."""
    from bdbnn_tpu.obs.rtrace import _splitmix64

    if fraction <= 0.0:
        return False
    return (_splitmix64(int(seed) ^ int(seq)) % 1_000_000) < int(
        float(fraction) * 1_000_000
    )


__all__ = [
    "CANARY",
    "INCONCLUSIVE",
    "INCUMBENT",
    "OBSERVE",
    "PROMOTE",
    "ROLLBACK",
    "CanaryConfig",
    "CanaryMonitor",
    "apply_canary_overrides",
    "assign_canary",
]
