"""Serving subsystem: frozen-artifact export + batched inference.

Training's other half. Four modules, composing bottom-up:

- :mod:`bdbnn_tpu.serve.export`   — freeze a training checkpoint into a
  deployment artifact (weights binarized once to packed sign +
  per-channel alpha, BatchNorm folded to scale/bias, all training-only
  state stripped, strict-JSON ``artifact.json`` provenance manifest)
- :mod:`bdbnn_tpu.serve.engine`   — the inference runtime: eval-mode
  forward AOT-compiled per batch-size bucket at startup, requests
  padded up to the bucket (no first-request compile stall)
- :mod:`bdbnn_tpu.serve.batching` — dynamic micro-batcher: bounded
  request queue with deadline coalescing, explicit load shedding, and
  latched-flag graceful drain (stdlib-only, engine injected)
- :mod:`bdbnn_tpu.serve.loadgen`  — closed/open-loop (Poisson) load
  generator producing the strict-JSON SLO verdict, the ``serve-bench``
  orchestration that wires everything to a run dir (manifest +
  ``serve`` events) the obs/ tooling already understands, plus the
  traffic-shaped arrival processes (diurnal / flash-crowd /
  heavy-tail / slow-client) and the raw-socket HTTP load generator
  that drives the network front end
- :mod:`bdbnn_tpu.serve.admission` — per-tenant token-bucket quotas +
  the admit / over-quota / draining decision taxonomy (stdlib-only)
- :mod:`bdbnn_tpu.serve.http`     — the network front end: stdlib
  asyncio HTTP/1.1 over the batcher with priority classes
  (``x-priority`` header → per-class bounded queues), per-tenant
  admission control (429 vs 503), /healthz + /readyz wired to AOT
  warmup + the drain latch, the ``/admin`` replica/swap operator
  routes, and the ``serve-http`` orchestration
- :mod:`bdbnn_tpu.serve.pool`     — the replica pool: one AOT-warmed
  engine per mesh device behind a least-loaded dispatcher with
  per-replica bounded queues, wedge detection + routed-around
  restarts, and zero-downtime blue/green artifact hot-swap
  (stdlib-only; engines injected)
- :mod:`bdbnn_tpu.serve.registry` — the versioned artifact registry:
  immutable published versions with a verified digest chain
  (index → artifact.json → weights.npz) + provenance, the store swap
  targets resolve from
- :mod:`bdbnn_tpu.serve.fleet`    — the cross-host fleet router: N
  serve-http hosts behind one health-routed listener (shared
  warmup→debounce→hysteresis probe discipline, least-occupancy
  dispatch), bounded retry-with-backoff host-failure tolerance
  (relay-vs-retry preserving the shed taxonomy), digest-verified
  registry replication and host-by-host fleet blue/green
  (stdlib-only; the hosts own the engines)
- :mod:`bdbnn_tpu.serve.canary`   — self-driving rollouts: the canary
  stage's live-verdict monitor (warmup→debounce→hysteresis detectors
  over per-cohort request windows, obs/health.py discipline) whose
  decision auto-promotes or auto-rolls-back a staged rollout, plus
  the exact shadow logit-drift probe packed determinism makes free
  (stdlib-only)

CLI surface: ``export`` / ``predict`` / ``serve-bench`` /
``serve-http`` / ``serve-fleet`` (``bdbnn_tpu.cli``). Import of this
package root stays light — the modules lazy-import jax where they
need it, so the batcher, admission, HTTP, fleet and verdict tooling
all work backend-free.
"""

from __future__ import annotations

from bdbnn_tpu.serve.admission import AdmissionController, TokenBucket
from bdbnn_tpu.serve.batching import LoadShedError, MicroBatcher
from bdbnn_tpu.serve.canary import (
    CanaryConfig,
    CanaryMonitor,
    apply_canary_overrides,
)
from bdbnn_tpu.serve.export import (
    ARTIFACT_NAME,
    WEIGHTS_NAME,
    export_artifact,
    load_artifact_variables,
    read_artifact,
)
from bdbnn_tpu.serve.http import HttpFrontEnd, run_serve_http
from bdbnn_tpu.serve.pool import (
    PoolAdmin,
    Replica,
    ReplicaPool,
    make_engine_runner_factory,
)
from bdbnn_tpu.serve.registry import ArtifactRegistry
from bdbnn_tpu.serve.loadgen import (
    SCENARIOS,
    VERDICT_NAME,
    HttpLoadGenerator,
    LoadGenerator,
    build_schedule,
    percentile,
    run_serve_bench,
    slo_verdict,
)

__all__ = [
    "ARTIFACT_NAME",
    "SCENARIOS",
    "VERDICT_NAME",
    "WEIGHTS_NAME",
    "AdmissionController",
    "ArtifactRegistry",
    "CanaryConfig",
    "CanaryMonitor",
    "HttpFrontEnd",
    "HttpLoadGenerator",
    "LoadGenerator",
    "LoadShedError",
    "MicroBatcher",
    "PoolAdmin",
    "Replica",
    "ReplicaPool",
    "TokenBucket",
    "apply_canary_overrides",
    "build_schedule",
    "make_engine_runner_factory",
    "export_artifact",
    "load_artifact_variables",
    "percentile",
    "read_artifact",
    "run_serve_bench",
    "run_serve_http",
    "slo_verdict",
]
