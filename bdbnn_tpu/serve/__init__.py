"""Serving subsystem: frozen-artifact export + batched inference.

Training's other half. Four modules, composing bottom-up:

- :mod:`bdbnn_tpu.serve.export`   — freeze a training checkpoint into a
  deployment artifact (weights binarized once to packed sign +
  per-channel alpha, BatchNorm folded to scale/bias, all training-only
  state stripped, strict-JSON ``artifact.json`` provenance manifest)
- :mod:`bdbnn_tpu.serve.engine`   — the inference runtime: eval-mode
  forward AOT-compiled per batch-size bucket at startup, requests
  padded up to the bucket (no first-request compile stall)
- :mod:`bdbnn_tpu.serve.batching` — dynamic micro-batcher: bounded
  request queue with deadline coalescing, explicit load shedding, and
  latched-flag graceful drain (stdlib-only, engine injected)
- :mod:`bdbnn_tpu.serve.loadgen`  — closed/open-loop (Poisson) load
  generator producing the strict-JSON SLO verdict, plus the
  ``serve-bench`` orchestration that wires everything to a run dir
  (manifest + ``serve`` events) the obs/ tooling already understands

CLI surface: ``export`` / ``predict`` / ``serve-bench``
(``bdbnn_tpu.cli``). Import of this package root stays light — the
modules lazy-import jax where they need it, so the batcher and verdict
tooling work backend-free.
"""

from __future__ import annotations

from bdbnn_tpu.serve.batching import LoadShedError, MicroBatcher
from bdbnn_tpu.serve.export import (
    ARTIFACT_NAME,
    WEIGHTS_NAME,
    export_artifact,
    load_artifact_variables,
    read_artifact,
)
from bdbnn_tpu.serve.loadgen import (
    VERDICT_NAME,
    LoadGenerator,
    percentile,
    run_serve_bench,
    slo_verdict,
)

__all__ = [
    "ARTIFACT_NAME",
    "VERDICT_NAME",
    "WEIGHTS_NAME",
    "LoadGenerator",
    "LoadShedError",
    "MicroBatcher",
    "export_artifact",
    "load_artifact_variables",
    "percentile",
    "read_artifact",
    "run_serve_bench",
    "slo_verdict",
]
