"""Artifact registry: versioned export dirs with digests + provenance.

A rollout needs more than "a directory with weights in it": the swap
orchestration (serve/pool.py) must be able to name a version, prove the
bytes it is about to load are the bytes that were published, and record
where they came from. The registry is a directory of immutable
versioned copies of export artifacts plus one strict-JSON index:

::

    registry/
      registry.json        # the index: one entry per version
      v0001/               # artifact.json + weights.npz (a full copy)
      v0002/
      ...

Each index entry carries:

- ``version``          monotonically increasing int (v0001, v0002, ...)
- ``path``             the version dir, relative to the registry root
- ``artifact_sha256``  digest of the version's ``artifact.json`` bytes
- ``weights_sha256``   the weights digest the artifact manifest records
  (the export already chains artifact.json -> weights.npz; the registry
  adds the outer link index -> artifact.json, so the whole chain
  index -> manifest -> weights is verifiable)
- ``provenance``       arch/dataset/config-hash/recipe + the recorded
  eval accuracy, copied from the artifact manifest at publish time —
  what ``GET /admin/replicas`` and the swap events report per version

``publish`` copies the artifact in (tmp dir + atomic rename, so a
crashed publish never leaves a half-copied version visible in the
index); ``resolve`` verifies the digest chain before handing the path
to an engine. Tampered or torn versions fail loudly at resolve, never
at serve time. Stdlib-only: registries are read and written with no
JAX backend.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

from bdbnn_tpu.serve.export import ARTIFACT_NAME, WEIGHTS_NAME, _file_sha256

REGISTRY_NAME = "registry.json"
REGISTRY_SCHEMA_VERSION = 1


def _version_dirname(version: int) -> str:
    return f"v{version:04d}"


def parse_version(spec) -> int:
    """``v0003`` / ``v3`` / ``3`` -> 3 — THE version-string parser,
    shared by the CLI, the serve-http artifact/swap-target resolution
    and the admin endpoint, so a malformed spec fails the same
    everywhere (ValueError with a pointed message, never a stray
    int() traceback or a silently over-stripped ``vv7``)."""
    import re

    m = re.fullmatch(r"v?(\d+)", str(spec).strip())
    if m is None:
        raise ValueError(
            f"not a registry version: {spec!r} (want vNNNN or an integer)"
        )
    return int(m.group(1))


def looks_like_version(spec) -> bool:
    """True when ``spec`` parses as a registry version — the decision
    serve-http uses to tell a version argument from an artifact dir."""
    try:
        parse_version(spec)
        return True
    except ValueError:
        return False


class ArtifactRegistry:
    """The versioned artifact store driving blue/green swaps."""

    def __init__(self, root: str):
        self.root = root

    # -- index i/o -----------------------------------------------------

    def _index_path(self) -> str:
        return os.path.join(self.root, REGISTRY_NAME)

    def _read_index(self) -> Dict[str, Any]:
        path = self._index_path()
        if not os.path.exists(path):
            return {"schema": REGISTRY_SCHEMA_VERSION, "entries": []}
        with open(path) as f:
            return json.load(f)

    def _write_index(self, index: Dict[str, Any]) -> None:
        from bdbnn_tpu.obs.events import jsonsafe

        os.makedirs(self.root, exist_ok=True)
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(jsonsafe(index), f, indent=2, sort_keys=True)
        os.replace(tmp, self._index_path())

    @contextlib.contextmanager
    def _publish_lock(
        self, timeout_s: float = 30.0, stale_s: float = 120.0
    ):
        """Inter-process mutual exclusion for publish: the index write
        is read-modify-write over the WHOLE entry list, so two
        concurrent publishers without a lock would each copy a version
        dir correctly and then one would overwrite the other's index
        entry — a fully-published version resolve() can never find.
        O_CREAT|O_EXCL on a sidecar lock file is atomic on every
        filesystem the registry targets; a lock older than ``stale_s``
        is presumed abandoned by a crashed publisher and stolen."""
        os.makedirs(self.root, exist_ok=True)
        path = self._index_path() + ".lock"
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, str(os.getpid()).encode())
                os.close(fd)
                break
            except FileExistsError:
                try:
                    age = time.time() - os.stat(path).st_mtime
                except OSError:
                    continue  # holder released between open and stat
                if age > stale_s:
                    # Steal by atomic rename: of N concurrent stealers
                    # exactly ONE wins (the rest get OSError and re-enter
                    # the wait loop). Unlink-based stealing let two
                    # processes both observe the stale lock, both unlink
                    # (the second unlinking the first's FRESH lock) and
                    # both enter the critical section — the exact lost-
                    # index-entry failure the lock exists to prevent.
                    stolen = f"{path}.stale.{os.getpid()}"
                    try:
                        os.rename(path, stolen)
                    except OSError:
                        continue  # another stealer won, or holder released
                    with contextlib.suppress(OSError):
                        os.unlink(stolen)
                    continue
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"registry {self.root!r}: publish lock {path!r} "
                        f"held for {age:.1f}s — another publish is "
                        "running (or crashed; it is stolen after "
                        f"{stale_s:.0f}s)"
                    )
                time.sleep(0.05)
        try:
            yield
        finally:
            with contextlib.suppress(OSError):
                os.unlink(path)

    # -- queries -------------------------------------------------------

    def entries(self) -> List[Dict[str, Any]]:
        return list(self._read_index()["entries"])

    def get(self, version: int) -> Optional[Dict[str, Any]]:
        for e in self.entries():
            if e["version"] == int(version):
                return e
        return None

    def latest(self) -> Optional[Dict[str, Any]]:
        entries = self.entries()
        return max(entries, key=lambda e: e["version"]) if entries else None

    # -- publish / resolve ---------------------------------------------

    def publish(
        self, artifact_dir: str, lock_timeout_s: float = 30.0
    ) -> Dict[str, Any]:
        """Copy ``artifact_dir`` into the registry as the next version;
        returns the new index entry. The version dir lands via tmp-dir +
        atomic rename BEFORE the index references it, so a crash at any
        point leaves either no trace or a fully-copied version.
        Concurrent publishers serialize on a lock file so neither's
        index entry is lost."""
        art_path = os.path.join(artifact_dir, ARTIFACT_NAME)
        if not os.path.exists(art_path):
            raise FileNotFoundError(
                f"{artifact_dir!r} holds no {ARTIFACT_NAME} — not an "
                "export artifact"
            )
        with open(art_path) as f:
            manifest = json.load(f)
        # verify the inner link before publishing: a torn export must
        # not become an immutable "good" version
        want = manifest.get("weights_sha256")
        wpath = os.path.join(artifact_dir, WEIGHTS_NAME)
        if want and _file_sha256(wpath) != want:
            raise RuntimeError(
                f"{artifact_dir!r}: weights do not match the sha256 its "
                f"{ARTIFACT_NAME} records — refusing to publish a torn "
                "artifact"
            )

        # Stage the copy OUTSIDE the lock, into a per-pid tmp dir: the
        # copytree is the unbounded part of publish (big artifact, slow
        # disk), and holding the lock through it would let the staleness
        # steal in _publish_lock evict a live-but-slow publisher —
        # readmitting the two-writers race the lock exists to prevent.
        # Inside the lock only version assignment, one same-filesystem
        # rename and the index write remain, all fast and bounded.
        os.makedirs(self.root, exist_ok=True)
        staging = os.path.join(
            self.root,
            f".publish.tmp.{os.getpid()}.{threading.get_ident()}",
        )
        if os.path.exists(staging):
            shutil.rmtree(staging)
        shutil.copytree(artifact_dir, staging)
        try:
            with self._publish_lock(timeout_s=lock_timeout_s):
                index = self._read_index()
                # next version = 1 + max over the INDEX and the DISK: a
                # crash between the version-dir rename and the index
                # write leaves an orphan vNNNN dir with no entry, and
                # reusing its number would make every later publish fail
                # on the non-empty rename target
                disk_versions = []
                for name in os.listdir(self.root):
                    if (
                        len(name) == 5 and name[0] == "v"
                        and name[1:].isdigit()
                        and os.path.isdir(os.path.join(self.root, name))
                    ):
                        disk_versions.append(int(name[1:]))
                version = 1 + max(
                    [e["version"] for e in index["entries"]]
                    + disk_versions,
                    default=0,
                )
                dirname = _version_dirname(version)
                dest = os.path.join(self.root, dirname)
                os.replace(staging, dest)

                entry = {
                    "version": version,
                    "path": dirname,
                    "published_unix": round(time.time(), 3),
                    "source": os.path.abspath(artifact_dir),
                    "artifact_sha256": _file_sha256(
                        os.path.join(dest, ARTIFACT_NAME)
                    ),
                    "weights_sha256": want,
                    "provenance": {
                        "arch": manifest.get("arch"),
                        "dataset": manifest.get("dataset"),
                        "config_hash": (
                            manifest.get("provenance", {}).get(
                                "config_hash"
                            )
                        ),
                        "recipe": (
                            manifest.get("provenance", {}).get("recipe")
                        ),
                        "checkpoint_acc1": (
                            manifest.get("eval", {}).get("checkpoint_acc1")
                        ),
                    },
                }
                index["entries"].append(entry)
                self._write_index(index)
                return entry
        finally:
            # a failed publish (lock timeout, rename error) must not
            # leave its staging dir behind; a successful one already
            # renamed it away
            if os.path.exists(staging):
                shutil.rmtree(staging, ignore_errors=True)

    def pull(
        self,
        remote_root: str,
        version: Optional[int] = None,
        lock_timeout_s: float = 30.0,
    ) -> List[Dict[str, Any]]:
        """Replicate versions from a REMOTE registry into this one —
        the fleet's publish-time replication primitive (every serving
        host pulls the version it is about to swap to, so a rollout
        never trusts a path it did not verify). ``version`` limits the
        pull to one version; None pulls every remote version absent
        locally. Returns the list of local index entries written.

        The digest chain is verified TWICE: the remote side resolves
        through :meth:`resolve` (index -> artifact.json -> weights.npz
        against the remote index), and the staged local copy is
        re-hashed against the remote entry's recorded digests before
        the rename — a copy torn mid-transfer (short read, full disk)
        fails HERE and leaves the local registry untouched. Version
        numbers and digests are preserved verbatim, so every host's
        registry resolves version N to byte-identical artifacts."""
        remote = ArtifactRegistry(remote_root)
        if version is not None:
            entry = remote.get(int(version))
            if entry is None:
                known = [e["version"] for e in remote.entries()]
                raise KeyError(
                    f"remote registry {remote_root!r} has no version "
                    f"{version} (known: {known})"
                )
            wanted = [entry]
        else:
            # EVERY remote entry is considered: versions already local
            # are digest-compared below (identical -> skipped, diverged
            # -> a registry fork that must fail loudly)
            wanted = list(remote.entries())
        pulled: List[Dict[str, Any]] = []
        for entry in sorted(wanted, key=lambda e: e["version"]):
            v = int(entry["version"])
            local = self.get(v)
            if local is not None:
                # idempotent re-pull of an identical version; a DIVERGED
                # version number is a registry fork and must fail loudly
                if (
                    local.get("artifact_sha256")
                    != entry.get("artifact_sha256")
                    or local.get("weights_sha256")
                    != entry.get("weights_sha256")
                ):
                    raise RuntimeError(
                        f"pull: local version {v} exists with DIFFERENT "
                        f"digests than {remote_root!r}'s — the registries "
                        "have forked; refusing to overwrite"
                    )
                continue
            src = remote.resolve(v)  # remote-side digest verification
            os.makedirs(self.root, exist_ok=True)
            staging = os.path.join(
                self.root,
                f".pull.tmp.{os.getpid()}.{threading.get_ident()}",
            )
            if os.path.exists(staging):
                shutil.rmtree(staging)
            try:
                shutil.copytree(src, staging)
                # verify the STAGED copy against the remote entry: a
                # torn copy must never become a local "good" version
                if (
                    _file_sha256(os.path.join(staging, ARTIFACT_NAME))
                    != entry["artifact_sha256"]
                ):
                    raise RuntimeError(
                        f"pull: staged copy of version {v} does not "
                        f"match {ARTIFACT_NAME}'s published digest — "
                        "torn transfer; local registry untouched"
                    )
                if entry.get("weights_sha256") and (
                    _file_sha256(os.path.join(staging, WEIGHTS_NAME))
                    != entry["weights_sha256"]
                ):
                    raise RuntimeError(
                        f"pull: staged copy of version {v} does not "
                        "match the published weights digest — torn "
                        "transfer; local registry untouched"
                    )
                with self._publish_lock(timeout_s=lock_timeout_s):
                    index = self._read_index()
                    if any(
                        e["version"] == v for e in index["entries"]
                    ):
                        continue  # a concurrent puller won; theirs verified
                    dest = os.path.join(self.root, _version_dirname(v))
                    os.replace(staging, dest)
                    new_entry = {
                        **entry,
                        "path": _version_dirname(v),
                        "pulled_from": os.path.abspath(remote_root),
                        "pulled_unix": round(time.time(), 3),
                    }
                    index["entries"].append(new_entry)
                    index["entries"].sort(key=lambda e: e["version"])
                    self._write_index(index)
                    pulled.append(new_entry)
            finally:
                if os.path.exists(staging):
                    shutil.rmtree(staging, ignore_errors=True)
        return pulled

    def resolve(self, version: int) -> str:
        """Verified absolute path of a version's artifact dir: the index
        entry's recorded digests must match the bytes on disk (both the
        outer index -> artifact.json link and the inner artifact.json ->
        weights.npz link), so a tampered or torn version fails HERE,
        before an engine ever maps its weights."""
        entry = self.get(version)
        if entry is None:
            known = [e["version"] for e in self.entries()]
            raise KeyError(
                f"registry {self.root!r} has no version {version} "
                f"(known: {known})"
            )
        dest = os.path.join(self.root, entry["path"])
        art_path = os.path.join(dest, ARTIFACT_NAME)
        if _file_sha256(art_path) != entry["artifact_sha256"]:
            raise RuntimeError(
                f"registry version {version}: {ARTIFACT_NAME} does not "
                "match the digest recorded at publish — the version dir "
                "was modified after publish; republish instead of editing"
            )
        if entry.get("weights_sha256"):
            if (
                _file_sha256(os.path.join(dest, WEIGHTS_NAME))
                != entry["weights_sha256"]
            ):
                raise RuntimeError(
                    f"registry version {version}: weights do not match "
                    "the digest recorded at publish"
                )
        return os.path.abspath(dest)

    def label(self, version: int) -> str:
        """The display label swap/replica events and the verdict use."""
        return _version_dirname(int(version))


__all__ = [
    "REGISTRY_NAME",
    "REGISTRY_SCHEMA_VERSION",
    "ArtifactRegistry",
    "looks_like_version",
    "parse_version",
]
