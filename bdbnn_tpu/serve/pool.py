"""Replica pool: data-parallel serving over the device mesh + hot swap.

One engine on one device serves one device's worth of traffic. This
module fans the serving stack out: one :class:`Replica` per mesh device
(each owning its own AOT-warmed engine, bounded work queue and worker
thread) behind one dispatcher, so the front micro-batcher's coalesced
batches execute on N devices concurrently. Four responsibilities:

1. **Dispatch** — :meth:`ReplicaPool.submit` places one coalesced batch
   on the least-loaded READY replica (queue depth + busy flag). The
   batcher already dequeued strict-priority, so batch ARRIVAL order
   preserves priority; least-loaded placement preserves it across
   replicas (no batch waits behind a deep queue while another replica
   idles). Per-replica queues are bounded: when every candidate is
   full, ``submit`` raises :class:`LoadShedError` — the same explicit
   rejection contract as the front batcher, one layer down.
2. **Health** — a monitor thread watches per-replica heartbeats: a
   worker wedged in its runner past ``wedge_timeout_s`` (or a dead
   worker thread) marks the replica UNHEALTHY, its queued (unstarted)
   batches are re-dispatched to healthy peers, and a fresh worker is
   spawned (generation-tagged, so the wedged thread retires itself
   when — if — its stuck call returns, and its in-flight batch is still
   ANSWERED, never dropped). The dispatcher routes around unhealthy
   replicas the whole time.
3. **Blue/green swap** — :meth:`swap` rolls the pool onto a new
   artifact version with zero requests dropped and zero shed caused by
   the swap itself: the new version's runners are ALL built and
   AOT-warmed first (the standby set — cheap, because 1-bit + alpha
   artifacts are ~7x smaller than dense weights), then traffic shifts
   replica-by-replica (shifting replica leaves the dispatch set, its
   accepted work finishes on vN, its runner pointer swaps, it rejoins
   serving vN+1) while the rest of the pool absorbs the load. Every
   request is answered by exactly one version; the pool ledger records
   which (``completed_by_version``). :meth:`canary_swap` extends the
   same machine with a CANARY stage (serve/canary.py): a seeded
   traffic fraction routes to vN+1 on a replica subset, sampled
   incumbent batches mirror onto it for an exact logit-drift probe,
   and the live-verdict monitor decides — promote into the full shift
   above, or auto-rollback (vN restored, registry untouched).
4. **Drain** — the PR 5/7 latched-flag contract one layer down: after
   :meth:`drain` no batch enters a replica queue, every queued batch is
   executed and answered, then workers exit.

Stdlib-only: runners are injected callables (the real path binds
:class:`bdbnn_tpu.serve.engine.InferenceEngine` instances placed on
their mesh devices via :func:`make_engine_runner_factory`), so the
dispatcher, health and swap machinery — and their tests — never need a
JAX backend. Telemetry flows through an injected ``on_event`` hook
(``replica`` and ``swap`` event kinds, obs/events.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

from bdbnn_tpu.obs.rtrace import set_future_answered_by, set_future_timing
from bdbnn_tpu.serve.batching import LoadShedError

# replica states: dispatchable is READY only
WARMING = "warming"
READY = "ready"
SHIFTING = "shifting"  # blue/green: leaving the dispatch set to swap
UNHEALTHY = "unhealthy"
STOPPED = "stopped"

# swap states (the admin endpoint's status machine)
SWAP_IDLE = "idle"
SWAP_WARMING = "warming"
SWAP_SHIFTING = "shifting"
SWAP_DONE = "done"
SWAP_FAILED = "failed"
# the canary stage's additions (serve/canary.py): a rollout may now
# pause in an observation state and resolve to a rollback — a terminal
# state that is NOT a failure (vN kept serving by design, registry
# untouched)
SWAP_CANARY_WARMING = "canary_warming"
SWAP_CANARY = "canary"
SWAP_ROLLING_BACK = "rolling_back"
SWAP_ROLLED_BACK = "rolled_back"

# the states in which a swap is no longer in flight — the fleet
# router's host-by-host shift (serve/fleet.py) polls each host's
# /admin/swap until its state lands here before touching the NEXT
# host, so a rollout never takes two hosts out of dispatch at once.
# One source of truth: a new in-flight state added above must be
# deliberately excluded here or the fleet would shift early.
SWAP_TERMINAL_STATES = frozenset({
    SWAP_IDLE, SWAP_DONE, SWAP_FAILED, SWAP_ROLLED_BACK, "rejected",
})


class _Work:
    __slots__ = ("payloads", "future", "t_enqueue", "shadow")

    def __init__(self, payloads, shadow: bool = False):
        self.payloads = payloads
        self.future: Future = Future()
        # perf_counter, matching the request tracer's clock: the
        # dispatch-wait span (submit -> worker pickup) is handed back
        # on the batch Future (obs/rtrace.py) and must never mix clock
        # bases with the batcher's stamps
        self.t_enqueue = time.perf_counter()
        # a shadow-mirror duplicate (serve/canary.py): executed for the
        # logit-drift probe only — excluded from every serving ledger
        # (batches/completed/answered_by), or the verdict's identity
        # "answered_by sums to requests_completed" would double-count
        self.shadow = shadow


class Replica:
    """One engine's worth of serving capacity: a bounded batch queue and
    a worker thread executing ``runner(payloads) -> results`` — with a
    heartbeat (``busy_since``) the pool's health monitor reads."""

    def __init__(
        self,
        rid: int,
        runner: Callable[[List[Any]], Any],
        *,
        device: str = "",
        version: str = "v0",
        max_queue_batches: int = 8,
    ):
        self.rid = int(rid)
        self.device = str(device)
        self.version = str(version)
        self.max_queue_batches = int(max_queue_batches)
        self._runner = runner  # guarded-by: _lock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()  # guarded-by: _lock
        self.state = READY  # guarded-by: _lock
        # canary cohort membership (serve/canary.py): while a canary
        # stage is active the dispatcher routes the canary traffic
        # fraction to replicas with this flag set; a health restart
        # preserves it (the runner — and therefore the version — is
        # unchanged by a restart)
        self.canary = False  # guarded-by: _lock
        # monotonic timestamp of the batch currently executing (None =
        # idle) — the wedge detector's heartbeat
        self.busy_since: Optional[float] = None  # guarded-by: _lock
        # generation tag: a restart bumps it; a worker observing a
        # newer generation retires itself instead of double-consuming
        self._gen = 0  # guarded-by: _lock
        # guarded-by: _lock: version, batches, completed, restarts
        self.batches = 0
        self.completed = 0
        self.restarts = 0
        self._stopping = False  # guarded-by: _lock
        # declared guarded so the checker audits every new touch point;
        # the start_worker writes are single-writer by construction
        # (baselined with justification in analysis-baseline.txt)
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock
        # superseded worker threads that were still alive at restart: a
        # wedged generation may hold an accepted batch Future, and
        # stop() must wait it out (or report unclean) — dropping the
        # reference would let drain() claim clean with the Future
        # unresolved
        self._retired_threads: List[threading.Thread] = []
        self._on_done: Optional[Callable[["Replica", int, str], None]] = None
        # canary-era hooks (both skip shadow work): _on_fail records
        # engine failures per version for the error-rate detector;
        # _on_batch feeds the measured dispatch/compute split to the
        # queue-share detector (serve/canary.py)
        self._on_fail: Optional[Callable[["Replica", int, str], None]] = None
        self._on_batch: Optional[
            Callable[[str, float, float], None]
        ] = None
        self.start_worker()

    # -- worker --------------------------------------------------------

    def start_worker(self) -> None:
        with self._lock:
            self._gen += 1
            gen = self._gen
            self._stopping = False
            # the heartbeat belongs to the NEW generation: a wedged old
            # worker's stale busy_since must not re-trip the monitor
            # (it retires itself when its stuck call returns)
            self.busy_since = None
        if self._thread is not None and self._thread.is_alive():
            self._retired_threads = [
                t for t in self._retired_threads if t.is_alive()
            ]
            self._retired_threads.append(self._thread)
        self._thread = threading.Thread(
            target=self._worker, args=(gen,),
            name=f"replica-{self.rid}", daemon=True,
        )
        self._thread.start()

    def _worker(self, gen: int) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stopping and self._gen == gen:
                    self._cv.wait(timeout=0.05)
                if self._gen != gen:
                    return  # superseded by a restart
                if self._stopping and not self._q:
                    return
                work = self._q.popleft()
                self.busy_since = time.monotonic()
                # the version label this batch executes under is fixed
                # at pickup: a concurrent swap must not relabel it
                version = self.version
                runner = self._runner
            # dispatch-wait span: submit -> this pickup (replica-queue
            # time under backpressure); compute span: the engine call
            # itself. Both ride the batch Future so the front batcher
            # can attribute them per request (obs/rtrace.py).
            t_pick = time.perf_counter()
            dispatch_ms = (t_pick - work.t_enqueue) * 1000.0
            try:
                results = runner(work.payloads)
            except Exception as e:
                with self._cv:
                    if self._gen == gen:
                        self.busy_since = None
                if not work.future.done():
                    work.future.set_exception(e)
                if self._on_fail is not None and not work.shadow:
                    try:
                        self._on_fail(self, len(work.payloads), version)
                    except Exception:
                        pass  # ledger hooks must never kill a worker
                continue
            compute_ms = (time.perf_counter() - t_pick) * 1000.0
            retired = False
            with self._cv:
                if self._gen == gen:
                    self.busy_since = None
                else:
                    retired = True
                # a retiring (superseded) worker's answered batch still
                # counts: it WAS served by this replica, and the
                # per-replica table must agree with the
                # completed-by-version ledger _on_done feeds. Shadow
                # duplicates count NOWHERE: they exist only for the
                # logit-drift probe, and every serving ledger must see
                # exactly the client's requests.
                if not work.shadow:
                    self.batches += 1
                    self.completed += len(work.payloads)
            if not work.future.done():
                set_future_timing(work.future, dispatch_ms, compute_ms)
                # the version that ANSWERED rides the batch Future so
                # the front end can attribute each request to its
                # cohort (serve/canary.py) — labeled before set_result
                set_future_answered_by(work.future, version)
                work.future.set_result(results)
            if not work.shadow:
                if self._on_done is not None:
                    try:
                        self._on_done(self, len(work.payloads), version)
                    except Exception:
                        pass  # ledger hooks must never kill a worker
                if self._on_batch is not None:
                    try:
                        self._on_batch(version, dispatch_ms, compute_ms)
                    except Exception:
                        pass
            if retired:
                return  # a wedged worker's last act: answer, then exit

    # -- pool-side surface (all called under pool coordination) --------

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    def load(self) -> int:
        with self._lock:
            return len(self._q) + (1 if self.busy_since is not None else 0)

    def try_enqueue(self, work: _Work) -> bool:
        with self._cv:
            if self.state != READY or self._stopping:
                return False
            if len(self._q) >= self.max_queue_batches:
                return False
            self._q.append(work)
            self._cv.notify()
            return True

    def take_queued(self) -> List[_Work]:
        """Strip the UNSTARTED queue (requeue path: unhealthy replica's
        pending work moves to healthy peers)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
            return out

    def idle(self) -> bool:
        with self._lock:
            return not self._q and self.busy_since is None

    def worker_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wedged(self, timeout_s: float) -> bool:
        with self._lock:
            return (
                self.busy_since is not None
                and time.monotonic() - self.busy_since > timeout_s
            )

    def swap_runner(self, runner, version: str) -> None:
        with self._lock:
            self._runner = runner
            self.version = str(version)

    def stop(self, timeout: Optional[float] = None) -> bool:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        clean = True
        threads = [t for t in [self._thread] if t is not None]
        threads += self._retired_threads
        for t in threads:
            t.join(
                None if deadline is None
                else max(deadline - time.monotonic(), 0.0)
            )
            clean = clean and not t.is_alive()
        return clean

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replica": self.rid,
                "device": self.device,
                "version": self.version,
                "state": self.state,
                "canary": self.canary,
                "queue_depth": len(self._q),
                "busy": self.busy_since is not None,
                "batches": self.batches,
                "completed": self.completed,
                "restarts": self.restarts,
                "max_queue_batches": self.max_queue_batches,
            }


class ReplicaPool:
    """N replicas behind one least-loaded dispatcher, with health
    monitoring and blue/green artifact swap.

    ``runner_factory(artifact_ref, device) -> runner`` builds one
    replica's batch callable (the real factory AOT-warms an engine on
    that device — see :func:`make_engine_runner_factory`); ``devices``
    names one replica per entry (device labels are opaque strings here;
    the jax Device objects live inside the factory's closure).
    ``on_event(kind, **fields)`` receives ``replica``/``swap``
    telemetry when wired.
    """

    def __init__(
        self,
        runner_factory: Callable[[Any, str], Callable[[List[Any]], Any]],
        devices: Sequence[str],
        *,
        artifact_ref: Any = None,
        version: str = "v0001",
        max_queue_batches: int = 8,
        wedge_timeout_s: float = 30.0,
        health_interval_s: float = 0.25,
        on_event: Optional[Callable[..., Any]] = None,
    ):
        if not devices:
            raise ValueError("a replica pool needs at least one device")
        if max_queue_batches <= 0:
            raise ValueError("max_queue_batches must be >= 1")
        self.runner_factory = runner_factory
        self.artifact_ref = artifact_ref
        self.version = str(version)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.on_event = on_event
        self._lock = threading.Lock()
        self._draining = threading.Event()
        # two units on purpose: `shed` counts BATCHES (the thing submit
        # rejects), `shed_requests` the requests inside them — swap
        # reporting and the verdict's request ledger read the latter so
        # they never mix units with the front batcher's per-request
        # counters
        # guarded-by: _lock: shed, shed_requests, dispatched
        self.shed = 0
        self.shed_requests = 0
        self.dispatched = 0
        self.completed_by_version: Dict[str, int] = {}  # guarded-by: _lock
        self.failed_by_version: Dict[str, int] = {}  # guarded-by: _lock
        self._swap_lock = threading.Lock()
        self._swap_status: Dict[str, Any] = {"state": SWAP_IDLE}  # guarded-by: _lock
        # canary stage (serve/canary.py): non-None while a canary is
        # observing — {"seed", "fraction", "version_to", "monitor",
        # "shadow_every"}; submit snapshots it once per batch (a plain
        # attribute read — the non-canary dispatch path pays one `is
        # None` check and nothing else)
        self._canary: Optional[Dict[str, Any]] = None
        self._canary_seq = 0  # guarded-by: _lock
        self._cohort_counts: Optional[Dict[str, Dict[str, int]]] = None  # guarded-by: _lock
        # shadow comparator: mirror pairs queue + the thread that diffs
        # them OFF the hot path (a worker's done-callback only appends).
        # _shadow_queue is deliberately UNguarded: deque append/popleft
        # are atomic under the GIL and the queue is a single-producer/
        # single-consumer channel — annotating it would demand a lock
        # the hot-path callback does not need.
        self._shadow_queue: deque = deque()
        self._shadow_wake = threading.Event()
        self._shadow_stop = threading.Event()
        self._shadow_thread: Optional[threading.Thread] = None
        self._shadow_stats = {"mirrored": 0, "skipped": 0, "failed": 0}  # guarded-by: _lock
        # the factory needs the REAL device objects (jax.Device on the
        # engine path); replica snapshots carry only the string label
        self._device_objs: List[Any] = list(devices)
        self.replicas: List[Replica] = []
        for rid, dev in enumerate(devices):
            r = Replica(
                rid,
                runner_factory(artifact_ref, dev),
                device=str(dev),
                version=self.version,
                max_queue_batches=max_queue_batches,
            )
            r._on_done = self._record_done
            r._on_fail = self._record_fail
            self.replicas.append(r)
            self._emit(
                "replica", phase="start", replica=rid, device=str(dev),
                version=self.version,
            )
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._health_loop, args=(float(health_interval_s),),
            name="replica-health", daemon=True,
        )
        self._monitor.start()

    def _emit(self, kind: str, **fields) -> None:
        if self.on_event is not None:
            try:
                self.on_event(kind, **fields)
            except Exception:
                pass  # telemetry must never take the pool down

    def _record_done(self, replica: Replica, n: int, version: str) -> None:
        with self._lock:
            self.completed_by_version[version] = (
                self.completed_by_version.get(version, 0) + n
            )

    def _record_fail(self, replica: Replica, n: int, version: str) -> None:
        with self._lock:
            self.failed_by_version[version] = (
                self.failed_by_version.get(version, 0) + n
            )
            canary = self._canary
            if canary is not None and self._cohort_counts is not None:
                cohort = (
                    "canary" if version == canary["version_to"]
                    else "incumbent"
                )
                self._cohort_counts[cohort]["failed_requests"] += n

    # -- dispatch ------------------------------------------------------

    def _place(self, work: _Work) -> Optional[bool]:
        """THE dispatch policy, shared by submit and the restart
        requeue path: least-loaded READY replica first, then the rest
        (a candidate can fill between the load read and the enqueue,
        so try in order). True = enqueued; False = every candidate
        full; None = no READY replica at all.

        During a canary stage the restart-requeue path still uses this
        cohort-less placement on purpose: a requeued batch crossing
        cohorts is answered under the version label of whoever runs it
        (the answered-by future channel), so the monitor's windows stay
        truthful either way — availability beats cohort purity for
        work that was already accepted."""
        candidates = sorted(
            (r for r in self.replicas if r.state == READY),
            key=lambda r: r.load(),
        )
        for r in candidates:
            if r.try_enqueue(work):
                return True
        return False if candidates else None

    def _place_cohort(self, work: _Work, to_canary: bool):
        """Cohort-constrained placement while a canary stage is
        active. Returns ``(placed_tristate, fallback)`` with the same
        tri-state as :meth:`_place`. A canary-assigned batch whose
        cohort cannot absorb it FALLS BACK to the incumbent — clients
        never pay for the canary machinery with a shed, the fallback
        is counted (the ``unabsorbed`` detector's evidence), and the
        request is truthfully attributed to the incumbent that
        answered it. Incumbent-assigned batches never touch canary
        replicas: the traffic fraction is the canary's blast-radius
        bound, not a hint."""
        primary = sorted(
            (
                r for r in self.replicas
                if r.state == READY and r.canary == to_canary
            ),
            key=lambda r: r.load(),
        )
        for r in primary:
            if r.try_enqueue(work):
                return True, False
        if to_canary:
            secondary = sorted(
                (
                    r for r in self.replicas
                    if r.state == READY and not r.canary
                ),
                key=lambda r: r.load(),
            )
            for r in secondary:
                if r.try_enqueue(work):
                    return True, True
        any_ready = any(r.state == READY for r in self.replicas)
        return (False if any_ready else None), False

    def submit(self, payloads: List[Any]) -> Future:
        """Place one coalesced batch on the least-loaded READY replica;
        returns the batch Future (one result list for the whole batch —
        exactly what the micro-batcher's async runner contract wants).
        Raises :class:`LoadShedError` when draining, when no replica is
        healthy, or when every healthy replica's queue is full.

        While a canary stage is active (serve/canary.py) the batch is
        first ASSIGNED a cohort — deterministic seeded draw over the
        batch sequence number, so the traffic split is reproducible —
        then placed within it (:meth:`_place_cohort`), and a sampled
        incumbent batch is additionally MIRRORED to the canary for the
        logit-drift probe (:meth:`_mirror`)."""
        if self._draining.is_set():
            with self._lock:
                self.shed += 1
                self.shed_requests += len(payloads)
            raise LoadShedError("draining")
        work = _Work(payloads)
        canary = self._canary
        if canary is None:
            placed = self._place(work)
        else:
            from bdbnn_tpu.serve.canary import assign_canary

            with self._lock:
                seq = self._canary_seq
                self._canary_seq += 1
            to_canary = assign_canary(
                canary["seed"], seq, canary["fraction"]
            )
            placed, fallback = self._place_cohort(work, to_canary)
            with self._lock:
                counts = self._cohort_counts
                if counts is not None:
                    c = counts["canary" if to_canary else "incumbent"]
                    c["assigned_batches"] += 1
                    c["assigned_requests"] += len(payloads)
                    if fallback:
                        counts["canary"]["fallbacks"] += 1
                    if not placed:
                        c["sheds"] += 1
            if (
                placed
                and not to_canary
                and not fallback
                and canary.get("shadow_every", 0) > 0
            ):
                self._maybe_mirror(canary, seq, work, payloads)
        if placed:
            with self._lock:
                self.dispatched += 1
            return work.future
        with self._lock:
            self.shed += 1
            self.shed_requests += len(payloads)
        raise LoadShedError(
            "queue full" if placed is False else "no healthy replica"
        )

    # -- shadow mirroring (the logit-drift probe) ----------------------

    def _maybe_mirror(
        self, canary: Dict[str, Any], seq: int, work: _Work, payloads
    ) -> None:
        """Mirror a sampled incumbent batch onto a canary replica: the
        incumbent's answer goes to the client (its future is the one
        submit returned), the canary executes the SAME payloads as a
        shadow duplicate, and the pair lands on the comparator queue —
        the diff itself runs on the dedicated shadow thread, never a
        replica worker's."""
        from bdbnn_tpu.obs.rtrace import _splitmix64

        if _splitmix64(
            (int(canary["seed"]) + 0x5AD0) ^ int(seq)
        ) % int(canary["shadow_every"]) != 0:
            return
        shadow = _Work(payloads, shadow=True)
        cands = sorted(
            (r for r in self.replicas if r.state == READY and r.canary),
            key=lambda r: r.load(),
        )
        placed = False
        for r in cands:
            if r.try_enqueue(shadow):
                placed = True
                break
        if not placed:
            # a full canary is already visible to the unabsorbed
            # detector; a skipped mirror is only a missed measurement
            with self._lock:
                self._shadow_stats["skipped"] += 1
            return
        with self._lock:
            self._shadow_stats["mirrored"] += 1
        armed: List[bool] = []

        def _arm(_f, armed=armed, work=work, shadow=shadow, seq=seq):
            if not (work.future.done() and shadow.future.done()):
                return
            with self._lock:
                if armed:
                    return  # both callbacks saw both done — once only
                armed.append(True)
            self._shadow_queue.append((seq, work.future, shadow.future))
            self._shadow_wake.set()

        work.future.add_done_callback(_arm)
        shadow.future.add_done_callback(_arm)

    def _start_shadow(self, monitor) -> None:
        # the stats pump may be snapshotting stats() concurrently with
        # a rollout arming the probe — the reset goes under the lock
        with self._lock:
            self._shadow_stats = {"mirrored": 0, "skipped": 0, "failed": 0}
        self._shadow_queue.clear()
        self._shadow_stop.clear()
        self._shadow_thread = threading.Thread(
            target=self._shadow_loop, args=(monitor,),
            name="canary-shadow", daemon=True,
        )
        self._shadow_thread.start()

    def _stop_shadow(self, timeout: float = 5.0) -> None:
        if self._shadow_thread is None:
            return
        self._shadow_stop.set()
        self._shadow_wake.set()
        self._shadow_thread.join(timeout)
        self._shadow_thread = None

    def _shadow_loop(self, monitor) -> None:
        """Drain mirror pairs and diff them — the one place logits are
        compared, off every request path. Runs until stopped AND the
        queue is empty, so in-flight mirrors at decision time still
        land their measurement."""
        from bdbnn_tpu.serve.engine import max_abs_logit_drift

        while True:
            try:
                seq, primary, mirror = self._shadow_queue.popleft()
            except IndexError:
                if self._shadow_stop.is_set():
                    return
                self._shadow_wake.wait(0.05)
                self._shadow_wake.clear()
                continue
            try:
                a, b = primary.result(0), mirror.result(0)
            except Exception:
                # either side shed/failed: not a comparison
                with self._lock:
                    self._shadow_stats["failed"] += 1
                continue
            drift = max_abs_logit_drift(a, b)
            if drift is None:
                with self._lock:
                    self._shadow_stats["failed"] += 1
                continue
            monitor.record_drift(drift)
            self._emit(
                "shadow", phase="mirror", seq=seq, drift=drift,
                version_from=monitor.version_from,
                version_to=monitor.version_to,
            )

    # -- health --------------------------------------------------------

    def _health_loop(self, interval_s: float) -> None:
        while not self._monitor_stop.wait(interval_s):
            for r in self.replicas:
                try:
                    if r.state not in (READY, SHIFTING):
                        continue
                    dead = not r.worker_alive()
                    wedged = r.wedged(self.wedge_timeout_s)
                    if dead or wedged:
                        self._restart_replica(
                            r, "worker died" if dead else "wedged"
                        )
                except Exception as e:
                    # the monitor is the thing that notices broken
                    # replicas — it must never die of one; record the
                    # miss and keep watching
                    self._emit(
                        "replica", phase="monitor_error",
                        replica=r.rid, error=str(e),
                    )

    def _restart_replica(self, r: Replica, reason: str) -> None:
        if self._draining.is_set():
            # drain owns the replicas now: restarting one here would
            # resurrect a worker (start_worker resets _stopping) that
            # drain already stopped and will never join
            return
        # a SHIFTING replica stays out of the dispatch set after its
        # restart (the swap loop owns bringing it back READY) —
        # clobbering it READY would re-admit traffic to the replica the
        # shift is waiting to drain
        with r._lock:
            prior = r.state
            r.state = UNHEALTHY
            busy = r.busy_since
        self._emit(
            "replica", phase="unhealthy", replica=r.rid, device=r.device,
            version=r.version, reason=reason,
            busy_s=(
                round(time.monotonic() - busy, 3)
                if busy is not None else None
            ),
        )
        # unstarted work moves to healthy peers (the wedged batch
        # itself is answered by the retiring worker when it unsticks)
        requeued = shed = 0
        for work in r.take_queued():
            if work.shadow:
                # a queued shadow duplicate is only a probe measurement:
                # it must neither count as shed (no client sent it — the
                # zero-shed swap gate would misfire) nor requeue through
                # the cohort-less _place (executing the mirror on an
                # incumbent replica would record a vN-vs-vN diff as a
                # genuine drift measurement). Fail its future so the
                # comparator files the pair under `failed`, and move on.
                if not work.future.done():
                    work.future.set_exception(
                        LoadShedError("no healthy replica")
                    )
                continue
            placed = self._place(work)
            if placed:
                requeued += 1
            else:
                shed += 1
                with self._lock:
                    self.shed += 1
                    self.shed_requests += len(work.payloads)
                if not work.future.done():
                    # preserve _place's tri-state, same as submit():
                    # False = backpressure (every READY queue full),
                    # None = no READY replica at all — a pool outage
                    # must not be misfiled as queue-full backpressure
                    work.future.set_exception(LoadShedError(
                        "queue full" if placed is False
                        else "no healthy replica"
                    ))
        # fresh generation + worker; the old thread retires itself.
        # restarts is a counter snapshot() reads concurrently — the
        # increment takes the replica lock like every other counter
        with r._lock:
            r.restarts += 1
        r.start_worker()
        with r._lock:
            # re-read under the lock, and overwrite ONLY our own
            # UNHEALTHY mark. Any newer state is someone else's truth:
            # the swap loop marking SHIFTING (it owns the return to
            # READY), the swap loop COMPLETING the shift with READY
            # while this restart ran (restoring prior=SHIFTING over
            # that would exclude a healthy replica from dispatch
            # forever), or drain marking STOPPED.
            if r.state == UNHEALTHY:
                r.state = SHIFTING if prior == SHIFTING else READY
        self._emit(
            "replica", phase="restart", replica=r.rid, device=r.device,
            version=r.version, reason=reason, requeued=requeued,
            shed=shed, restarts=r.restarts,
        )

    # -- blue/green swap -----------------------------------------------

    def swap_status(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._swap_status)

    def _set_swap_status(self, status: Dict[str, Any]) -> None:
        with self._lock:
            self._swap_status = dict(status)

    def _warm_standbys(
        self,
        replicas: Sequence[Replica],
        new_artifact_ref: Any,
        new_version: str,
        status: Dict[str, Any],
        *,
        canary: bool = False,
    ) -> List[Callable]:
        """Build + AOT-warm one standby runner per replica BEFORE any
        traffic shifts — a failing factory marks the rollout FAILED
        with vN fully serving and re-raises (the PR 8 contract, shared
        by the full swap and both canary phases)."""
        try:
            standby = []
            for r in replicas:
                t_w = time.monotonic()
                standby.append(
                    self.runner_factory(
                        new_artifact_ref, self._device_objs[r.rid]
                    )
                )
                self._emit(
                    "swap", phase="warm", replica=r.rid,
                    device=r.device, version_to=str(new_version),
                    seconds=round(time.monotonic() - t_w, 3),
                    canary=canary or None,
                )
            return standby
        except Exception as e:
            status.update(state=SWAP_FAILED, error=str(e))
            self._set_swap_status(status)
            self._emit(
                "swap", phase="failed", version_to=str(new_version),
                error=str(e),
            )
            raise

    def _drain_and_swap(
        self, r: Replica, runner: Callable, version: str, *,
        canary: bool,
    ) -> bool:
        """THE runner-replacement protocol, shared by the shift path
        and the canary rollback: leave the dispatch set, let accepted
        work finish (bounded by the wedge timeout), swap the runner,
        rejoin READY with the cohort flag. State writes go under the
        replica's lock (the health monitor also writes state). Returns
        the drain outcome, captured BEFORE the replica rejoins — after
        READY, peers' batches land on it and "queue empty now" no
        longer says anything about how the drain went."""
        with r._lock:
            r.state = SHIFTING
        deadline = time.monotonic() + max(self.wedge_timeout_s, 1.0)
        while not r.idle() and time.monotonic() < deadline:
            time.sleep(0.005)
        drained_clean = r.idle()
        r.swap_runner(runner, str(version))
        with r._lock:
            r.canary = canary
            r.state = READY
        return drained_clean

    def _shift_one(
        self,
        r: Replica,
        runner: Callable,
        new_version: str,
        status: Dict[str, Any],
        *,
        canary: bool = False,
    ) -> None:
        """Shift ONE replica onto ``runner`` (peers absorb the load
        meanwhile), account it in ``status`` and emit the shift event.
        ``canary`` marks the replica's cohort on rejoin."""
        drained_clean = self._drain_and_swap(
            r, runner, new_version, canary=canary
        )
        status["replicas_shifted"] = status.get("replicas_shifted", 0) + 1
        self._set_swap_status(status)
        self._emit(
            "swap", phase="shift", replica=r.rid, device=r.device,
            version_from=status.get("version_from"),
            version_to=str(new_version),
            drained_clean=drained_clean, canary=canary or None,
        )

    def swap(
        self, new_artifact_ref: Any, new_version: str
    ) -> Dict[str, Any]:
        """Roll every replica onto ``new_artifact_ref`` under live
        traffic. Blocking (run it on its own thread — the admin
        endpoint and the CLI orchestration both do); one swap at a
        time. Returns the final status dict; raises RuntimeError when a
        swap is already in progress and propagates a factory failure
        after marking the status FAILED (serving continues on vN —
        a bad artifact must never take the pool down)."""
        if not self._swap_lock.acquire(blocking=False):
            raise RuntimeError("a swap is already in progress")
        try:
            t0 = time.monotonic()
            status = {
                "state": SWAP_WARMING,
                "version_from": self.version,
                "version_to": str(new_version),
                "replicas_total": len(self.replicas),
                "replicas_shifted": 0,
            }
            with self._lock:
                self._swap_status = dict(status)
            self._emit(
                "swap", phase="start", version_from=self.version,
                version_to=str(new_version), replicas=len(self.replicas),
            )
            # 1. standby set: build + AOT-warm EVERY new runner before
            #    any traffic shifts — a failed load aborts with vN
            #    fully serving
            standby = self._warm_standbys(
                self.replicas, new_artifact_ref, new_version, status
            )
            # 2. shift traffic replica-by-replica (helper shared with
            #    the canary promote path)
            status["state"] = SWAP_SHIFTING
            self._set_swap_status(status)
            for r, runner in zip(self.replicas, standby):
                if self._draining.is_set():
                    # the pool is being torn down mid-rollout: stop
                    # shifting (drain owns the replicas now) and report
                    # the truth instead of racing restarted states
                    status.update(
                        state=SWAP_FAILED,
                        error="pool drained mid-swap",
                    )
                    self._set_swap_status(status)
                    self._emit(
                        "swap", phase="failed",
                        version_to=str(new_version),
                        error="pool drained mid-swap",
                    )
                    return dict(status)
                self._shift_one(r, runner, new_version, status)
            # 3. vN is drained (no replica runs it anymore); retire it
            old_version = self.version
            self.version = str(new_version)
            self.artifact_ref = new_artifact_ref
            status.update(
                state=SWAP_DONE, seconds=round(time.monotonic() - t0, 3)
            )
            with self._lock:
                self._swap_status = dict(status)
            self._emit(
                "swap", phase="done", version_from=old_version,
                version_to=str(new_version),
                seconds=status["seconds"],
                replicas_shifted=status["replicas_shifted"],
            )
            return dict(status)
        finally:
            self._swap_lock.release()

    # -- canary rollout (serve/canary.py) ------------------------------

    def _cohort_snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            counts = self._cohort_counts or {}
            return {c: dict(v) for c, v in counts.items()}

    def _rollback_canaries(
        self,
        cans: Sequence[Replica],
        status: Dict[str, Any],
        old_ref: Any,
        old_version: str,
        old_runners: Dict[int, Callable],
    ) -> None:
        """Drain each canary replica's accepted vN+1 work, restore vN,
        rejoin. The vN runner is REBUILT through the factory when
        possible (keeps the factory's resident-cache accounting
        truthful); when the factory fails, the RETAINED vN runner
        object is restored instead — a rollback must never depend on a
        possibly-broken factory to get back to the version that was
        serving fine a minute ago. The registry is untouched either
        way."""
        for r in cans:
            try:
                runner = self.runner_factory(
                    old_ref, self._device_objs[r.rid]
                )
                restored = "rebuilt"
            except Exception:
                runner = old_runners[r.rid]
                restored = "retained"
            drained_clean = self._drain_and_swap(
                r, runner, old_version, canary=False
            )
            self._emit(
                "canary", phase="rollback", replica=r.rid,
                device=r.device, version_restored=old_version,
                runner=restored, drained_clean=drained_clean,
            )

    def canary_swap(
        self,
        new_artifact_ref: Any,
        new_version: str,
        monitor,
        *,
        fraction: float,
        canary_replicas: int = 1,
        shadow_every: int = 8,
        seed: int = 0,
    ) -> Dict[str, Any]:
        """The self-driving rollout: :meth:`swap` extended with a
        canary stage whose live verdict decides the outcome.

        1. **warm** — standby vN+1 runners for the canary replica
           subset only (the LAST ``canary_replicas`` replicas); a
           failing factory aborts with vN fully serving.
        2. **canary** — the subset shifts to vN+1, the seeded
           ``fraction`` of batches routes to it, sampled incumbent
           batches mirror onto it for the logit-drift probe, and the
           ``monitor`` (serve/canary.py) compares the cohorts' live
           windows every ``eval_interval_s``.
        3. **decision** — ``promote``: the remaining replicas warm and
           shift exactly like :meth:`swap` and the pool retires vN;
           ``rollback``: the canary replicas drain their vN+1 work and
           restore vN — the registry untouched, the pool version
           unchanged, the episode recorded. An expired observation
           budget rolls back as ``inconclusive``.

        Blocking (run on the admin rollout thread, like swap); one
        rollout at a time. Returns the final status dict whose
        ``canary`` key is the monitor's full evidence block."""
        if not self._swap_lock.acquire(blocking=False):
            raise RuntimeError("a swap is already in progress")
        try:
            t0 = time.monotonic()
            n_can = max(int(canary_replicas), 1)
            if n_can >= len(self.replicas):
                raise ValueError(
                    "a canary needs at least one incumbent replica: "
                    f"canary_replicas={n_can} of "
                    f"{len(self.replicas)} total"
                )
            cans = list(self.replicas[-n_can:])
            rest = list(self.replicas[:-n_can])
            old_version = self.version
            old_ref = self.artifact_ref
            old_runners = {r.rid: r._runner for r in cans}
            status: Dict[str, Any] = {
                "state": SWAP_CANARY_WARMING,
                "version_from": old_version,
                "version_to": str(new_version),
                "replicas_total": len(self.replicas),
                "replicas_shifted": 0,
                "canary": None,
            }
            self._set_swap_status(status)
            self._emit(
                "swap", phase="start", version_from=old_version,
                version_to=str(new_version),
                replicas=len(self.replicas), canary=True,
            )
            self._emit(
                "canary", phase="start", version_from=old_version,
                version_to=str(new_version), fraction=float(fraction),
                replicas_canary=[r.rid for r in cans],
                shadow_every=int(shadow_every),
            )
            standby_can = self._warm_standbys(
                cans, new_artifact_ref, new_version, status, canary=True
            )
            # cohort routing activates BEFORE the subset shifts:
            # canary-assigned batches that arrive while the subset is
            # still shifting fall back to the incumbent (counted),
            # never leak unbounded traffic onto vN+1. The MONITOR is
            # armed only at observation start below — every feed from
            # the shift window (queue waits behind the draining
            # replica, fallback floods) is drain physics, not health
            # evidence, and an inactive monitor drops it.
            self._start_shadow(monitor)
            with self._lock:
                self._canary_seq = 0
                self._cohort_counts = {
                    c: {
                        "assigned_batches": 0,
                        "assigned_requests": 0,
                        "sheds": 0,
                        "fallbacks": 0,
                        "failed_requests": 0,
                    }
                    for c in ("incumbent", "canary")
                }
            for r in self.replicas:
                r._on_batch = monitor.record_batch
            self._canary = {
                "seed": int(seed),
                "fraction": float(fraction),
                "version_to": str(new_version),
                "shadow_every": int(shadow_every),
            }
            status["state"] = SWAP_CANARY
            self._set_swap_status(status)
            aborted = False
            try:
                for r, runner in zip(cans, standby_can):
                    if self._draining.is_set():
                        aborted = True
                        break
                    self._shift_one(
                        r, runner, new_version, status, canary=True
                    )
                # the observation loop: the monitor's verdict drives
                # the state machine, no human in it
                decision: Optional[Dict[str, Any]] = None
                if not aborted:
                    # observation starts HERE: zero the cohort
                    # counters and only now arm the monitor. Routing
                    # was live through the subset's shift (by design),
                    # so that window's canary-assigned batches FELL
                    # BACK mechanically and everything queued behind
                    # the draining replica carried drain-sized waits —
                    # left in, a slow subset drain would pin the
                    # unabsorbed ratio near 1.0 (or the queue-share
                    # delta near 1) and roll back a perfectly healthy
                    # canary on its own shift physics.
                    with self._lock:
                        for c in self._cohort_counts.values():
                            for k in c:
                                c[k] = 0
                    monitor.arm(
                        version_from=old_version,
                        version_to=str(new_version),
                        fraction=float(fraction),
                        replicas=[r.rid for r in cans],
                    )
                    self._emit(
                        "canary", phase="observing",
                        version_to=str(new_version),
                        eval_interval_s=monitor.cfg.eval_interval_s,
                        max_wait_s=monitor.cfg.max_wait_s,
                    )
                    deadline = time.monotonic() + monitor.cfg.max_wait_s
                    while True:
                        if self._draining.is_set():
                            aborted = True
                            break
                        res = monitor.evaluate(self._cohort_snapshot())
                        if res["decision"] != "observe":
                            decision = res
                            break
                        if time.monotonic() >= deadline:
                            decision = monitor.conclude("timeout")
                            break
                        time.sleep(monitor.cfg.eval_interval_s)
            finally:
                # cohort routing + feeds off before any resolution
                # path runs: promote/rollback shifts must dispatch
                # freely, and a teardown mid-observation must not
                # leave routing pinned to a half-rolled pool
                self._canary = None
                for r in self.replicas:
                    r._on_batch = None
                self._stop_shadow()
                monitor.disarm()
            if aborted:
                status.update(
                    state=SWAP_FAILED, error="pool drained mid-canary",
                    canary=monitor.report(dict(self._shadow_stats)),
                )
                self._set_swap_status(status)
                self._emit(
                    "swap", phase="failed", version_to=str(new_version),
                    error="pool drained mid-canary",
                )
                return dict(status)
            if decision["decision"] == "promote":
                try:
                    standby_rest = self._warm_standbys(
                        rest, new_artifact_ref, new_version, status
                    )
                except Exception:
                    # promote-warm failed with a mixed fleet: restore
                    # the canary replicas to vN so the pool is whole
                    # on the incumbent again, then report FAILED
                    self._rollback_canaries(
                        cans, status, old_ref, old_version, old_runners
                    )
                    status["canary"] = monitor.report(
                        dict(self._shadow_stats)
                    )
                    self._set_swap_status(status)
                    return dict(status)
                status["state"] = SWAP_SHIFTING
                self._set_swap_status(status)
                for r, runner in zip(rest, standby_rest):
                    if self._draining.is_set():
                        status.update(
                            state=SWAP_FAILED,
                            error="pool drained mid-swap",
                            canary=monitor.report(
                                dict(self._shadow_stats)
                            ),
                        )
                        self._set_swap_status(status)
                        self._emit(
                            "swap", phase="failed",
                            version_to=str(new_version),
                            error="pool drained mid-swap",
                        )
                        return dict(status)
                    self._shift_one(r, runner, new_version, status)
                for r in cans:
                    with r._lock:
                        r.canary = False
                self.version = str(new_version)
                self.artifact_ref = new_artifact_ref
                canary_block = monitor.report(dict(self._shadow_stats))
                canary_block["promote_s"] = round(
                    time.monotonic() - t0, 3
                )
                status.update(
                    state=SWAP_DONE,
                    seconds=round(time.monotonic() - t0, 3),
                    canary=canary_block,
                )
                self._set_swap_status(status)
                self._emit(
                    "canary", phase="promote",
                    version_from=old_version,
                    version_to=str(new_version),
                    seconds=canary_block["promote_s"],
                    evaluations=canary_block["evaluations"],
                )
                self._emit(
                    "swap", phase="done", version_from=old_version,
                    version_to=str(new_version),
                    seconds=status["seconds"],
                    replicas_shifted=status["replicas_shifted"],
                )
                return dict(status)
            # rollback (a fired detector, or inconclusive at budget)
            status["state"] = SWAP_ROLLING_BACK
            self._set_swap_status(status)
            self._rollback_canaries(
                cans, status, old_ref, old_version, old_runners
            )
            canary_block = monitor.report(dict(self._shadow_stats))
            canary_block["promote_s"] = None
            status.update(
                state=SWAP_ROLLED_BACK,
                seconds=round(time.monotonic() - t0, 3),
                canary=canary_block,
                error=None,
            )
            self._set_swap_status(status)
            self._emit(
                "swap", phase="rolled_back",
                version_from=old_version,
                version_to=str(new_version),
                trigger=canary_block["trigger"],
                seconds=status["seconds"],
            )
            return dict(status)
        finally:
            self._canary = None
            self._stop_shadow()
            self._swap_lock.release()

    # -- lifecycle / reporting -----------------------------------------

    def drain(self, timeout: float = 60.0) -> bool:
        """Latch the drain flag (submit sheds), execute every queued
        batch, stop the workers and the health monitor. Every accepted
        Future resolves before this returns True."""
        self._draining.set()
        self._monitor_stop.set()
        self._stop_shadow()
        deadline = time.monotonic() + timeout
        clean = True
        # monitor FIRST: a restart racing the replica stops below would
        # resurrect a worker thread drain never joins (start_worker
        # resets _stopping); _restart_replica also bails on _draining,
        # so this join is bounded by one in-flight health pass
        self._monitor.join(timeout=max(deadline - time.monotonic(), 0.1))
        for r in self.replicas:
            clean = r.stop(
                timeout=max(deadline - time.monotonic(), 0.1)
            ) and clean
            # an unclean stop leaves a worker alive reading state under
            # its lock (try_enqueue) — the terminal write takes it too
            with r._lock:
                r.state = STOPPED
        # belt and braces: a worker that failed to stop in time may
        # leave queued work — answer it explicitly, never silently
        for r in self.replicas:
            for work in r.take_queued():
                clean = False
                if not work.future.done():
                    work.future.set_exception(LoadShedError("draining"))
        return clean

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            shed = self.shed
            shed_requests = self.shed_requests
            dispatched = self.dispatched
            by_version = dict(self.completed_by_version)
            failed_by_version = dict(self.failed_by_version)
            swap_status = dict(self._swap_status)
            canary = self._canary
            cohorts = (
                {c: dict(v) for c, v in self._cohort_counts.items()}
                if self._cohort_counts is not None else None
            )
            shadow = dict(self._shadow_stats)
        reps = [r.snapshot() for r in self.replicas]
        batches = sum(r["batches"] for r in reps)
        return {
            "replicas": reps,
            "n_replicas": len(reps),
            "version": self.version,
            "dispatched": dispatched,
            "shed": shed,
            "shed_requests": shed_requests,
            "batches": batches,
            "completed": sum(r["completed"] for r in reps),
            "restarts": sum(r["restarts"] for r in reps),
            "completed_by_version": by_version,
            "failed_by_version": failed_by_version,
            "swap": swap_status,
            # live canary routing state: None outside an observation
            # window; the cohort counters persist past the decision so
            # the verdict's evidence survives the teardown
            "canary_active": canary is not None,
            "cohorts": cohorts,
            "shadow": shadow,
        }


class PoolAdmin:
    """The operator surface the HTTP front end's ``/admin/*`` routes
    call into: the per-replica table, swap status, and the
    ``POST /admin/swap`` trigger — which resolves its target through
    the artifact registry (``{"version": N}``, digest-verified) or a
    raw artifact dir (``{"artifact": "/path"}``), then runs
    :meth:`ReplicaPool.swap` on its own thread so the admin request
    returns 202 immediately while the rollout proceeds under traffic.

    ``shed_counter`` (optional) is polled at swap start/end so the
    swap report can pin "shed caused during the swap window" — the
    number the zero-shed-due-to-swap acceptance gate reads.

    ``canary`` (optional) configures the self-driving rollout
    (serve/canary.py): ``{"monitor": CanaryMonitor, "fraction": f,
    "replicas": n, "shadow_every": k, "seed": s}``. When set, every
    triggered rollout runs :meth:`ReplicaPool.canary_swap` — the
    monitor's live verdict decides promote vs auto-rollback — unless
    the swap body explicitly opts out with ``{"canary": false}``.
    """

    def __init__(
        self,
        pool: ReplicaPool,
        *,
        registry: Any = None,
        shed_counter: Optional[Callable[[], int]] = None,
        canary: Optional[Dict[str, Any]] = None,
    ):
        self.pool = pool
        self.registry = registry
        self.shed_counter = shed_counter
        self.canary = canary
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock
        self._last_swap: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        # the target of an ACCEPTED start_swap, recorded before the
        # rollout thread runs: a swap still in flight (or wedged) at
        # verdict time must report an honest not-performed block, not
        # a null that skips every zero-downtime gate
        self._requested: Optional[str] = None  # guarded-by: _lock

    def replicas(self) -> Dict[str, Any]:
        return self.pool.stats()

    def swap_status(self) -> Dict[str, Any]:
        with self._lock:
            last = dict(self._last_swap) if self._last_swap else None
        return {"current": self.pool.swap_status(), "last": last}

    def resolve_target(self, spec: Dict[str, Any]):
        """``{"version": N}`` (registry, verified) or ``{"artifact":
        dir}`` -> (artifact_dir, version_label); raises KeyError /
        ValueError with operator-pointed messages."""
        if "version" in spec:
            if self.registry is None:
                raise ValueError(
                    "no --registry configured: swap by version needs one"
                )
            from bdbnn_tpu.serve.registry import parse_version

            version = parse_version(spec["version"])
            return (
                self.registry.resolve(version),
                self.registry.label(version),
            )
        if "artifact" in spec:
            path = str(spec["artifact"])
            import os as _os

            if not _os.path.isdir(path):
                raise KeyError(f"artifact dir not found: {path!r}")
            return path, _os.path.basename(path.rstrip("/")) or path
        raise ValueError(
            'swap body must carry {"version": N} or {"artifact": dir}'
        )

    def start_swap(self, spec: Dict[str, Any]):
        """Returns ``(http_status, payload)``: 202 accepted, 409 when a
        swap is already running, 400/404 on a bad target."""
        try:
            artifact_dir, label = self.resolve_target(spec)
        except (KeyError, FileNotFoundError) as e:
            return 404, {"error": str(e)}
        except Exception as e:
            # total by design: ANY resolution failure (bad spec, digest
            # mismatch, a version dir torn after publish, ...) must
            # come back as an HTTP error — an escaped exception would
            # kill the scheduled swap-trigger thread before
            # note_request_failed runs, nulling the verdict's swap
            # block and silently skipping the zero-downtime gate
            return 400, {"error": str(e)}
        if len(self.pool.replicas) < 2:
            # same hazard ServeHttpConfig.validate rejects for
            # --swap-at: the blue/green shift takes the shifting
            # replica out of the dispatch set while peers absorb its
            # load — with one replica every batch assembled during the
            # shift sheds, so the "zero-downtime" rollout is a
            # guaranteed outage window
            return 409, {
                "error": (
                    "blue/green swap needs >= 2 replicas: with one "
                    "replica the shift has no peer to absorb traffic "
                    "and every request during the swap window sheds "
                    "(restart serve-http with --replicas >= 2)"
                )
            }
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return 409, {
                    "error": "a swap is already in progress",
                    "current": self.pool.swap_status(),
                }
            shed_before = (
                self.shed_counter() if self.shed_counter else 0
            )
            self._requested = label

            canary = (
                self.canary
                if self.canary is not None
                and spec.get("canary", True) is not False
                else None
            )

            def _run():
                try:
                    if canary is not None:
                        status = self.pool.canary_swap(
                            artifact_dir,
                            label,
                            canary["monitor"],
                            fraction=canary["fraction"],
                            canary_replicas=canary.get("replicas", 1),
                            shadow_every=canary.get("shadow_every", 8),
                            seed=canary.get("seed", 0),
                        )
                    else:
                        status = self.pool.swap(artifact_dir, label)
                except Exception as e:
                    # the pool records a FULL failed status
                    # (version_from, replicas_total, ...) before
                    # re-raising — prefer it over a minimal rebuild,
                    # as long as it is THIS swap's record
                    status = self.pool.swap_status()
                    if (
                        status.get("version_to") != label
                        or status.get("state") != SWAP_FAILED
                    ):
                        status = {
                            "state": SWAP_FAILED, "version_to": label,
                        }
                    status.setdefault("error", str(e))
                shed_after = (
                    self.shed_counter() if self.shed_counter else 0
                )
                with self._lock:
                    self._last_swap = {
                        **status,
                        # every shed that happened while the swap was
                        # rolling, against any layer — the conservative
                        # upper bound on "shed caused by the swap"
                        "shed": max(shed_after - shed_before, 0),
                    }

            self._thread = threading.Thread(
                target=_run, name="pool-swap", daemon=True
            )
            self._thread.start()
        return 202, {
            "accepted": True,
            "version_to": label,
            "artifact": artifact_dir,
        }

    def note_request_failed(self, target: Any, error: Any) -> None:
        """Record a swap REQUEST that was rejected before any rollout
        could start (bad version, failed digest, missing dir) — the
        scheduled swap-under-load path calls this on a non-202 so the
        verdict reports an honest not-performed swap instead of a null
        that skips every zero-downtime gate. Never overwrites a real
        rollout's report."""
        with self._lock:
            if self._last_swap is None:
                self._requested = str(target)
                self._last_swap = {
                    "state": "rejected",
                    "version_from": self.pool.version,
                    "version_to": str(target),
                    "error": str(error),
                    "shed": 0,
                }

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join an in-flight swap (drain-time tidy-up)."""
        t = self._thread
        if t is not None:
            t.join(timeout)
            return not t.is_alive()
        return True

    def swap_report(self) -> Optional[Dict[str, Any]]:
        """The verdict's ``swap`` block: the last completed swap's
        disposition plus the completed-by-version ledger. None only
        when no swap was ever REQUESTED — a rollout still in flight
        (or wedged) at report time yields an explicit not-performed
        block, so the zero-downtime gates fail loudly instead of
        skipping a null."""
        with self._lock:
            last = dict(self._last_swap) if self._last_swap else None
            requested = self._requested
        if last is None:
            if requested is None:
                return None
            stats = self.pool.stats()
            return {
                "performed": False,
                "state": self.pool.swap_status().get("state"),
                "version_from": None,
                "version_to": requested,
                "seconds": None,
                "replicas_shifted": None,
                "shed": None,
                "error": "swap did not complete before the report",
                "answered_by": stats["completed_by_version"],
            }
        stats = self.pool.stats()
        return {
            "performed": last.get("state") == SWAP_DONE,
            "state": last.get("state"),
            "version_from": last.get("version_from"),
            "version_to": last.get("version_to"),
            "seconds": last.get("seconds"),
            "replicas_shifted": last.get("replicas_shifted"),
            "shed": last.get("shed", 0),
            "error": last.get("error"),
            "answered_by": stats["completed_by_version"],
        }

    def canary_report(self) -> Optional[Dict[str, Any]]:
        """The verdict's nullable ``canary`` block (SLO verdict v5):
        the last rollout's canary-episode evidence, or None when no
        canary stage ever ran (plain swaps, pre-canary runs) so
        ``compare``'s canary metrics skip cleanly."""
        with self._lock:
            last = dict(self._last_swap) if self._last_swap else None
        if last is None or last.get("canary") is None:
            return None
        return dict(last["canary"])


def replica_stats_fields(ps: Dict[str, Any]) -> Dict[str, Any]:
    """The ``replica phase=stats`` event payload over a
    :meth:`ReplicaPool.stats` snapshot — one row per replica plus the
    swap state machine's position, the live heartbeat ``watch``
    renders. Shared by both serve CLIs (serve-http's pump and the
    pooled serve-bench passes) so the consumers see ONE shape."""
    return {
        "version": ps["version"],
        "completed": ps["completed"],
        "restarts": ps["restarts"],
        "completed_by_version": ps["completed_by_version"],
        "swap": ps["swap"],
        "canary_active": ps.get("canary_active", False),
        "cohorts": ps.get("cohorts"),
        "replicas": [
            {
                "replica": r["replica"],
                "device": r["device"],
                "version": r["version"],
                "state": r["state"],
                "canary": r.get("canary", False),
                "queue_depth": r["queue_depth"],
                "completed": r["completed"],
            }
            for r in ps["replicas"]
        ],
    }


class ResidentModelCache:
    """N packed artifacts co-resident on ONE replica device, with LRU
    accounting — the multi-tenant unlock the 1-bit residency buys: a
    packed resnet is ~16-32x smaller on the conv weights, so one chip
    holds dozens of models and ``serve-http`` can route ``x-model`` to
    co-resident versions without a reload in the request path.

    ``loader(model_key) -> engine`` builds (and AOT-warms) one model's
    engine on this replica's device; ``capacity`` bounds how many stay
    resident. ``get`` returns the resident engine, loading on first
    use and evicting the least-recently-used OTHER model when the
    cache is full (the evicted engine's device buffers free when the
    reference drops). Every load/hit/eviction is counted and each
    model's resident bytes recorded — the verdict's ``resident`` block
    and the ``memory`` serve events read :meth:`stats`.

    Thread-safe: one replica worker owns the request path, but swap
    factories, admin stats reads and the verdict assembly may look in
    concurrently."""

    def __init__(
        self,
        loader: Callable[[str], Any],
        *,
        capacity: int = 1,
        device: str = "",
        on_event: Optional[Callable[..., Any]] = None,
    ):
        if capacity < 1:
            raise ValueError("resident-model capacity must be >= 1")
        self.loader = loader
        self.capacity = int(capacity)
        self.device = str(device)
        self.on_event = on_event
        self._lock = threading.Lock()
        # insertion/refresh order IS the LRU order (oldest first)
        self._engines: "dict[str, Any]" = {}  # guarded-by: _lock
        # guarded-by: _lock: hits, misses, evictions, loads,
        # guarded-by: _lock: load_seconds, resident_bytes, dense_equiv_bytes
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.loads = 0
        self.load_seconds: Dict[str, float] = {}
        self.resident_bytes: Dict[str, int] = {}
        self.dense_equiv_bytes: Dict[str, int] = {}

    def get(self, key: str):
        """The resident engine for ``key`` — loading it (and evicting
        the LRU resident if the cache is full) on first use. The load
        happens OUTSIDE the lock: a cold model compiling for seconds
        must not block stats reads, and the worst double-load race
        costs one redundant build, never a wrong answer."""
        key = str(key)
        with self._lock:
            engine = self._engines.pop(key, None)
            if engine is not None:
                self._engines[key] = engine  # refresh LRU position
                self.hits += 1
                return engine
            self.misses += 1
        t0 = time.monotonic()
        engine = self.loader(key)
        load_s = round(time.monotonic() - t0, 3)
        report = self._engine_residency(engine)
        nbytes = report.get("resident_bytes") if report else None
        with self._lock:
            if key not in self._engines:
                while len(self._engines) >= self.capacity:
                    old_key = next(iter(self._engines))
                    self._engines.pop(old_key)
                    self.evictions += 1
                    # the byte accounting tracks what is resident NOW
                    # — an evicted model's row must leave with its
                    # engine, or stats()/resident_block report freed
                    # device memory as still occupied
                    evicted_bytes = self.resident_bytes.pop(
                        old_key, None
                    )
                    self.dense_equiv_bytes.pop(old_key, None)
                    self.load_seconds.pop(old_key, None)
                    self._emit(
                        "replica", phase="model_evict", device=self.device,
                        model=old_key,
                        resident_bytes=evicted_bytes,
                    )
                self._engines[key] = engine
                self.loads += 1
                self.load_seconds[key] = load_s
                if nbytes is not None:
                    self.resident_bytes[key] = nbytes
                if report and report.get("dense_equiv_bytes") is not None:
                    self.dense_equiv_bytes[key] = int(
                        report["dense_equiv_bytes"]
                    )
                self._emit(
                    "replica", phase="model_load", device=self.device,
                    model=key, seconds=load_s, resident_bytes=nbytes,
                )
            return self._engines[key]

    @staticmethod
    def _engine_residency(engine) -> Optional[Dict[str, Any]]:
        residency = getattr(engine, "residency", None)
        if callable(residency):
            try:
                return residency()
            except Exception:
                return None
        return None

    def _emit(self, kind: str, **fields) -> None:
        if self.on_event is not None:
            try:
                self.on_event(kind, **fields)
            except Exception:
                pass  # telemetry must never break the request path

    def resident_keys(self) -> List[str]:
        with self._lock:
            return list(self._engines)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "device": self.device,
                "capacity": self.capacity,
                "resident": list(self._engines),
                "hits": self.hits,
                "misses": self.misses,
                "loads": self.loads,
                "evictions": self.evictions,
                "load_seconds": dict(self.load_seconds),
                "resident_bytes": dict(self.resident_bytes),
                "dense_equiv_bytes": dict(self.dense_equiv_bytes),
            }


def resident_block(
    caches: Sequence["ResidentModelCache"],
    *,
    completed_by_model: Optional[Dict[str, int]] = None,
) -> Optional[Dict[str, Any]]:
    """The verdict's ``resident`` block over every replica's model
    cache: per-model resident bytes (max over replicas — the binding
    per-chip figure), load/hit/eviction totals, and — when the front
    end tracked it — completed requests per model. None when no cache
    exists (dense single-engine serving), so pre-packed verdicts skip
    cleanly in ``compare``."""
    if not caches:
        return None
    models: Dict[str, Dict[str, Any]] = {}
    hits = misses = evictions = loads = 0

    def _row(key):
        return models.setdefault(
            key,
            {
                "resident_bytes": None,
                "dense_equiv_bytes": None,
                "completed": None,
            },
        )

    for c in caches:
        s = c.stats()
        hits += s["hits"]
        misses += s["misses"]
        evictions += s["evictions"]
        loads += s["loads"]
        for key, nbytes in s["resident_bytes"].items():
            row = _row(key)
            if nbytes is not None:
                row["resident_bytes"] = max(
                    row["resident_bytes"] or 0, int(nbytes)
                )
        for key, nbytes in s["dense_equiv_bytes"].items():
            row = _row(key)
            if nbytes is not None:
                row["dense_equiv_bytes"] = max(
                    row["dense_equiv_bytes"] or 0, int(nbytes)
                )
    for key, n in (completed_by_model or {}).items():
        _row(key)["completed"] = int(n)
    per_model = [
        b for b in (m["resident_bytes"] for m in models.values())
        if b is not None
    ]
    return {
        "capacity": max(c.capacity for c in caches),
        "replicas": len(caches),
        "models": models,
        "hits": hits,
        "misses": misses,
        "loads": loads,
        "evictions": evictions,
        "bytes_per_model_max": max(per_model) if per_model else None,
    }


def first_warm_capture():
    """``(warm_compile, on_engine)`` pair for
    :func:`make_engine_runner_factory`: records only the FIRST replica
    engine's per-bucket compile seconds — the representative warmup
    figure both orchestrations report — without retaining any engine
    (whole engines are owned by their replicas; keeping them across a
    sweep would pin every pass's device weights alive at once)."""
    warm_compile: Dict[Any, float] = {}

    def on_engine(e, d):
        if not warm_compile:
            warm_compile.update(e.compile_seconds)

    return warm_compile, on_engine


DEFAULT_MODEL = "default"


def single_engine_resident_block(
    residency: Dict[str, Any], *, completed: Optional[int] = None
) -> Dict[str, Any]:
    """The verdict's ``resident`` block for the single-engine serving
    paths (no pool, no cache): ONE model, the engine's own
    :meth:`~bdbnn_tpu.serve.engine.InferenceEngine.residency` report.
    Same shape :func:`resident_block` emits, built here once so the
    serve-bench and serve-http verdicts cannot drift apart."""
    return {
        "capacity": 1,
        "replicas": 1,
        "models": {
            DEFAULT_MODEL: {
                "resident_bytes": residency["resident_bytes"],
                "dense_equiv_bytes": residency["dense_equiv_bytes"],
                "completed": completed,
            }
        },
        "hits": None,
        "misses": None,
        "loads": 1,
        "evictions": 0,
        "bytes_per_model_max": residency["resident_bytes"],
    }


def _apply_degradation(
    runner: Callable[[List[Any]], Any],
    spec: Optional[Dict[str, Any]],
    artifact_ref: Any,
    device: Any,
) -> Callable[[List[Any]], Any]:
    """Fault-injection wrapper for canary drills and tests: degrade
    ONE version's runners with injectable latency inflation, engine
    failures, or logit perturbation, leaving every other version
    untouched.

    ``spec`` keys (all optional except that at least one fault must be
    nonzero to wrap):

    - ``artifact`` — the artifact ref the degradation targets; a
      runner built for any OTHER ref is returned UNWRAPPED (the
      zero-cost-when-inactive contract: disabled means the plain
      runner object, not a pass-through shim).
    - ``latency_ms`` — sleep this long before answering a batch that
      contains a matched payload.
    - ``error_rate`` — probability (per matched batch, seeded per
      device) of raising instead of answering — an ENGINE failure,
      ledgered as failed, never as load shedding.
    - ``logit_eps`` — added to the matched rows' logits (per-row, so a
      perturbation can target a payload subset exactly) — what the
      shadow logit-drift probe exists to catch.
    - ``match`` — ``callable(payload) -> bool`` selecting payloads
      (None = every payload). The acceptance e2e marks its premium
      class's request bodies and matches on the marker, so the
      injected degradation hits ONLY priority 0.
    - ``seed`` — the error-draw seed (keyed with the device label, so
      replicas degrade independently but reproducibly).
    """
    if spec is None:
        return runner
    target = spec.get("artifact")
    if target is not None and str(target) != str(artifact_ref):
        return runner
    latency_s = float(spec.get("latency_ms", 0.0)) / 1000.0
    error_rate = float(spec.get("error_rate", 0.0))
    eps = float(spec.get("logit_eps", 0.0))
    if latency_s <= 0 and error_rate <= 0 and eps == 0:
        return runner
    match = spec.get("match")
    import random as _random

    rng = _random.Random(f"{spec.get('seed', 0)}:{device}")

    def degraded(payloads: List[Any]):
        import numpy as np

        hits = [
            i for i, p in enumerate(payloads)
            if match is None or match(p)
        ]
        if hits and latency_s > 0:
            time.sleep(latency_s)
        if hits and error_rate > 0 and rng.random() < error_rate:
            raise RuntimeError(
                "injected engine failure (degradation hook)"
            )
        out = runner(payloads)
        if hits and eps:
            out = [np.asarray(x) for x in list(out)]
            for i in hits:
                out[i] = out[i] + eps
        return out

    # the marker the zero-cost pin asserts is ABSENT on undegraded
    # runners: disabled injection returns the plain runner object
    degraded.degraded = True
    return degraded


def make_engine_runner_factory(
    buckets: Sequence[int],
    *,
    pace_ms: float = 0.0,
    on_engine: Optional[Callable[[Any, Any], None]] = None,
    packed: bool = False,
    packed_impl: str = "unpack",
    resident_models: int = 1,
    model_dirs: Optional[Dict[str, str]] = None,
    on_event: Optional[Callable[..., Any]] = None,
    degrade: Optional[Dict[str, Any]] = None,
) -> Callable[[str, Any], Callable[[List[Any]], Any]]:
    """The real runner factory: ``factory(artifact_dir, device) ->
    runner`` builds an :class:`~bdbnn_tpu.serve.engine.InferenceEngine`
    with its weights placed and its buckets AOT-warmed on that device,
    and returns its batched-predict callable.

    ``packed=True`` keeps the weights 1-bit resident (engine
    ``packed`` mode, nn/packed.py). ``resident_models > 1`` puts a
    :class:`ResidentModelCache` of that capacity behind each replica:
    payloads may then be ``(model_key, image)`` tuples — the
    ``x-model``-routed multi-model path — and the runner groups each
    coalesced batch by model, answers every group from its co-resident
    engine, and reassembles results in arrival order. ``model_dirs``
    maps model keys to artifact dirs (``DEFAULT_MODEL`` falls back to
    the factory's own ``artifact_dir`` argument). Every cache built is
    appended to ``factory.caches`` so the orchestration can assemble
    the verdict's ``resident`` block.

    ``degrade`` (fault injection — canary drills and tests only)
    wraps the runners built for ONE targeted artifact ref with
    :func:`_apply_degradation`; runners for every other ref come back
    unwrapped, so the hook is zero-cost when inactive.

    ``pace_ms > 0`` swaps the engine's compute for a fixed sleep per
    batch (weights never load, nothing compiles): the serving-fabric
    bench mode. On a CPU-simulated mesh every "device" shares the one
    host's cores, so compute-bound throughput cannot scale with
    replica count no matter how good the dispatcher is — pacing
    measures what the POOL adds (dispatch concurrency, queue isolation,
    swap machinery) with a service time that parallelizes the way a
    real per-chip engine does. On-chip sweeps (the r06 recipe) run
    unpaced."""
    import numpy as np

    pace_s = float(pace_ms) / 1000.0
    caches: List[ResidentModelCache] = []

    def factory(artifact_dir: str, device):
        if pace_s > 0:

            def paced(payloads: List[Any]):
                time.sleep(pace_s)
                return [np.zeros((1,), np.float32)] * len(payloads)

            return _apply_degradation(paced, degrade, artifact_dir, device)
        from bdbnn_tpu.serve.engine import InferenceEngine

        def load_model(key: str):
            path = (model_dirs or {}).get(key)
            if path is None:
                if key != DEFAULT_MODEL:
                    raise KeyError(f"unknown model key {key!r}")
                path = artifact_dir
            engine = InferenceEngine(
                path, buckets=buckets, device=device,
                packed=packed, packed_impl=packed_impl,
            )
            if on_engine is not None:
                on_engine(engine, device)  # warmup-seconds hook
            return engine

        cache = ResidentModelCache(
            load_model,
            capacity=max(int(resident_models), 1),
            device=str(device),
            on_event=on_event,
        )
        # one LIVE cache per device: a blue/green swap calls the
        # factory again for the same device, and the retired runner's
        # cache must leave the list with it — keeping it would pin the
        # old version's engines (device weights never freed) and make
        # resident_block aggregate dead caches into the verdict
        for stale in [c for c in caches if c.device == str(device)]:
            caches.remove(stale)
        caches.append(cache)
        cache.get(DEFAULT_MODEL)  # the default model warms eagerly

        def runner(payloads: List[Any]):
            # multi-model path: (model_key, image) tuples grouped by
            # key, each group answered by its co-resident engine, the
            # results reassembled in arrival order
            if payloads and isinstance(payloads[0], tuple):
                groups: Dict[str, List[int]] = {}
                for idx, (key, _img) in enumerate(payloads):
                    groups.setdefault(key or DEFAULT_MODEL, []).append(idx)
                results: List[Any] = [None] * len(payloads)
                for key, idxs in groups.items():
                    engine = cache.get(key)
                    logits = engine.predict_logits(
                        np.stack([payloads[i][1] for i in idxs])
                    )
                    for row, i in enumerate(idxs):
                        results[i] = logits[row]
                return results
            return cache.get(DEFAULT_MODEL).predict_logits(
                np.stack(payloads)
            )

        return _apply_degradation(runner, degrade, artifact_dir, device)

    factory.caches = caches
    return factory


__all__ = [
    "DEFAULT_MODEL",
    "READY",
    "SHIFTING",
    "STOPPED",
    "SWAP_CANARY",
    "SWAP_CANARY_WARMING",
    "SWAP_DONE",
    "SWAP_FAILED",
    "SWAP_IDLE",
    "SWAP_ROLLED_BACK",
    "SWAP_ROLLING_BACK",
    "SWAP_SHIFTING",
    "SWAP_TERMINAL_STATES",
    "SWAP_WARMING",
    "UNHEALTHY",
    "WARMING",
    "PoolAdmin",
    "Replica",
    "ReplicaPool",
    "ResidentModelCache",
    "first_warm_capture",
    "make_engine_runner_factory",
    "replica_stats_fields",
    "resident_block",
    "single_engine_resident_block",
]
