"""Dynamic micro-batching: coalesce requests, bound the queues, drain.

Serving individual requests through a batched accelerator engine wants
four properties the naive loop lacks:

1. **Coalescing under a deadline** — single requests are batched up to
   the engine's largest bucket, but never held past ``max_delay_ms``
   from the first request's enqueue: throughput from batching, with a
   hard cap on the latency it can add.
2. **Bounded queues + load shedding** — every request queue has a fixed
   capacity; when a priority's queue is full, ``submit`` raises
   :class:`LoadShedError` IMMEDIATELY (explicit rejection the client
   can retry against) instead of growing without bound until the
   process dies far from the overload that caused it.
3. **Priority classes + strict-priority dequeue** — requests carry a
   priority (0 = most important); each class gets its OWN bounded
   queue, and the worker always drains the highest class first when
   assembling a batch. Under overload the low classes shed while the
   high class keeps its latency: per-class isolation on the queue
   bound, per-batch preference on the dequeue. The HTTP front end
   (serve/http.py) maps the ``x-priority`` request header onto this.
4. **Graceful drain** — ``drain()`` latches a flag (the same
   latched-flag pattern as ``train/resilience.py``'s
   ``PreemptionHandler``: the signal moment only sets state; the worker
   loop observes it at a safe boundary), after which new submits are
   shed but every request already accepted is answered before the
   worker exits. SIGTERM → ``drain()`` is wired by the ``serve-bench``
   and ``serve-http`` CLIs through a ``PreemptionHandler``.

Stdlib-only: the engine is injected as a callable, so the batcher (and
its tests) never need a JAX backend.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

from bdbnn_tpu.obs.rtrace import (
    pop_future_answered_by,
    pop_future_timing,
    set_future_answered_by,
)


class LoadShedError(RuntimeError):
    """The request was rejected — queue full or batcher draining."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(f"request shed: {reason}")


class _Request:
    __slots__ = ("payload", "priority", "future", "t_enqueue", "trace")

    def __init__(self, payload, priority: int = 0, trace=None):
        self.payload = payload
        self.priority = priority
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()
        # optional obs.rtrace.RequestTrace riding the request: the
        # batcher stamps its queue/coalesce/dispatch/compute stages at
        # the owning sites; None costs one attribute read per boundary
        self.trace = trace


class MicroBatcher:
    """Coalescing request batcher in front of a batch-callable engine.

    ``runner(batch_list) -> results`` receives the payloads of one
    coalesced batch and returns one result per payload (any indexable).
    A runner may instead return a ``concurrent.futures.Future``
    resolving to the results (**async dispatch** — the replica-pool
    path, serve/pool.py): the worker chains the per-request futures to
    it and immediately collects the NEXT batch, so N pool replicas
    execute batches concurrently instead of serializing behind one
    blocking runner call. Completion accounting moves to the chained
    callback; :meth:`drain` additionally waits for every dispatched
    batch to resolve, so the no-unresolved-Future guarantee holds in
    both modes. ``on_batch(stats_dict)`` (optional) fires after every
    executed batch — the serve CLIs use it to emit ``serve`` events.

    ``priorities`` (default 1) sets the number of priority classes;
    ``submit(payload, priority=p)`` with ``0 <= p < priorities``
    enqueues into class p's own bounded queue (bound = ``max_queue``
    PER class). ``stats()["per_priority"]`` is the one source of truth
    for per-class occupancy — the HTTP stats endpoint, the live
    ``watch`` events and the SLO verdict all read it.
    """

    def __init__(
        self,
        runner: Callable[[List[Any]], Any],
        *,
        max_batch: int = 32,
        max_queue: int = 128,
        max_delay_ms: float = 5.0,
        on_batch: Optional[Callable[[Dict[str, Any]], None]] = None,
        priorities: int = 1,
        max_pending_batches: Optional[int] = None,
    ):
        if max_batch <= 0 or max_queue <= 0:
            raise ValueError("max_batch and max_queue must be positive")
        if priorities <= 0:
            raise ValueError("priorities must be >= 1")
        if max_pending_batches is not None and max_pending_batches <= 0:
            raise ValueError("max_pending_batches must be >= 1")
        self.runner = runner
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.on_batch = on_batch
        self.priorities = int(priorities)
        # one bounded deque per priority class, 0 drained first; all
        # guarded by _lock (the Condition's lock)
        self._qs: List[deque] = [deque() for _ in range(self.priorities)]  # guarded-by: _lock
        # latched drain flag (resilience.py pattern): set once, observed
        # by the worker at batch boundaries and by submit immediately
        self._draining = threading.Event()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # set by the WORKER, under _lock, after its final queue sweep:
        # once True no request can enter a queue, so no accepted
        # Future can ever be left unresolved (see _worker/submit)
        self._dead = False  # guarded-by: _lock
        # shared counters, written by submit / the worker / settle
        # callbacks and snapshotted by stats():
        # guarded-by: _lock: shed, completed, batches, occupancy_sum,
        # guarded-by: _lock: max_queue_depth_seen, _shed_p, _completed_p,
        # guarded-by: _lock: _max_depth_p, _occupancy_sum_p
        self.shed = 0
        self.completed = 0
        self.batches = 0
        self.occupancy_sum = 0.0
        self.max_queue_depth_seen = 0
        # per-priority counters, index = priority class
        self._shed_p = [0] * self.priorities
        self._completed_p = [0] * self.priorities
        self._max_depth_p = [0] * self.priorities
        self._occupancy_sum_p = [0.0] * self.priorities
        # async-dispatched batches (runner returned a Future) not yet
        # resolved — drain() waits for this to hit zero.
        # max_pending_batches is the async-mode BACKPRESSURE bound: the
        # worker stops assembling new batches while this many are
        # outstanding, so requests wait in the per-priority FRONT
        # queues (where strict-priority dequeue still applies) instead
        # of FIFO-ing into downstream replica queues — and an overload
        # sheds at submit() like the blocking path, never by failing
        # batches that were already accepted. The pool orchestrations
        # set it to ~2x the replica count: one batch executing + one
        # queued per replica, bounding priority inversion to what is
        # already dispatched.
        self._pending_async = 0  # guarded-by: _lock
        self.max_pending_batches = max_pending_batches
        self._thread = threading.Thread(
            target=self._worker, name="micro-batcher", daemon=True
        )
        self._thread.start()

    # -- client side ---------------------------------------------------

    def submit(self, payload, priority: int = 0, trace=None) -> Future:
        """Enqueue one request into its priority class; returns its
        Future. Raises :class:`LoadShedError` when draining or that
        class's queue is full — never blocks the caller on a full
        queue; raises ``ValueError`` on an out-of-range priority (a
        malformed header must be rejected by the CALLER with a 400,
        not silently reclassified here). ``trace`` (optional,
        obs/rtrace.py) rides the request so the worker can stamp its
        queue-wait and coalesce spans at the sites that own them.

        The enqueue happens under ``_lock``, the same lock the worker's
        drain-exit holds for its final queue sweep + ``_dead`` latch: a
        request either lands before that sweep (and is answered or
        explicitly failed by it) or observes ``_dead`` and is shed here
        — an accepted Future can never be left unresolved."""
        p = int(priority)
        if not 0 <= p < self.priorities:
            raise ValueError(
                f"priority must be in [0, {self.priorities}), got {p}"
            )
        req = _Request(payload, p, trace=trace)
        with self._cv:
            if self._dead or self._draining.is_set():
                self.shed += 1
                self._shed_p[p] += 1
                raise LoadShedError("draining")
            if len(self._qs[p]) >= self.max_queue:
                self.shed += 1
                self._shed_p[p] += 1
                raise LoadShedError("queue full")
            self._qs[p].append(req)
            depth = len(self._qs[p])
            self._max_depth_p[p] = max(self._max_depth_p[p], depth)
            self.max_queue_depth_seen = max(
                self.max_queue_depth_seen, sum(len(q) for q in self._qs)
            )
            self._cv.notify()
        return req.future

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Latch the drain flag, answer every accepted request, stop the
        worker. Returns True when the worker exited within ``timeout``.
        Idempotent.

        The no-unresolved-Future guarantee is enforced by the worker's
        exit protocol (final queue sweep + ``_dead`` latch under the
        submit lock, see :meth:`_worker`), not by timing here — plus,
        in async-dispatch mode, by waiting out every batch Future the
        runner handed back (the pool resolves them as it drains)."""
        self._draining.set()
        with self._cv:
            self._cv.notify_all()  # wake a worker parked on an empty queue
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        self._thread.join(timeout)
        clean = not self._thread.is_alive()
        with self._cv:
            while self._pending_async > 0:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            clean = clean and self._pending_async == 0
        return clean

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            batches = max(self.batches, 1)
            return {
                "completed": self.completed,
                "shed": self.shed,
                "batches": self.batches,
                "mean_occupancy": round(self.occupancy_sum / batches, 4),
                "queue_depth": sum(len(q) for q in self._qs),
                "max_queue_depth_seen": self.max_queue_depth_seen,
                # the AGGREGATE capacity the aggregate depth is bounded
                # by (per-class bound x classes) — `peak depth N of
                # bound M` must be a coherent pair in every consumer;
                # the per-class bound rides alongside
                "max_queue": self.max_queue * self.priorities,
                "max_queue_per_class": self.max_queue,
                "priorities": self.priorities,
                # the one per-class source of truth: verdict + watch +
                # /statsz all read this, never private counters
                "per_priority": [
                    {
                        "priority": p,
                        "queue_depth": len(self._qs[p]),
                        "max_queue_depth_seen": self._max_depth_p[p],
                        "completed": self._completed_p[p],
                        "shed": self._shed_p[p],
                        "mean_occupancy": round(
                            self._occupancy_sum_p[p] / batches, 4
                        ),
                    }
                    for p in range(self.priorities)
                ],
            }

    # -- worker side ---------------------------------------------------

    def _pop_highest(self) -> Optional[_Request]:  # requires-lock: _lock
        """Pop the oldest request of the HIGHEST nonempty class (strict
        priority: class 1 is only served when class 0 is empty). Caller
        holds ``_lock``."""
        for q in self._qs:
            if q:
                return q.popleft()
        return None

    def _collect(self) -> List[_Request]:
        """One coalesced batch: block for the first request (waking to
        re-check the drain flag), then gather — highest priority first —
        until the batch is full or the first request's deadline passes."""
        with self._cv:
            while True:
                first = self._pop_highest()
                if first is not None:
                    break
                if self._draining.is_set():
                    return []
                self._cv.wait(timeout=0.02)
        if first.trace is not None:
            # queue stage ends at pickup — everything since submit
            # (including any async-backpressure hold that kept the
            # worker from assembling a batch) is queue wait
            first.trace.stamp("queue")
        batch = [first]
        deadline = first.t_enqueue + self.max_delay_s
        while len(batch) < self.max_batch:
            with self._cv:
                nxt = self._pop_highest()
                if nxt is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._draining.is_set():
                        # deadline passed (or draining: flush what we
                        # have — latency over occupancy on the way out)
                        break
                    self._cv.wait(timeout=remaining)
                    nxt = self._pop_highest()
            if nxt is not None:
                if nxt.trace is not None:
                    nxt.trace.stamp("queue")
                batch.append(nxt)
            elif time.monotonic() >= deadline or self._draining.is_set():
                break
        return batch

    def _worker(self) -> None:
        while True:
            if self.max_pending_batches is not None:
                # async backpressure: hold off assembling the next batch
                # until the pool has headroom. This holds THROUGH drain
                # too — the pool keeps resolving batches, pending falls,
                # and every queued request is dispatched in (priority)
                # order rather than shed against a full replica queue.
                with self._cv:
                    while self._pending_async >= self.max_pending_batches:
                        self._cv.wait(timeout=0.02)
            batch = self._collect()
            if not batch:
                # drain exit: latch _dead and sweep stragglers ATOMICALLY
                # with respect to submit's enqueue — a request either
                # landed before this sweep (failed here, explicitly) or
                # its submit observes _dead and sheds. Futures are
                # resolved outside the lock; nothing else touches them.
                with self._cv:
                    stragglers = []
                    for q in self._qs:
                        while q:
                            stragglers.append(q.popleft())
                    self.shed += len(stragglers)
                    for req in stragglers:
                        self._shed_p[req.priority] += 1
                    self._dead = True
                for req in stragglers:
                    if not req.future.done():
                        req.future.set_exception(LoadShedError("draining"))
                return
            t0 = time.monotonic()
            for r in batch:
                if r.trace is not None:
                    # coalesce stage ends when the batch dispatches
                    r.trace.stamp("coalesce")
            try:
                results = self.runner([r.payload for r in batch])
            except Exception as e:
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
                continue
            if isinstance(results, Future):
                # async dispatch (replica pool): chain settlement to the
                # batch Future and collect the NEXT batch immediately —
                # this is what lets N replicas run concurrently behind
                # one front batcher
                with self._cv:
                    self._pending_async += 1

                def _chain(f: Future, batch=batch, t0=t0):
                    try:
                        exc = None if f.cancelled() else f.exception()
                        if f.cancelled() or exc is not None:
                            e = exc or LoadShedError("draining")
                            for r in batch:
                                if not r.future.done():
                                    r.future.set_exception(e)
                        else:
                            self._settle(
                                batch, f.result(), t0, time.monotonic(),
                                timing=pop_future_timing(f),
                                answered_by=pop_future_answered_by(f),
                            )
                    finally:
                        with self._cv:
                            self._pending_async -= 1
                            self._cv.notify_all()

                results.add_done_callback(_chain)
                continue
            self._settle(batch, results, t0, time.monotonic())

    def _settle(
        self, batch, results, t0: float, t1: float, timing=None,
        answered_by=None,
    ) -> None:
        """Distribute one executed batch's results and account it —
        shared by the synchronous runner path and the async-dispatch
        callback. ``timing`` is the replica pool's measured
        (dispatch_ms, compute_ms) split riding the batch Future
        (obs/rtrace.py); the sync path has no dispatch hop, so the
        whole runner wall is the compute stage. ``answered_by`` (the
        version label the replica worker attached) is relabeled onto
        every per-request future so the front end can attribute each
        request to the cohort that ANSWERED it (serve/canary.py)."""
        # stage accounting BEFORE the futures resolve: a waiter waking
        # on set_result must observe a fully-stamped trace
        for r in batch:
            tr = r.trace
            if tr is None:
                continue
            if timing is not None:
                tr.add("dispatch", timing[0])
                tr.add("compute", timing[1])
                tr.sync()
            else:
                tr.stamp("compute")
        for i, r in enumerate(batch):
            # done() guard: a client may have cancel()ed its Future
            # (set_result would raise InvalidStateError); a runner
            # returning too few results must fail THAT future, not
            # kill the worker thread for good
            try:
                if not r.future.done():
                    if answered_by is not None:
                        # before set_result, so the waiter always
                        # observes the label (the timing-split rule)
                        set_future_answered_by(r.future, answered_by)
                    r.future.set_result(results[i])
            except Exception as e:
                if not r.future.done():
                    r.future.set_exception(e)
        with self._cv:
            per_prio_n = [0] * self.priorities
            for r in batch:
                per_prio_n[r.priority] += 1
            self.completed += len(batch)
            self.batches += 1
            self.occupancy_sum += len(batch) / self.max_batch
            for p in range(self.priorities):
                self._completed_p[p] += per_prio_n[p]
                self._occupancy_sum_p[p] += (
                    per_prio_n[p] / self.max_batch
                )
            stats = {
                "batch_size": len(batch),
                "occupancy": round(len(batch) / self.max_batch, 4),
                "queue_depth": sum(len(q) for q in self._qs),
                "queue_depth_by_priority": [
                    len(q) for q in self._qs
                ],
                "batch_by_priority": per_prio_n,
                "run_ms": round((t1 - t0) * 1000.0, 3),
                "oldest_wait_ms": round(
                    (t0 - batch[0].t_enqueue) * 1000.0, 3
                ),
                "completed": self.completed,
                "shed": self.shed,
            }
        if self.on_batch is not None:
            try:
                self.on_batch(stats)
            except Exception:
                pass  # telemetry must never kill the serving loop


__all__ = ["LoadShedError", "MicroBatcher"]
