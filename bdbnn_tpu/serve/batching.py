"""Dynamic micro-batching: coalesce requests, bound the queue, drain.

Serving individual requests through a batched accelerator engine wants
three properties the naive loop lacks:

1. **Coalescing under a deadline** — single requests are batched up to
   the engine's largest bucket, but never held past ``max_delay_ms``
   from the first request's enqueue: throughput from batching, with a
   hard cap on the latency it can add.
2. **Bounded queue + load shedding** — the request queue has a fixed
   capacity; when it is full, ``submit`` raises :class:`LoadShedError`
   IMMEDIATELY (explicit rejection the client can retry against)
   instead of growing without bound until the process dies far from the
   overload that caused it.
3. **Graceful drain** — ``drain()`` latches a flag (the same
   latched-flag pattern as ``train/resilience.py``'s
   ``PreemptionHandler``: the signal moment only sets state; the worker
   loop observes it at a safe boundary), after which new submits are
   shed but every request already accepted is answered before the
   worker exits. SIGTERM → ``drain()`` is wired by the ``serve-bench``
   CLI through a ``PreemptionHandler``.

Stdlib-only: the engine is injected as a callable, so the batcher (and
its tests) never need a JAX backend.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional


class LoadShedError(RuntimeError):
    """The request was rejected — queue full or batcher draining."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(f"request shed: {reason}")


class _Request:
    __slots__ = ("payload", "future", "t_enqueue")

    def __init__(self, payload):
        self.payload = payload
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()


class MicroBatcher:
    """Coalescing request batcher in front of a batch-callable engine.

    ``runner(batch_list) -> results`` receives the payloads of one
    coalesced batch and returns one result per payload (any indexable).
    ``on_batch(stats_dict)`` (optional) fires after every executed
    batch — the serve-bench CLI uses it to emit ``serve`` events.
    """

    def __init__(
        self,
        runner: Callable[[List[Any]], Any],
        *,
        max_batch: int = 32,
        max_queue: int = 128,
        max_delay_ms: float = 5.0,
        on_batch: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        if max_batch <= 0 or max_queue <= 0:
            raise ValueError("max_batch and max_queue must be positive")
        self.runner = runner
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.on_batch = on_batch
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=max_queue)
        # latched drain flag (resilience.py pattern): set once, observed
        # by the worker at batch boundaries and by submit immediately
        self._draining = threading.Event()
        self._lock = threading.Lock()
        # set by the WORKER, under _lock, after its final queue sweep:
        # once True no request can enter the queue, so no accepted
        # Future can ever be left unresolved (see _worker/submit)
        self._dead = False
        self.shed = 0
        self.completed = 0
        self.batches = 0
        self.occupancy_sum = 0.0
        self.max_queue_depth_seen = 0
        self._thread = threading.Thread(
            target=self._worker, name="micro-batcher", daemon=True
        )
        self._thread.start()

    # -- client side ---------------------------------------------------

    def submit(self, payload) -> Future:
        """Enqueue one request; returns its Future. Raises
        :class:`LoadShedError` when draining or the queue is full —
        never blocks the caller on a full queue.

        The enqueue happens under ``_lock``, the same lock the worker's
        drain-exit holds for its final queue sweep + ``_dead`` latch: a
        request either lands before that sweep (and is answered or
        explicitly failed by it) or observes ``_dead`` and is shed here
        — an accepted Future can never be left unresolved."""
        req = _Request(payload)
        with self._lock:
            if self._dead or self._draining.is_set():
                self.shed += 1
                raise LoadShedError("draining")
            try:
                self._q.put_nowait(req)
            except queue.Full:
                self.shed += 1
                raise LoadShedError("queue full") from None
            self.max_queue_depth_seen = max(
                self.max_queue_depth_seen, self._q.qsize()
            )
        return req.future

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Latch the drain flag, answer every accepted request, stop the
        worker. Returns True when the worker exited within ``timeout``.
        Idempotent.

        The no-unresolved-Future guarantee is enforced by the worker's
        exit protocol (final queue sweep + ``_dead`` latch under the
        submit lock, see :meth:`_worker`), not by timing here."""
        self._draining.set()
        self._thread.join(timeout)
        return not self._thread.is_alive()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "completed": self.completed,
                "shed": self.shed,
                "batches": self.batches,
                "mean_occupancy": round(
                    self.occupancy_sum / max(self.batches, 1), 4
                ),
                "queue_depth": self._q.qsize(),
                "max_queue_depth_seen": self.max_queue_depth_seen,
                "max_queue": self.max_queue,
            }

    # -- worker side ---------------------------------------------------

    def _collect(self) -> List[_Request]:
        """One coalesced batch: block for the first request (waking to
        re-check the drain flag), then gather until the batch is full or
        the first request's deadline passes."""
        while True:
            try:
                first = self._q.get(timeout=0.02)
                break
            except queue.Empty:
                if self._draining.is_set():
                    return []
        batch = [first]
        deadline = first.t_enqueue + self.max_delay_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # deadline passed: take whatever is already queued, but
                # wait no further
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
                continue
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _worker(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                # drain exit: latch _dead and sweep stragglers ATOMICALLY
                # with respect to submit's enqueue — a request either
                # landed before this sweep (failed here, explicitly) or
                # its submit observes _dead and sheds. Futures are
                # resolved outside the lock; nothing else touches them.
                with self._lock:
                    stragglers = []
                    while True:
                        try:
                            stragglers.append(self._q.get_nowait())
                        except queue.Empty:
                            break
                    self.shed += len(stragglers)
                    self._dead = True
                for req in stragglers:
                    if not req.future.done():
                        req.future.set_exception(LoadShedError("draining"))
                return
            t0 = time.monotonic()
            try:
                results = self.runner([r.payload for r in batch])
            except Exception as e:
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
                continue
            t1 = time.monotonic()
            for i, r in enumerate(batch):
                # done() guard: a client may have cancel()ed its Future
                # (set_result would raise InvalidStateError); a runner
                # returning too few results must fail THAT future, not
                # kill the worker thread for good
                try:
                    if not r.future.done():
                        r.future.set_result(results[i])
                except Exception as e:
                    if not r.future.done():
                        r.future.set_exception(e)
            with self._lock:
                self.completed += len(batch)
                self.batches += 1
                self.occupancy_sum += len(batch) / self.max_batch
                stats = {
                    "batch_size": len(batch),
                    "occupancy": round(len(batch) / self.max_batch, 4),
                    "queue_depth": self._q.qsize(),
                    "run_ms": round((t1 - t0) * 1000.0, 3),
                    "oldest_wait_ms": round(
                        (t0 - batch[0].t_enqueue) * 1000.0, 3
                    ),
                    "completed": self.completed,
                    "shed": self.shed,
                }
            if self.on_batch is not None:
                try:
                    self.on_batch(stats)
                except Exception:
                    pass  # telemetry must never kill the serving loop


__all__ = ["LoadShedError", "MicroBatcher"]
