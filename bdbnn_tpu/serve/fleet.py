"""Cross-host serving fleet: the health-routed HTTP router.

Pools scale chips; fleets scale hosts. Every host runs the EXISTING
``serve-http`` path unchanged — its own replica pool (or single
engine), its own admission control, its own drain contract — and this
module puts a thin router process in front of N of them, spreading
traffic by health and occupancy and surviving the failure modes a
single host never sees: a host dying mid-flash-crowd, a torn artifact
replica, a peer that stopped answering. Stdlib-only (sockets + threads,
no aiohttp), the same zero-dependency stance as the rest of
``bdbnn_tpu/serve``.

The contract, piece by piece:

- **Health-routed dispatch.** A prober polls every host's ``/readyz``
  (and ``/statsz`` for the live table) on an interval and runs the
  SHARED warmup→debounce→hysteresis state machine
  (:class:`bdbnn_tpu.obs.health.DetectorState` — one discipline for
  training, canary and fleet health): the first ``warmup`` probes are
  never judged, a connect/timeout breach must persist ``debounce``
  consecutive probes before the host is declared ``dead``, and a dead
  host re-arms on the first successful probe. A host answering
  ``/readyz`` 503 is not dead — it is ``draining`` (SIGTERM landed) or
  ``warming`` (AOT compile running) and is routed around WITHOUT
  burning the failure detector. Dispatch picks the ready host with the
  lowest in-flight count (occupancy), round-robin on ties.

- **Retry with backoff, never a drop.** A request the router accepted
  is answered, period. A transport failure against one host — connect
  refused, per-attempt timeout, connection reset mid-exchange — is
  retried on a DIFFERENT host (up to ``max_attempts`` distinct hosts)
  with exponential backoff between attempts, and every retry is
  ledgered per host and per cause (``connect`` / ``timeout`` /
  ``reset``). Inference is deterministic and idempotent, so a request
  whose connection died after the backend started computing is safe to
  re-execute on a peer; the accounting counts it ONCE — against the
  host that actually answered. Only when every attempt is exhausted
  does the router answer 503 itself (``no host available``,
  ``retry-after`` set) — an explicit shed, never a dropped connection.

- **Load-shed taxonomy preserved end-to-end.** A WELL-FORMED backend
  response is relayed verbatim, never retried: a 429 ``over_quota`` is
  THIS tenant's fault on every host (same quotas), and a 503
  ``draining``/``queue full`` re-executed elsewhere would turn one
  explicit shed into a duplicate execution the moment the first host
  answers after all. The router's per-priority ledger files relayed
  sheds under the backend's own reason (parsed from the shed body), so
  the fleet verdict's shed taxonomy reads exactly like a single
  host's.

- **Graceful degradation.** A draining host (``/readyz`` 503
  ``draining``) leaves the dispatch set immediately, bleeds its
  in-flight work (the host's own drain contract answers everything it
  accepted), and the fleet keeps serving at reduced capacity. The
  router's own ``drain()`` does the same one level up: latch, answer
  every in-flight proxy, then close the listener.

- **Fleet blue/green.** ``POST /fleet/swap`` (or ``--swap-at`` under a
  scenario) rolls the fleet host by host: first the target version is
  replicated into every host's registry by digest-verified
  :meth:`~bdbnn_tpu.serve.registry.ArtifactRegistry.pull`, then each
  host's ``POST /admin/swap`` fires and the router POLLS that host's
  swap state machine to a TERMINAL state
  (:data:`bdbnn_tpu.serve.pool.SWAP_TERMINAL_STATES`) before touching
  the next — a rollout can never take two hosts out of dispatch at
  once.

- **Fleet-consistent verdicts.** The run ends in a v6 SLO verdict
  whose ``fleet`` block carries the per-host ledgers (proxied /
  completed / relayed / retries-by-cause / probe transitions / p99),
  and those ledgers must SUM to the client's own observation —
  ``ledger_consistent`` is computed, not asserted, and the
  zero-dropped gate is now summed across hosts. ``compare`` judges
  ``serve_fleet_dropped`` (zero tolerance), ``serve_fleet_retry_rate``
  and ``serve_fleet_host_p99_spread``.

Events: the ``fleet`` kind (obs/events.py), phases ``start`` /
``ready`` / ``probe`` / ``proxy`` / ``pull`` / ``swap`` / ``stats`` /
``drain`` / ``stop``; the verdict lands as the usual ``serve``
``verdict`` event so ``watch``/``summarize``/``compare`` consume a
fleet run through the same pipeline as every other serving run.
"""

from __future__ import annotations

import json
import os
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from bdbnn_tpu.obs.capacity import FleetCapacityWindows
from bdbnn_tpu.obs.events import jsonsafe
from bdbnn_tpu.obs.health import DetectorState
from bdbnn_tpu.obs.rtrace import (
    STAGE_HEADER,
    TRACE_HEADER,
    FleetTracer,
    HostStatsWindows,
    encode_trace_context,
)
from bdbnn_tpu.serve.http import PREDICT_PATH, _REASONS
from bdbnn_tpu.serve.loadgen import _pct, recv_response

# retry causes the per-host ledger buckets by — the transport-failure
# taxonomy (a backend RESPONSE is never a retry cause: it is relayed)
RETRY_CAUSES = ("connect", "timeout", "reset")

# host states the prober assigns. "ready" is the only dispatchable one;
# "draining"/"warming" are the host's own explicit /readyz words (alive,
# not dispatchable — they never burn the failure detector); "dead" is
# the detector's debounced verdict on connect/timeout breaches.
HOST_WARMING = "warming"
HOST_READY = "ready"
HOST_DRAINING = "draining"
HOST_DEAD = "dead"


def backoff_s(attempt: int, base_s: float, cap_s: float) -> float:
    """The retry backoff schedule: ``base * 2^attempt`` capped — the
    exact sequence the schedule-pin test asserts, so a refactor cannot
    silently turn bounded backoff into a hot retry loop."""
    return min(base_s * (2.0 ** max(int(attempt), 0)), cap_s)


def _read_request(
    rfile, max_body: int
) -> Optional[Tuple[str, str, Dict[str, str], Optional[bytes]]]:
    """One HTTP/1.1 request off a buffered reader; None at EOF; body
    None signals over-``max_body`` (the caller answers 413)."""
    line = rfile.readline()
    if not line:
        return None
    parts = line.decode("latin-1").strip().split()
    if len(parts) != 3:
        raise ValueError(f"malformed request line: {line!r}")
    method, path, _version = parts
    headers: Dict[str, str] = {}
    while True:
        h = rfile.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        name, _, value = h.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    n = int(headers.get("content-length", 0) or 0)
    if n > max_body:
        return method, path, headers, None
    body = rfile.read(n) if n else b""
    if len(body) != n:
        raise ValueError("truncated request body")
    return method, path, headers, body


def _head_bytes(
    status: int, headers: Dict[str, str], body: bytes, *, close: bool
) -> bytes:
    head = (
        f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
        f"content-length: {len(body)}\r\n"
    )
    for name, value in headers.items():
        head += f"{name}: {value}\r\n"
    if close:
        head += "connection: close\r\n"
    return head.encode("latin-1") + b"\r\n"


class HostState:
    """One backend host's live record inside the router.

    Every mutable field is guarded by the ROUTER's lock, shared into
    each host record (one lock for the whole table: the proxy path
    touches a host's counters and the router's aggregates in one
    logical step, and a per-host lock would just invite ordering
    bugs). The DetectorState is deliberately NOT guarded: the probe
    loop is its single writer by construction.
    """

    def __init__(
        self, idx: int, label: str, host: str, port: int,
        lock: "threading.RLock", warmup: int, debounce: int,
    ):
        self.idx = idx
        self.label = label
        self.host = host
        self.port = int(port)
        self._lock = lock  # the router's lock, shared — see class doc
        self.detector = DetectorState(warmup, debounce)  # prober-only
        # guarded-by: _lock: state, server_id, inflight, proxied, completed, responses_by_status, retries, retried_away, consecutive_failures, backoff_until, probes, transitions, lat_ms, last_statsz
        self.state = HOST_WARMING
        self.server_id: Optional[str] = None
        self.inflight = 0
        self.proxied = 0
        self.completed = 0
        self.responses_by_status: Dict[int, int] = {}
        self.retries: Dict[str, int] = {c: 0 for c in RETRY_CAUSES}
        self.retried_away = 0
        self.consecutive_failures = 0
        self.backoff_until = 0.0
        self.probes = 0
        self.transitions = 0
        self.lat_ms: List[float] = []
        self.last_statsz: Optional[Dict[str, Any]] = None

    def snapshot(self) -> Dict[str, Any]:  # requires-lock: _lock
        """The per-host row of ``/statsz``, the ``fleet`` stats event
        and the verdict's fleet block — one shape, three consumers."""
        relayed_other = sum(
            n for s, n in self.responses_by_status.items()
            if s not in (200, 429, 503)
        )
        return {
            "host": self.host,
            "port": self.port,
            "state": self.state,
            "server_id": self.server_id,
            "inflight": self.inflight,
            "proxied": self.proxied,
            "completed": self.completed,
            "relayed_429": self.responses_by_status.get(429, 0),
            "relayed_503": self.responses_by_status.get(503, 0),
            "relayed_other": relayed_other,
            "retries": dict(self.retries),
            "retried_away": self.retried_away,
            "probes": self.probes,
            "probe_transitions": self.transitions,
            "p99_ms": _pct(sorted(self.lat_ms), 99.0),
        }


class _RouterServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    router: "FleetRouter"


class _RouterHandler(socketserver.StreamRequestHandler):
    # idle keep-alive connections are reaped so drain converges; a
    # torn keep-alive is exactly what the load generator's
    # reconnect-once path exists for
    timeout = 60.0

    def handle(self) -> None:
        router = self.server.router
        while True:
            try:
                req = _read_request(self.rfile, router.max_body_bytes)
            except (ValueError, OSError):
                break
            if req is None:
                break
            method, path, headers, body = req
            close = headers.get("connection", "").lower() == "close"
            try:
                if body is None:
                    self.wfile.write(_head_bytes(
                        413, {"content-type": "application/json"},
                        b'{"error": "payload too large"}', close=True,
                    ) + b'{"error": "payload too large"}')
                    self.wfile.flush()
                    break
                status, out_headers, out_body = router.handle_request(
                    method, path, headers, body
                )
                do_close = close or router.draining
                self.wfile.write(
                    _head_bytes(
                        status, out_headers, out_body, close=do_close
                    )
                    + out_body
                )
                self.wfile.flush()
            except (OSError, ConnectionError):
                break
            if close or router.draining:
                break


class FleetRouter:
    """The fleet's traffic spreader: N backend serve-http hosts behind
    one listener, health-routed, retry-ledgered, swap-orchestrated.

    Thread shape: one acceptor thread (``serve_forever``), one handler
    thread per client connection (proxying is blocking I/O), one
    prober thread, and at most one fleet-swap thread. All shared state
    sits behind ONE reentrant lock (each :class:`HostState` shares
    it); the drain latch and stop flags are Events.
    """

    def __init__(
        self,
        hosts: List[Tuple[str, int]],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        priorities: int = 3,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 1.0,
        proxy_timeout_s: float = 60.0,
        max_attempts: int = 3,
        backoff_base_s: float = 0.025,
        backoff_cap_s: float = 0.25,
        health_warmup: int = 0,
        health_debounce: int = 2,
        retry_after_s: int = 1,
        max_body_bytes: int = 16 * 2**20,
        registry: str = "",
        host_registries: Tuple[str, ...] = (),
        swap_host_timeout_s: float = 120.0,
        on_event: Optional[Callable[..., Any]] = None,
        tracer: Optional[FleetTracer] = None,
        scrape_timeout_s: float = 0.5,
        scrape_stale_after: int = 3,
        scrape_window: int = 64,
    ):
        self.host = host
        self.port = int(port)
        self.priorities = max(int(priorities), 1)
        self.default_priority = self.priorities - 1
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.max_attempts = max(int(max_attempts), 1)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.retry_after_s = int(retry_after_s)
        self.max_body_bytes = int(max_body_bytes)
        self.registry_root = registry
        self.host_registries = tuple(host_registries)
        self.swap_host_timeout_s = float(swap_host_timeout_s)
        self.on_event = on_event
        # cross-host tracing (obs/rtrace.py): when wired, every
        # proxied predict carries a minted trace context and its
        # router stages + the backend's stitched stage block roll into
        # the v7 fleet_attribution. The scrape plane (HostStatsWindows,
        # internally locked) merges each host's /statsz rtrace block
        # on the stats pump's bounded-timeout schedule.
        self.tracer = tracer
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.scrape = HostStatsWindows(
            window=int(scrape_window),
            stale_after=int(scrape_stale_after),
        )
        # the fleet capacity merge (obs/capacity.py): per-host scraped
        # capacity blocks under the SAME staleness discipline as the
        # rtrace windows above — internally locked, fed only by the
        # scrape pump
        self.capacity = FleetCapacityWindows(
            stale_after=int(scrape_stale_after),
        )
        # ONE reentrant lock for the whole router (host table included):
        # reentrancy makes an accidental nested acquire harmless, and
        # the condition below shares it so drain's inflight-zero wait
        # cannot race a proxy between accounting and decrement
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self.hosts = [
            HostState(
                i, f"h{i}", h, p, self._lock,
                health_warmup, health_debounce,
            )
            for i, (h, p) in enumerate(hosts)
        ]
        # guarded-by: _lock: _inflight, _rr, _counts, _lats, _arrival_stamps, _unrouteable, _shed_draining, _t_started, _t_drained, _swap, _swap_thread
        self._inflight = 0
        # observed proxy arrival stamps: the MEASURED offered rate
        # serve-mode fleet verdicts report (never a config figure)
        self._arrival_stamps: List[float] = []
        self._rr = 0
        self._counts: List[Dict[str, int]] = [
            {"submitted": 0, "completed": 0, "failed": 0,
             "rejected": 0, "shed_draining": 0, "shed_over_quota": 0,
             "shed_queue_full": 0, "shed_unavailable": 0}
            for _ in range(self.priorities)
        ]
        self._lats: List[List[float]] = [
            [] for _ in range(self.priorities)
        ]
        self._unrouteable = 0
        self._shed_draining = 0
        self._t_started: Optional[float] = None
        self._t_drained: Optional[float] = None
        self._swap: Optional[Dict[str, Any]] = None
        self._draining = threading.Event()
        self._stop = threading.Event()
        self._server: Optional[_RouterServer] = None
        self._server_thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._swap_thread: Optional[threading.Thread] = None

    # -- events ---------------------------------------------------------

    def _emit(self, kind: str, **fields: Any) -> None:
        if self.on_event is None:
            return
        try:
            self.on_event(kind, **fields)
        except Exception:
            pass  # telemetry must never take the dispatch path down

    # -- lifecycle ------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def start(self) -> Tuple[str, int]:
        srv = _RouterServer((self.host, self.port), _RouterHandler)
        srv.router = self
        self._server = srv
        self.port = srv.server_address[1]
        self._server_thread = threading.Thread(
            target=srv.serve_forever, name="fleet-router", daemon=True,
            kwargs={"poll_interval": 0.1},
        )
        self._server_thread.start()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="fleet-prober", daemon=True
        )
        self._probe_thread.start()
        return self.host, self.port

    def wait_ready(self, timeout: float = 60.0) -> bool:
        """Block until at least one host probes ready (dispatch is
        possible) or the timeout lapses."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if any(h.state == HOST_READY for h in self.hosts):
                    return True
            if self._stop.is_set():
                return False
            time.sleep(0.02)
        return False

    def wait_swap(self, timeout: Optional[float] = None) -> bool:
        """Block until an in-flight fleet swap settles (the http.py
        ``admin.wait`` precedent): a rollout legitimately still
        rolling when the load generator finishes must reach its
        terminal state — and its terminal event — BEFORE the drain
        snapshots the verdict, or a successful run reads as a torn
        'shifting' failure."""
        with self._lock:
            t = self._swap_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def drain(self, timeout: float = 30.0) -> bool:
        """Latch the drain flag (new predicts answered 503 draining),
        wait for every in-flight proxy's response to be written, stop
        the prober, then close the listener. Idempotent."""
        self._draining.set()
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            clean = self._inflight == 0
            if self._t_drained is None:
                self._t_drained = time.perf_counter()
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(max(deadline - time.monotonic(), 0.1))
        with self._lock:
            swap_thread = self._swap_thread
        if swap_thread is not None:
            swap_thread.join(max(deadline - time.monotonic(), 0.1))
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if self._server_thread is not None:
            self._server_thread.join(
                max(deadline - time.monotonic(), 0.1)
            )
            clean = clean and not self._server_thread.is_alive()
        return clean

    # -- health probing -------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            for h in self.hosts:
                if self._stop.is_set():
                    return
                self._probe_host(h)

    def _probe_host(self, h: HostState) -> None:
        ok = False
        word = None
        statsz: Optional[Dict[str, Any]] = None
        try:
            status, _headers, body = self._request_host(
                h, "GET", "/readyz", {}, b"",
                timeout=self.probe_timeout_s,
            )
            ok = True  # the host ANSWERED: alive, whatever the status
            try:
                word = (json.loads(body) or {}).get("state")
            except Exception:
                word = None
            if word not in (HOST_READY, HOST_DRAINING, HOST_WARMING):
                word = HOST_READY if status == 200 else HOST_WARMING
        except (OSError, ValueError, ConnectionError):
            ok = False
        if ok:
            # /statsz is ENRICHMENT only (live table, server_id): a
            # host that answers /readyz is alive, and a failed or slow
            # statsz fetch must never feed the failure detector — it
            # just leaves the last snapshot stale
            try:
                s_status, _h2, s_body = self._request_host(
                    h, "GET", "/statsz", {}, b"",
                    timeout=self.probe_timeout_s,
                )
                if s_status == 200:
                    statsz = json.loads(s_body)
            except (OSError, ValueError, ConnectionError):
                statsz = None
        transition = None
        with h._lock:
            h.probes += 1
            # the shared warmup -> debounce -> hysteresis discipline:
            # fired exactly once per dead episode; a successful probe
            # is the recovery signal that re-arms the detector
            fired = h.detector.update(not ok, recovered=ok)
            if ok:
                new = word
                if statsz is not None:
                    h.last_statsz = {
                        k: statsz.get(k)
                        for k in ("inflight", "requests_seen", "state")
                    }
                    h.server_id = statsz.get("server_id")
                h.consecutive_failures = 0
                h.backoff_until = 0.0
            elif fired or h.state == HOST_DEAD:
                new = HOST_DEAD
            else:
                # breach not yet debounced: keep the last known state
                # (one blip must not evict a host mid-flash-crowd)
                new = h.state
            if new != h.state:
                h.transitions += 1
                old, h.state = h.state, new
                transition = (old, new)
        if transition is not None:
            self._emit(
                "fleet", phase="probe", host=h.label,
                state_from=transition[0], state_to=transition[1],
            )

    # -- dispatch -------------------------------------------------------

    def _pick_host(self, exclude) -> Optional[HostState]:
        """Least-occupancy over the ready set (round-robin on ties),
        skipping hosts in retry backoff unless nothing else is left —
        a backoff host beats an unconditional shed."""
        now = time.monotonic()
        with self._lock:
            ready = [
                h for h in self.hosts
                if h.label not in exclude and h.state == HOST_READY
            ]
            usable = [h for h in ready if now >= h.backoff_until]
            pool = usable or ready
            if not pool:
                return None
            self._rr += 1
            rr = self._rr
            return min(
                pool,
                key=lambda h: (h.inflight, (h.idx - rr) % len(self.hosts)),
            )

    def _request_host(
        self, h: HostState, method: str, path: str,
        headers: Dict[str, str], body: bytes, *, timeout: float,
    ) -> Tuple[int, Dict[str, str], bytes]:
        """One request/response exchange with a backend over a fresh
        connection (connection: close — the backend's drain grace then
        never waits on the router's idle keep-alives)."""
        return self._request_host_timed(
            h, method, path, headers, body, timeout=timeout
        )[:3]

    def _request_host_timed(
        self, h: HostState, method: str, path: str,
        headers: Dict[str, str], body: bytes, *, timeout: float,
    ) -> Tuple[int, Dict[str, str], bytes, float, float]:
        """:meth:`_request_host` plus the trace's connect/exchange
        split, both measured on the ROUTER's clock: ``connect_ms`` is
        the TCP establish, ``exchange_ms`` the wall from first request
        byte sent to response fully received. The trace charges the
        attempt's full wall (not these timers alone) so the stage sum
        reconciles with the trace total by construction; the
        ``network`` stage is the wall's residual minus the backend's
        self-reported span — never a cross-clock subtract."""
        t0 = time.perf_counter()
        sock = socket.create_connection((h.host, h.port), timeout=timeout)
        t_conn = time.perf_counter()
        try:
            sock.settimeout(timeout)
            head = (
                f"{method} {path} HTTP/1.1\r\n"
                f"host: {h.host}:{h.port}\r\n"
                "connection: close\r\n"
            )
            for name in (
                "x-priority", "x-tenant", "x-model", "content-type",
                TRACE_HEADER,
            ):
                if name in headers:
                    head += f"{name}: {headers[name]}\r\n"
            head += f"content-length: {len(body)}\r\n\r\n"
            sock.sendall(head.encode("latin-1") + body)
            rfile = sock.makefile("rb")
            try:
                status, rheaders, rbody = recv_response(rfile)
            finally:
                rfile.close()
            return (
                status, rheaders, rbody,
                (t_conn - t0) * 1000.0,
                (time.perf_counter() - t_conn) * 1000.0,
            )
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _proxy_predict(
        self, headers: Dict[str, str], body: bytes, priority: int,
        trace=None,
    ) -> Tuple[int, Dict[str, str], bytes]:
        """The retry/relay core: try distinct hosts on transport
        failures (ledgered per host and per cause, backoff between
        attempts); RELAY the first well-formed response verbatim.

        When tracing is wired, the router stages stamp here:
        ``probe_wait`` (request parse -> first pick, i.e. the cv wait
        on probed router state), ``pick`` per host selection,
        ``connect``/``network`` on the successful attempt, and one
        ``retry_hop`` per failed attempt — the attempt's wall PLUS the
        backoff sleep it incurred, charged to that attempt."""
        tried: set = set()
        if trace is not None:
            trace.stamp("probe_wait")
            headers = dict(headers)
            headers[TRACE_HEADER] = encode_trace_context(
                trace.trace_id, trace.seq, priority,
                headers.get("x-tenant"),
            )
        for attempt in range(self.max_attempts):
            h = self._pick_host(tried)
            if h is None:
                break
            if trace is not None:
                trace.stamp("pick")
            tried.add(h.label)
            with h._lock:
                h.inflight += 1
                h.proxied += 1
            t0 = time.perf_counter()
            cause = None
            try:
                (
                    status, rheaders, rbody, connect_ms, exchange_ms,
                ) = self._request_host_timed(
                    h, "POST", PREDICT_PATH, headers, body,
                    timeout=self.proxy_timeout_s,
                )
            except (socket.timeout, TimeoutError):
                cause = "timeout"
            except ConnectionRefusedError:
                cause = "connect"
            except (ConnectionError, BrokenPipeError):
                cause = "reset"
            except (OSError, ValueError):
                cause = "connect"
            if cause is not None:
                with h._lock:
                    h.inflight -= 1
                    h.retries[cause] = h.retries.get(cause, 0) + 1
                    h.retried_away += 1
                    h.consecutive_failures += 1
                    # the failing host backs off from dispatch on its
                    # own schedule, independent of the probe cadence
                    h.backoff_until = time.monotonic() + backoff_s(
                        h.consecutive_failures - 1,
                        self.backoff_base_s, self.backoff_cap_s,
                    )
                self._emit(
                    "fleet", phase="proxy", host=h.label,
                    cause=cause, attempt=attempt,
                )
                # bounded backoff before the NEXT attempt: the peer
                # retry must not arrive as a synchronized hammer. No
                # sleep after the final attempt — the shed is already
                # decided and the wait would only delay the client's
                # explicit 503 (and drain convergence)
                if attempt < self.max_attempts - 1:
                    time.sleep(backoff_s(
                        attempt, self.backoff_base_s,
                        self.backoff_cap_s,
                    ))
                if trace is not None:
                    trace.stamp("retry_hop")
                continue
            t_done = time.perf_counter()
            lat_ms = (t_done - t0) * 1000.0
            if trace is not None:
                # reconciliation by construction (the backend header's
                # own discipline, one hop up): charge the attempt's
                # FULL wall since the last stamp — `connect` gets the
                # measured TCP establish, and the residual (exchange
                # plus the router's own pre-connect/post-read slop the
                # socket timer cannot see) goes to the stitcher, which
                # splits it into backend span + `network`. The stage
                # sum then equals the trace wall exactly, so the
                # cross-hop identity never flags scheduler slop on a
                # contended box as misattribution.
                elapsed_ms = (t_done - trace._last) * 1000.0
                conn_ms = min(connect_ms, elapsed_ms)
                trace.add("connect", conn_ms)
                trace.attempts = attempt + 1
                self.tracer.stitch(
                    trace, elapsed_ms - conn_ms,
                    rheaders.get(STAGE_HEADER), h.label,
                )
                trace.sync(at=t_done)
            with h._lock:
                h.inflight -= 1
                h.consecutive_failures = 0
                h.backoff_until = 0.0
                h.responses_by_status[status] = (
                    h.responses_by_status.get(status, 0) + 1
                )
                if status == 200:
                    h.completed += 1
                    h.lat_ms.append(lat_ms)
            out_headers = {
                "content-type": rheaders.get(
                    "content-type", "application/json"
                ),
                "x-served-by": h.label,
            }
            if "retry-after" in rheaders:
                out_headers["retry-after"] = rheaders["retry-after"]
            self._ledger_response(priority, status, rbody, lat_ms=(
                lat_ms if status == 200 else None
            ))
            return status, out_headers, rbody
        # every attempt exhausted (or zero dispatchable hosts): the
        # router's OWN explicit shed — an answer, never a hang
        with self._lock:
            self._unrouteable += 1
            self._counts[priority]["shed_unavailable"] += 1
        body_out = json.dumps(
            {"error": "no host available", "tried": sorted(tried)}
        ).encode()
        return 503, {
            "content-type": "application/json",
            "retry-after": str(self.retry_after_s),
        }, body_out

    def _ledger_response(
        self, priority: int, status: int, rbody: bytes,
        lat_ms: Optional[float],
    ) -> None:
        """File one RELAYED response under the backend's own shed
        taxonomy (parsed from the shed body), so the fleet verdict's
        per-priority blocks read exactly like a single host's."""
        reason = None
        if status in (429, 503):
            try:
                reason = (json.loads(rbody) or {}).get("error")
            except Exception:
                reason = None
        with self._lock:
            c = self._counts[priority]
            if status == 200:
                c["completed"] += 1
                if lat_ms is not None:
                    self._lats[priority].append(lat_ms)
            elif status == 429:
                c["shed_over_quota"] += 1
            elif status == 503:
                if reason == "draining":
                    c["shed_draining"] += 1
                elif reason == "no healthy replica":
                    c["shed_unavailable"] += 1
                else:
                    c["shed_queue_full"] += 1
            elif 400 <= status < 500:
                c["rejected"] += 1
            else:
                c["failed"] += 1

    # -- request routing ------------------------------------------------

    def handle_request(
        self, method: str, path: str, headers: Dict[str, str],
        body: bytes,
    ) -> Tuple[int, Dict[str, str], bytes]:
        json_h = {"content-type": "application/json"}

        def respond(status: int, obj: Any, **extra: str):
            return status, {**json_h, **extra}, json.dumps(
                jsonsafe(obj)
            ).encode()

        if method == "GET" and path == "/healthz":
            with self._lock:
                ready = sum(
                    1 for h in self.hosts if h.state == HOST_READY
                )
            return respond(200, {
                "status": "ok",
                "role": "fleet-router",
                "hosts_ready": ready,
                "hosts_total": len(self.hosts),
                "draining": self.draining,
            })
        if method == "GET" and path == "/readyz":
            if self.draining:
                return respond(
                    503, {"state": "draining"},
                    **{"retry-after": str(self.retry_after_s)},
                )
            with self._lock:
                any_ready = any(
                    h.state == HOST_READY for h in self.hosts
                )
            if not any_ready:
                return respond(
                    503, {"state": "warming"},
                    **{"retry-after": str(self.retry_after_s)},
                )
            return respond(200, {"state": "ready"})
        if method == "GET" and path in ("/statsz", "/fleet/hosts"):
            return respond(200, self.stats())
        if method == "GET" and path == "/fleet/swap":
            with self._lock:
                swap = dict(self._swap) if self._swap else {
                    "state": "idle"
                }
            return respond(200, swap)
        if method == "POST" and path == "/fleet/swap":
            try:
                spec = json.loads(body) if body else {}
            except Exception as e:
                return respond(400, {"error": f"undecodable body: {e}"})
            if not isinstance(spec, dict):
                return respond(
                    400, {"error": "swap body must be a JSON object"}
                )
            status, payload = self.start_fleet_swap(spec)
            return respond(status, payload)
        if method == "POST" and path == PREDICT_PATH:
            return self._handle_predict(headers, body)
        return respond(404, {"error": f"no route {method} {path}"})

    def _handle_predict(
        self, headers: Dict[str, str], body: bytes
    ) -> Tuple[int, Dict[str, str], bytes]:
        raw_p = headers.get("x-priority")
        if raw_p is None:
            priority = self.default_priority
        else:
            try:
                priority = int(raw_p)
            except ValueError:
                priority = -1
            if not 0 <= priority < self.priorities:
                return 400, {"content-type": "application/json"}, (
                    json.dumps({
                        "error": "bad x-priority",
                        "want": f"int in [0, {self.priorities})",
                        "got": raw_p,
                    }).encode()
                )
        # the trace begins BEFORE the cv block so probe_wait charges
        # the router-state wait a request actually experienced
        trace = (
            self.tracer.begin(priority, headers.get("x-tenant"))
            if self.tracer is not None else None
        )
        with self._cv:
            if self._t_started is None:
                # the verdict wall clock starts at the first routed
                # request — warmup idle must not dilute throughput
                self._t_started = time.perf_counter()
            self._counts[priority]["submitted"] += 1
            self._arrival_stamps.append(time.perf_counter())
            if self._draining.is_set():
                self._counts[priority]["shed_draining"] += 1
                self._shed_draining += 1
                if trace is not None:
                    self.tracer.abort(trace)
                return 503, {
                    "content-type": "application/json",
                    "retry-after": str(self.retry_after_s),
                }, b'{"error": "draining"}'
            self._inflight += 1
        try:
            status, out_headers, out_body = self._proxy_predict(
                headers, body, priority, trace
            )
            if trace is not None:
                # only a relayed 200 is a served request; a relayed
                # shed/reject or the router's own 503 must never read
                # as a fast fleet serve
                if status == 200:
                    self.tracer.finish(trace)
                else:
                    self.tracer.abort(trace)
            return status, out_headers, out_body
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    # -- fleet blue/green ----------------------------------------------

    def start_fleet_swap(
        self, spec: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        """Kick the host-by-host rollout thread. 202 accepted / 409
        already rolling / 400 bad spec."""
        if "version" not in spec and "artifact" not in spec:
            return 400, {
                "error": 'swap body must carry {"version": N} or '
                '{"artifact": dir}',
            }
        with self._lock:
            if self._swap is not None and self._swap.get("state") in (
                "replicating", "shifting"
            ):
                return 409, {
                    "error": "a fleet swap is already in flight",
                    **dict(self._swap),
                }
            # the target set is SNAPSHOTTED at trigger time — the same
            # hosts hosts_total reports, so the done-report's
            # shifted/total ratio cannot disagree with the set that
            # actually shifted when a host transitions mid-rollout
            targets = [
                h for h in self.hosts if h.state == HOST_READY
            ]
            self._swap = {
                "state": "replicating",
                "target": spec.get("version", spec.get("artifact")),
                "hosts_total": len(targets),
                "hosts_shifted": [],
                "error": None,
                "seconds": None,
            }
            snapshot = dict(self._swap)
            # the thread handle is published under the SAME lock as the
            # swap doc: wait_swap/drain racing a just-accepted trigger
            # must see either neither or both, or a verdict could
            # snapshot a legitimately-running swap as torn
            self._swap_thread = threading.Thread(
                target=self._run_fleet_swap, args=(dict(spec), targets),
                name="fleet-swap", daemon=True,
            )
            thread = self._swap_thread
        thread.start()
        return 202, snapshot

    def _run_fleet_swap(
        self, spec: Dict[str, Any], targets: List[HostState]
    ) -> None:
        from bdbnn_tpu.serve.pool import SWAP_TERMINAL_STATES

        t0 = time.monotonic()

        def fail(err: str) -> None:
            with self._lock:
                if self._swap is not None:
                    self._swap["state"] = "failed"
                    self._swap["error"] = err
            self._emit("fleet", phase="swap", state="failed", error=err)

        # 1. replicate: the target version lands in every host registry
        #    by digest-verified pull BEFORE any host is asked to shift —
        #    a torn replica fails the rollout here, with vN fully
        #    serving everywhere
        if self.registry_root and "version" in spec:
            from bdbnn_tpu.serve.registry import ArtifactRegistry

            seen: set = set()
            for root in self.host_registries:
                if root in seen or os.path.abspath(
                    root
                ) == os.path.abspath(self.registry_root):
                    continue
                seen.add(root)
                try:
                    pulled = ArtifactRegistry(root).pull(
                        self.registry_root, int(spec["version"])
                    )
                except Exception as e:
                    fail(f"registry pull into {root!r}: {e}")
                    return
                self._emit(
                    "fleet", phase="pull", host_registry=root,
                    version=int(spec["version"]),
                    pulled=len(pulled),
                )
        with self._lock:
            if self._swap is not None:
                self._swap["state"] = "shifting"
        # 2. host by host, SERIALLY: fire the host's own blue/green and
        #    poll its swap state machine to a terminal state before the
        #    next host — never two hosts out of dispatch at once
        for h in targets:
            # the host's swap state machine runs on ITS OWN thread
            # after the 202 — a poll landing before its first status
            # write would read the PREVIOUS swap's record. Snapshot the
            # pre-trigger status: a terminal state is only attributable
            # to THIS swap once an in-flight state was observed or the
            # status document CHANGED from the snapshot.
            try:
                _s0, _h0, b0 = self._request_host(
                    h, "GET", "/admin/swap", {}, b"",
                    timeout=self.probe_timeout_s * 10,
                )
                before = (json.loads(b0) or {}).get("current") or {}
            except (OSError, ValueError, ConnectionError):
                before = {}
            try:
                status, _hh, rbody = self._request_host(
                    h, "POST", "/admin/swap", {
                        "content-type": "application/json"
                    }, json.dumps(spec).encode(),
                    timeout=self.probe_timeout_s * 10,
                )
            except (OSError, ValueError, ConnectionError) as e:
                fail(f"host {h.label}: swap trigger failed: {e}")
                return
            if status != 202:
                fail(
                    f"host {h.label}: swap rejected (HTTP {status}): "
                    f"{rbody[:200]!r}"
                )
                return
            deadline = time.monotonic() + self.swap_host_timeout_s
            final = None
            seen_inflight = False
            while time.monotonic() < deadline:
                try:
                    s2, _h2, b2 = self._request_host(
                        h, "GET", "/admin/swap", {}, b"",
                        timeout=self.probe_timeout_s * 10,
                    )
                    current = (
                        (json.loads(b2) or {}).get("current") or {}
                    )
                    state = current.get("state")
                except (OSError, ValueError, ConnectionError):
                    current, state = {}, None
                if state is not None and state not in (
                    SWAP_TERMINAL_STATES
                ):
                    seen_inflight = True
                elif state in SWAP_TERMINAL_STATES and (
                    seen_inflight or current != before
                ):
                    final = state
                    break
                time.sleep(0.2)
            if final != "done":
                fail(
                    f"host {h.label}: swap ended in state {final!r} "
                    f"(want 'done' within {self.swap_host_timeout_s}s)"
                )
                return
            with self._lock:
                if self._swap is not None:
                    self._swap["hosts_shifted"].append(h.label)
            self._emit(
                "fleet", phase="swap", state="shifted", host=h.label,
            )
        seconds = round(time.monotonic() - t0, 3)
        shifted = {h.label for h in targets}
        # hosts OUTSIDE the trigger-time ready set (warming, draining,
        # dead) were not shifted and still serve the previous version
        # if they rejoin — the done report names them so a partial
        # rollout can never masquerade as full coverage
        unshifted = [
            h.label for h in self.hosts if h.label not in shifted
        ]
        with self._lock:
            if self._swap is not None:
                self._swap["state"] = "done"
                self._swap["seconds"] = seconds
                self._swap["hosts_unshifted"] = unshifted
        self._emit(
            "fleet", phase="swap", state="done", seconds=seconds,
            hosts_shifted=len(targets), hosts_unshifted=unshifted,
        )

    # -- reporting ------------------------------------------------------

    def scrape_host_stats(self) -> None:
        """One merge pass of the fleet metrics plane: GET every host's
        ``/statsz`` with the scrape's OWN bounded timeout and fold the
        ``rtrace`` block into that host's rolling windows. A wedged or
        dead host costs at most ``scrape_timeout_s`` and one failure
        count — it can never stall the pump; after ``stale_after``
        consecutive failures its window reads stale and drops out of
        the merged view. Called from the stats pump, never from the
        request path."""
        for h in self.hosts:
            if self._stop.is_set():
                return
            try:
                status, _, rbody = self._request_host(
                    h, "GET", "/statsz", {}, b"",
                    timeout=self.scrape_timeout_s,
                )
                block = None
                cap_block = None
                if status == 200:
                    payload = json.loads(rbody) or {}
                    block = payload.get("rtrace")
                    cap_block = payload.get("capacity")
                if isinstance(block, dict):
                    self.scrape.record(h.label, block)
                else:
                    self.scrape.record_failure(h.label)
                # the capacity merge follows the same discipline but
                # keeps its own staleness book: a host serving rtrace
                # without a capacity block (pre-v8) goes stale HERE
                # without poisoning the rtrace windows, and vice versa
                self.capacity.record(h.label, cap_block)
            except Exception:
                self.scrape.record_failure(h.label)
                self.capacity.record_failure(h.label)

    def stats(self) -> Dict[str, Any]:
        hosts: Dict[str, Any] = {}
        for h in self.hosts:
            with h._lock:
                hosts[h.label] = h.snapshot()
        with self._lock:
            ready = sum(
                1 for h in self.hosts if h.state == HOST_READY
            )
            swap = dict(self._swap) if self._swap else None
            out = {
                "role": "fleet-router",
                "draining": self.draining,
                "hosts_total": len(self.hosts),
                "hosts_ready": ready,
                "inflight": self._inflight,
                "unrouteable": self._unrouteable,
                "router_shed_draining": self._shed_draining,
                "hosts": hosts,
                "swap": swap,
            }
        # the live fleet metrics plane: the router's own cross-host
        # trace windows plus the per-host scraped windows (both
        # internally locked — never under the router lock above)
        out["rtrace"] = (
            self.tracer.stats() if self.tracer is not None else None
        )
        out["host_windows"] = self.scrape.snapshot()
        # the fleet-merged capacity view: per-host demand/headroom/burn
        # summaries + the merged-over-fresh-hosts totals — what the
        # router's own /statsz serves one level up
        out["capacity"] = self.capacity.snapshot()
        return jsonsafe(out)

    def accounting(self) -> Dict[str, Any]:
        """The post-drain ledger the fleet verdict is built from —
        the same shape as the HTTP front end's, so the verdict
        assembly reads identically one layer up."""
        with self._lock:
            t_end = self._t_drained or time.perf_counter()
            wall_s = (
                t_end - self._t_started
                if self._t_started is not None else 0.0
            )
            stamps = self._arrival_stamps
            measured_rate = None
            if len(stamps) >= 2 and stamps[-1] > stamps[0]:
                # (n-1) inter-arrival gaps over their observed span:
                # what actually hit the router, not a config knob
                measured_rate = round(
                    (len(stamps) - 1) / (stamps[-1] - stamps[0]), 4
                )
            return {
                "wall_s": wall_s,
                "latencies_ms_by_priority": [
                    sorted(l) for l in self._lats
                ],
                "counts_by_priority": [dict(c) for c in self._counts],
                "measured_rate_rps": measured_rate,
            }

    def capacity_block(self) -> Dict[str, Any]:
        """The fleet verdict's v8 ``capacity`` block: the per-host
        summaries + the merged-over-fresh-hosts view, with the three
        flat gates ``compare`` judges (``burn_rate_max``,
        ``headroom_rps``, ``demand_shed_ratio_max``) at the top level
        — same contract as a single host's block."""
        snap = self.capacity.snapshot()
        merged = snap["merged"]
        return {
            "fleet": snap,
            "burn_rate_max": merged["burn_rate_max"],
            "headroom_rps": merged["headroom_rps"],
            "demand_shed_ratio_max": merged["demand_shed_ratio_max"],
        }

    def fleet_block(
        self, client: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """The verdict's v6 ``fleet`` block: per-host ledgers + fleet
        totals + the consistency judgment against the client's own
        observation (computed, never assumed)."""
        stats = self.stats()
        hosts = stats["hosts"]
        with self._lock:
            submitted = sum(c["submitted"] for c in self._counts)
            unrouteable = self._unrouteable
            shed_draining = self._shed_draining
            swap = dict(self._swap) if self._swap else None
        completed_total = sum(h["completed"] for h in hosts.values())
        relayed_total = sum(
            h["relayed_429"] + h["relayed_503"] + h["relayed_other"]
            for h in hosts.values()
        )
        retries_total = sum(
            sum(h["retries"].values()) for h in hosts.values()
        )
        p99s = [
            h["p99_ms"] for h in hosts.values()
            if h["p99_ms"] is not None
        ]
        spread = (
            round(max(p99s) / max(min(p99s), 1e-9), 4)
            if len(p99s) >= 2 else None
        )
        # ledger consistency: every response the client saw must be
        # attributable — per-status — to exactly one host relay or one
        # router-origin shed; None when no client observed the run
        consistent = None
        if client is not None:
            expected: Dict[int, int] = {}
            for h in hosts.values():
                # snapshot carries the split; rebuild the status map
                expected[200] = expected.get(200, 0) + h["completed"]
                expected[429] = expected.get(429, 0) + h["relayed_429"]
                expected[503] = expected.get(503, 0) + h["relayed_503"]
            for hh in self.hosts:
                with hh._lock:
                    for s, n in hh.responses_by_status.items():
                        if s not in (200, 429, 503):
                            expected[s] = expected.get(s, 0) + n
            expected[503] = (
                expected.get(503, 0) + unrouteable + shed_draining
            )
            observed = {
                int(k): v
                for k, v in (client.get("by_status") or {}).items()
            }
            consistent = {
                k: v for k, v in expected.items() if v
            } == {k: v for k, v in observed.items() if v}
        return jsonsafe({
            "n_hosts": len(hosts),
            "hosts": hosts,
            "submitted": submitted,
            "completed_total": completed_total,
            "relayed_total": relayed_total,
            "router_unrouteable": unrouteable,
            "router_shed_draining": shed_draining,
            "retries_total": retries_total,
            "retry_rate": round(retries_total / max(submitted, 1), 6),
            "host_p99_spread": spread,
            "dropped": (
                None if client is None
                else int(client.get("dropped") or 0)
            ),
            "ledger_consistent": consistent,
            "swap": swap,
        })


# ---------------------------------------------------------------------------
# Verdict assembly + the serve-fleet orchestration (the CLI body)
# ---------------------------------------------------------------------------


def fleet_slo_verdict(
    accounting: Dict[str, Any],
    fleet: Dict[str, Any],
    *,
    scenario: str,
    rate: Optional[float],
    seed: int,
    provenance: Optional[Dict[str, Any]] = None,
    preempted: bool = False,
    drained_clean: bool = True,
    client: Optional[Dict[str, Any]] = None,
    slo_p99_ms: float = 0.0,
    fleet_attribution: Optional[Dict[str, Any]] = None,
    capacity: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Build the v7 verdict from the router's ledger: the same
    per-priority skeleton as the HTTP front end's verdict (so
    ``compare``/``summarize`` read a fleet run unchanged) plus the
    ``fleet`` block and — when the router traced — the
    ``fleet_attribution`` cross-host waterfall block."""
    from bdbnn_tpu.serve.loadgen import slo_verdict

    lat_p = accounting["latencies_ms_by_priority"]
    counts_p = accounting["counts_by_priority"]
    per_priority: Dict[str, Dict[str, Any]] = {}
    all_lats: List[float] = []
    for p, (lats, counts) in enumerate(zip(lat_p, counts_p)):
        all_lats += lats
        shed = (
            counts["shed_draining"] + counts["shed_over_quota"]
            + counts["shed_queue_full"] + counts["shed_unavailable"]
        )
        per_priority[str(p)] = {
            "submitted": counts["submitted"],
            "completed": counts["completed"],
            "failed": counts["failed"],
            "rejected": counts["rejected"],
            "shed": shed,
            "shed_draining": counts["shed_draining"],
            "shed_over_quota": counts["shed_over_quota"],
            "shed_queue_full": counts["shed_queue_full"],
            "shed_unavailable": counts["shed_unavailable"],
            "shed_rate": round(shed / max(counts["submitted"], 1), 6),
            "p50_ms": _pct(lats, 50.0),
            "p95_ms": _pct(lats, 95.0),
            "p99_ms": _pct(lats, 99.0),
        }
    submitted = sum(c["submitted"] for c in counts_p)
    completed = sum(c["completed"] for c in counts_p)
    failed = sum(c["failed"] for c in counts_p)
    rejected = sum(c["rejected"] for c in counts_p)
    shed = sum(v["shed"] for v in per_priority.values())
    all_lats.sort()
    slo = None
    if slo_p99_ms > 0:
        p0_p99 = per_priority.get("0", {}).get("p99_ms")
        slo = {
            "p99_ms_target_priority0": slo_p99_ms,
            "p99_ms_priority0": p0_p99,
            "met": bool(p0_p99 is not None and p0_p99 <= slo_p99_ms),
        }
    return slo_verdict(
        {
            "submitted": submitted,
            "completed": completed,
            "shed": shed,
            "failed": failed,
            "rejected": rejected,
            "wall_s": accounting["wall_s"],
            "latencies_ms": all_lats,
        },
        {},  # no batcher at the router: occupancy fields land null
        mode="fleet",
        rate=rate,
        seed=seed,
        provenance=provenance,
        preempted=preempted,
        drained_clean=drained_clean,
        scenario=scenario,
        per_priority=per_priority,
        client=client,
        slo=slo,
        fleet=fleet,
        fleet_attribution=fleet_attribution,
        capacity=capacity,
    )


def parse_hosts(specs) -> List[Tuple[str, int]]:
    """``("127.0.0.1:8100", ...)`` -> [(host, port), ...]."""
    out = []
    for spec in specs:
        host, _, port = str(spec).rpartition(":")
        out.append((host, int(port)))
    return out


def _scenario_bodies(
    artifact_dir: str, seed: int, n_bodies: int = 8
) -> Tuple[List[bytes], int]:
    """Deterministic raw-float32 request bodies shaped from the
    artifact's own manifest — a stdlib read (no weights, no numpy, no
    JAX): the router is a byte proxy and must stay importable
    anywhere."""
    with open(os.path.join(artifact_dir, "artifact.json")) as f:
        artifact = json.load(f)
    size = int(artifact["image_size"])
    n = size * size * 3
    rnd = random.Random(seed)
    bodies = [
        struct.pack(
            f"<{n}f", *(rnd.uniform(-2.0, 2.0) for _ in range(n))
        )
        for _ in range(n_bodies)
    ]
    return bodies, n * 4


def run_serve_fleet(cfg, on_arrival=None) -> Dict[str, Any]:
    """End-to-end fleet serving (the ``serve-fleet`` CLI body).
    ``cfg`` is a :class:`bdbnn_tpu.configs.config.ServeFleetConfig`;
    the backend hosts are EXISTING serve-http processes (brought up by
    an operator, a supervisor, or the fleet e2e's subprocess harness).
    ``on_arrival`` (tests only) observes each offered schedule index —
    the fault-injection hook the SIGTERM-mid-flash-crowd acceptance
    drives its kill through."""
    from bdbnn_tpu.train.resilience import PreemptionHandler

    cfg = cfg.validate()
    with PreemptionHandler() as handler:
        return _serve_fleet_body(cfg, handler, on_arrival)


def _serve_fleet_body(cfg, handler, on_arrival=None) -> Dict[str, Any]:
    import datetime

    from bdbnn_tpu.obs.events import EventWriter
    from bdbnn_tpu.obs.manifest import write_manifest
    from bdbnn_tpu.serve.loadgen import (
        HttpLoadGenerator,
        build_schedule,
        write_verdict_files,
    )

    stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    run_dir = os.path.join(cfg.log_path, stamp)
    os.makedirs(run_dir, exist_ok=True)
    recipe: Dict[str, Any] = {}
    if cfg.artifact:
        try:
            with open(
                os.path.join(cfg.artifact, "artifact.json")
            ) as f:
                art = json.load(f)
            recipe = (art.get("provenance") or {}).get("recipe") or {}
        except (OSError, ValueError):
            recipe = {}
    manifest = write_manifest(
        run_dir,
        {
            "mode": "serve-fleet",
            "hosts": list(cfg.hosts),
            "artifact": (
                os.path.abspath(cfg.artifact) if cfg.artifact else None
            ),
            **{k: v for k, v in recipe.items() if v is not None},
            "priorities": cfg.priorities,
            "scenario": cfg.scenario or None,
            "rate": cfg.rate,
            "requests": cfg.requests,
            "seed": cfg.seed,
            "probe_interval_s": cfg.probe_interval_s,
            "health_warmup": cfg.health_warmup,
            "health_debounce": cfg.health_debounce,
            "max_attempts": cfg.max_attempts,
            "backoff_base_ms": cfg.backoff_base_ms,
            "backoff_cap_ms": cfg.backoff_cap_ms,
            "registry": (
                os.path.abspath(cfg.registry) if cfg.registry else None
            ),
            "swap_to": cfg.swap_to or None,
            "swap_at": cfg.swap_at or None,
        },
    )
    events = EventWriter(
        run_dir, max_bytes=int(cfg.events_max_mb * 2**20)
    )
    tracer = None
    if cfg.rtrace:
        tracer = FleetTracer(
            sample_every=cfg.rtrace_sample_every,
            tail_k=cfg.rtrace_tail_k,
            seed=cfg.seed,
            on_sample=lambda wf: events.emit(
                "rtrace", phase="request", **wf
            ),
        )
    router = FleetRouter(
        parse_hosts(cfg.hosts),
        host=cfg.host,
        port=cfg.port,
        priorities=cfg.priorities,
        probe_interval_s=cfg.probe_interval_s,
        probe_timeout_s=cfg.probe_timeout_s,
        proxy_timeout_s=cfg.proxy_timeout_s,
        max_attempts=cfg.max_attempts,
        backoff_base_s=cfg.backoff_base_ms / 1000.0,
        backoff_cap_s=cfg.backoff_cap_ms / 1000.0,
        health_warmup=cfg.health_warmup,
        health_debounce=cfg.health_debounce,
        registry=cfg.registry,
        host_registries=cfg.host_registries,
        swap_host_timeout_s=cfg.swap_host_timeout_s,
        on_event=lambda kind, **f: events.emit(kind, **f),
        tracer=tracer,
        scrape_timeout_s=cfg.scrape_timeout_s,
        scrape_stale_after=cfg.scrape_stale_after,
    )
    host, port = router.start()
    events.emit(
        "fleet",
        phase="start",
        host=host,
        port=port,
        hosts=list(cfg.hosts),
        priorities=cfg.priorities,
        scenario=cfg.scenario or None,
        rate_rps=cfg.rate if cfg.scenario else None,
        requests=cfg.requests if cfg.scenario else None,
    )
    if not router.wait_ready(timeout=cfg.ready_timeout_s):
        router.drain(timeout=5.0)
        events.emit("fleet", phase="stop", host=host, port=port)
        events.close()
        raise RuntimeError(
            f"no backend host probed ready within "
            f"{cfg.ready_timeout_s:.0f}s — are the serve-http hosts "
            f"up at {list(cfg.hosts)}?"
        )
    events.emit("fleet", phase="ready", host=host, port=port)

    stats_stop = threading.Event()

    def stats_pump():
        while not stats_stop.wait(cfg.stats_interval_s):
            # scrape first so the heartbeat carries windows no older
            # than one pump period; each host is bounded by the
            # scrape's own timeout, so a wedged host cannot stall this
            router.scrape_host_stats()
            events.emit("fleet", phase="stats", **router.stats())

    pump = threading.Thread(target=stats_pump, daemon=True)
    pump.start()

    client_raw = None
    try:
        if cfg.scenario:
            bodies, _nbytes = _scenario_bodies(cfg.artifact, cfg.seed)
            schedule = build_schedule(
                cfg.scenario,
                requests=cfg.requests,
                rate=cfg.rate,
                seed=cfg.seed,
                priorities=cfg.priorities,
                priority_weights=(
                    list(cfg.priority_weights)
                    if cfg.priority_weights else None
                ),
                tenants=cfg.tenants,
                tenant_weights=(
                    list(cfg.tenant_weights)
                    if cfg.tenant_weights else None
                ),
                flash_factor=cfg.flash_factor,
                diurnal_amp=cfg.diurnal_amp,
                heavy_sigma=cfg.heavy_sigma,
                slow_fraction=cfg.slow_fraction,
            )
            hooks: List[Callable[[int], None]] = []
            if on_arrival is not None:
                hooks.append(on_arrival)
            if cfg.swap_at > 0:
                threshold = max(int(cfg.swap_at * len(schedule)), 1)
                swap_fired: List[bool] = []
                from bdbnn_tpu.serve.registry import (
                    looks_like_version,
                    parse_version,
                )

                if cfg.registry and looks_like_version(cfg.swap_to):
                    swap_spec: Dict[str, Any] = {
                        "version": parse_version(cfg.swap_to)
                    }
                else:
                    swap_spec = {"artifact": cfg.swap_to}

                def _swap_hook(i: int) -> None:
                    if not swap_fired and i + 1 >= threshold:
                        swap_fired.append(True)
                        status, payload = router.start_fleet_swap(
                            swap_spec
                        )
                        events.emit(
                            "fleet", phase="swap", state="trigger",
                            at_request=i + 1, of=len(schedule),
                            status=status, **payload,
                        )

                hooks.append(_swap_hook)

            def chained(i: int) -> None:
                for hook in hooks:
                    hook(i)

            gen = HttpLoadGenerator(
                host,
                port,
                schedule,
                body_fn=lambda i: bodies[i % len(bodies)],
                concurrency=cfg.concurrency,
                stop_fn=lambda: handler.preempted,
                slow_chunks=cfg.slow_chunks,
                slow_gap_s=cfg.slow_gap_ms / 1000.0,
                on_arrival=chained if hooks else None,
            )
            client_raw = gen.run()
        else:
            while not handler.preempted:
                time.sleep(0.1)
    finally:
        preempted = handler.preempted
        events.emit(
            "fleet",
            phase="drain",
            signum=handler.signum,
            preempted=preempted,
        )
        # let an in-flight fleet rollout settle before the router
        # winds down — its terminal report belongs in the verdict
        # either way (one full per-host shift budget per host)
        router.wait_swap(
            timeout=cfg.swap_host_timeout_s * max(len(cfg.hosts), 1)
        )
        drained_clean = router.drain(timeout=60.0)
        stats_stop.set()
        pump.join(timeout=5.0)

    fleet = router.fleet_block(client=client_raw)
    accounting = router.accounting()
    verdict = fleet_slo_verdict(
        accounting,
        fleet,
        scenario=cfg.scenario or "fleet",
        # scenario mode records the SCHEDULED rate; serve mode records
        # the MEASURED offered rate from observed arrival stamps —
        # cfg.rate there would fabricate a figure nothing measured
        rate=(
            cfg.rate if cfg.scenario
            else accounting["measured_rate_rps"]
        ),
        seed=cfg.seed,
        provenance={
            "hosts": list(cfg.hosts),
            "artifact": (
                os.path.abspath(cfg.artifact) if cfg.artifact else None
            ),
            "config_hash": None,
            "recipe": recipe,
            "serve_config_hash": manifest.get("config_hash"),
        },
        preempted=preempted,
        drained_clean=drained_clean,
        client=client_raw,
        slo_p99_ms=cfg.slo_p99_ms,
        fleet_attribution=(
            tracer.attribution() if tracer is not None else None
        ),
        capacity=router.capacity_block(),
    )
    events.emit("serve", phase="verdict", **verdict)
    events.emit("fleet", phase="stop", host=host, port=port)
    events.close()
    write_verdict_files(verdict, run_dir, cfg.out)
    return {
        "verdict": verdict,
        "run_dir": run_dir,
        "host": host,
        "port": port,
    }


__all__ = [
    "HOST_DEAD",
    "HOST_DRAINING",
    "HOST_READY",
    "HOST_WARMING",
    "RETRY_CAUSES",
    "FleetRouter",
    "HostState",
    "backoff_s",
    "fleet_slo_verdict",
    "parse_hosts",
    "run_serve_fleet",
]
