"""Torch checkpoint → JAX pytree ingestion for KD teachers.

The reference loads full-precision teachers from torchvision /
``DataParallel``-wrapped torch checkpoints whose keys carry a
``module.`` prefix (reference ``train.py:258-277``,
``utils/KD_loss.py:60``). To reproduce its KD configs on TPU we must be
able to ingest those ``.pth.tar`` state dicts into our float-twin
models.

Key translation (torchvision basic-block ResNet → ``BiResNet`` float
variant):

- ``module.`` prefix stripped;
- ``layer{S}.{B}.conv{i}.weight``     → ``layer{S}_{B}/conv{i}/weight``
  with OIHW → HWIO transpose;
- ``layer{S}.{B}.downsample.0.weight``→ ``.../downsample_conv/weight``;
- ``layer{S}.{B}.downsample.1.*``     → ``.../downsample_bn/*``;
- BN ``weight``/``bias`` → flax ``scale``/``bias`` (params);
  ``running_mean``/``running_var`` → batch_stats ``mean``/``var``;
- ``fc.weight`` (out, in) → transposed flax Dense ``kernel``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def _strip_module(key: str) -> str:
    return key[len("module.") :] if key.startswith("module.") else key


def _translate_key(key: str) -> Tuple[Tuple[str, ...], str]:
    """torch state_dict key → (flax path, kind) where kind ∈
    {conv_w, bn_scale, bn_bias, bn_mean, bn_var, fc_kernel, fc_bias,
    skip}."""
    key = _strip_module(key)
    parts = key.split(".")

    # layerS.B.rest → layerS_B.rest
    if parts[0].startswith("layer") and len(parts) > 2 and parts[1].isdigit():
        parts = [f"{parts[0]}_{parts[1]}"] + parts[2:]

    # downsample.0 → downsample_conv, downsample.1 → downsample_bn
    if "downsample" in parts:
        i = parts.index("downsample")
        sub = parts[i + 1]
        parts = parts[:i] + [
            "downsample_conv" if sub == "0" else "downsample_bn"
        ] + parts[i + 2 :]

    leaf = parts[-1]
    mod = parts[:-1]

    if leaf == "num_batches_tracked":
        return tuple(mod), "skip"
    if mod and mod[-1] == "fc":
        return tuple(mod), "fc_kernel" if leaf == "weight" else "fc_bias"
    if leaf in ("running_mean", "running_var"):
        return tuple(mod), "bn_mean" if leaf == "running_mean" else "bn_var"
    if leaf == "weight":
        return tuple(mod), "bn_scale" if _is_bn(mod) else "conv_w"
    if leaf == "bias":
        return tuple(mod), "bn_bias" if _is_bn(mod) else "conv_bias"
    return tuple(mod), "skip"


def _is_bn(mod_path) -> bool:
    return bool(mod_path) and ("bn" in mod_path[-1])


def _set(tree: Dict, path, value) -> None:
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def convert_torch_state_dict(state_dict) -> Dict[str, Dict]:
    """torch ``state_dict`` (tensors or ndarrays) → flax variables dict
    ``{'params': ..., 'batch_stats': ...}`` for the float-twin models."""
    params: Dict = {}
    batch_stats: Dict = {}
    for key, val in state_dict.items():
        arr = np.asarray(val.detach().cpu().numpy() if hasattr(val, "detach") else val)
        mod, kind = _translate_key(key)
        if kind == "skip":
            continue
        if kind == "conv_w":
            _set(params, (*mod, "weight"), arr.transpose(2, 3, 1, 0))  # OIHW→HWIO
        elif kind == "conv_bias":
            _set(params, (*mod, "bias"), arr)
        elif kind == "bn_scale":
            _set(params, (*mod, "scale"), arr)
        elif kind == "bn_bias":
            _set(params, (*mod, "bias"), arr)
        elif kind == "bn_mean":
            _set(batch_stats, (*mod, "mean"), arr)
        elif kind == "bn_var":
            _set(batch_stats, (*mod, "var"), arr)
        elif kind == "fc_kernel":
            _set(params, (*mod, "kernel"), arr.T)  # (out,in) → (in,out)
        elif kind == "fc_bias":
            _set(params, (*mod, "bias"), arr)
    return {"params": params, "batch_stats": batch_stats}


def load_torch_checkpoint(path: str) -> Dict[str, Dict]:
    """Load a reference-format ``.pth.tar`` checkpoint (dict with a
    ``state_dict`` entry, reference ``train.py:265-269``) or a bare
    state dict, and convert it. Requires the baked-in CPU torch."""
    import torch

    ckpt = torch.load(path, map_location="cpu", weights_only=False)
    state_dict = ckpt.get("state_dict", ckpt) if isinstance(ckpt, dict) else ckpt
    return convert_torch_state_dict(state_dict)
