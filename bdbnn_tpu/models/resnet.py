"""Binary ResNet model families, re-designed TPU-first.

The reference's ``models/`` package is missing from its snapshot
(SURVEY.md §0.2); these models are re-derived from the paper
(arXiv:2204.02004) + the Bi-Real/IR-Net/ReActNet lineage + the hard
behavioral constraints recoverable from reference call sites:

- flagship = ResNet-18-shaped net with 20 convs, 19 of them binarized /
  kurtosis-regularized (the ``all_convs[1:]`` selector at reference
  ``train.py:390-393`` and the 19-entry ``--diffkurt`` tables at
  ``train.py:467-475``);
- binary convs keep latent FP master weights addressable as
  ``float_weight`` (QAT-name fallback, reference ``train.py:404``);
- a ReActNet-style variant (``HardBinaryConv_react``, ``train.py:30``),
  a plain-STE "step 2" variant (``HardBinaryConv``, ``train.py:31``),
  and a CIFAR variant (``HardBinaryConv_cifar``, ``train.py:32``) that
  accepts the annealed EDE estimator (``train.py:409-415``).

Architecture notes (TPU-first, not a torch translation):

- NHWC activations / HWIO kernels throughout — XLA's native TPU conv
  layout, so the ±1 bf16 operands tile straight onto the MXU.
- Each binary 3x3 conv is its own residual unit (Bi-Real "shortcut per
  conv"): ``y = act(BN(BinConv(x)) + shortcut)``. This keeps an FP
  information path around every 1-bit conv — essential for BNN accuracy
  and free on TPU (the add fuses into the conv epilogue).
- Downsample shortcuts use AvgPool + binary 1x1 conv (Bi-Real recipe);
  the FP teacher twins use torchvision's strided 1x1 conv + BN so torch
  teacher checkpoints can be ingested weight-for-weight.
- Module names mirror torch ResNet (``conv1``/``bn1``/``layerS_B``/
  ``downsample_conv``/``fc``) so student/teacher conv pairing and the
  kurtosis hook selection work by path equality, and so that the
  alphabetical flax param ordering reproduces torch's
  ``named_parameters`` conv order (conv1 < conv2 < downsample_conv).

BatchNorm uses torch-default effective momentum (torch 0.1 == flax 0.9)
and eps 1e-5 for teacher-checkpoint parity.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from bdbnn_tpu.nn.layers import (
    BinaryConv,
    BinaryConvCifar,
    BinaryConvReact,
    FloatConv,
    RPReLU,
)

Array = jax.Array

_CONV_CLASSES = {
    "react": BinaryConvReact,
    "step2": BinaryConv,
    "cifar": BinaryConvCifar,
    "float": FloatConv,
}


def _batch_norm(train: bool, name: str, dtype=None) -> nn.BatchNorm:
    # dtype=bfloat16 keeps outputs in the compute dtype while flax
    # computes the batch statistics in float32 (force_float32_reductions
    # default) — the standard TPU mixed-precision recipe: bf16 activations
    # on the MXU, f32 statistics and master params.
    return nn.BatchNorm(
        use_running_average=not train,
        momentum=0.9,
        epsilon=1e-5,
        name=name,
        dtype=dtype,
    )


def _activation(kind: str, name: str) -> Callable[[Array], Array]:
    """Post-add activation of a residual unit.

    - 'rprelu': ReActNet RPReLU (learnable, react variant);
    - 'hardtanh': clip(-1, 1) (IR-Net-style plain/cifar variants — ReLU
      would collapse the following sign() to all-ones);
    - 'identity'.
    """
    if kind == "rprelu":
        mod = RPReLU(name=name)
        return mod
    if kind == "hardtanh":
        return lambda x: jnp.clip(x, -1.0, 1.0)
    if kind == "identity":
        return lambda x: x
    raise ValueError(f"unknown activation kind: {kind!r}")


class BiBasicBlock(nn.Module):
    """Two 3x3 binary residual units with torch-compatible module names.

    Unit 1 (may downsample): ``y = act(BN(conv1(x)) + shortcut(x))``
    Unit 2:                  ``z = act(BN(conv2(y)) + y)``

    The downsample path (when stride > 1 or channels change) is
    AvgPool(2) + 1x1 conv (binary for binary variants, strided FP conv
    for the float teacher) + BN, named ``downsample_conv`` /
    ``downsample_bn`` so it sorts after ``conv2`` like torch's
    ``downsample.0``.
    """

    features: int
    strides: int = 1
    variant: str = "react"  # react | step2 | cifar | float
    act: str = "rprelu"
    dtype: Any = None

    @nn.compact
    def __call__(self, x: Array, train: bool = True, tk=None) -> Array:
        # train/tk accept positional calls: BiResNet's remat wrapper
        # marks train static by argnum (nn.remat static_argnums). The
        # guard keeps block(x, tk) misuse loud now that train binds
        # positionally; a TypeError (not assert) so it survives
        # ``python -O`` (ADVICE r4).
        if not isinstance(train, bool):
            raise TypeError(
                f"train must be a bool, got {type(train).__name__} — "
                "did you pass tk positionally as the second argument?"
            )
        if self.variant == "float":
            return self._float_forward(x, train=train)
        conv_cls = _CONV_CLASSES[self.variant]
        in_features = x.shape[-1]
        needs_ds = self.strides != 1 or in_features != self.features

        # -- shortcut for unit 1
        if needs_ds:
            pooled = nn.avg_pool(
                x,
                window_shape=(self.strides, self.strides),
                strides=(self.strides, self.strides),
            )
            shortcut = conv_cls(
                self.features,
                kernel_size=(1, 1),
                strides=(1, 1),
                name="downsample_conv",
            )(pooled, tk=tk)
            with jax.named_scope("bn_act"):
                shortcut = _batch_norm(
                    train, "downsample_bn", self.dtype
                )(shortcut)
        else:
            shortcut = x

        # -- unit 1 ("bn_act" named scopes: BN + residual add +
        # activation attribute as one semantic category in device
        # traces, obs/trace.py DEVICE_SPANS)
        y = conv_cls(
            self.features,
            kernel_size=(3, 3),
            strides=(self.strides, self.strides),
            name="conv1",
        )(x, tk=tk)
        with jax.named_scope("bn_act"):
            y = _batch_norm(train, "bn1", self.dtype)(y)
            y = y + shortcut
            y = _activation(self.act, "act1")(y)

        # -- unit 2 (identity shortcut)
        z = conv_cls(
            self.features, kernel_size=(3, 3), strides=(1, 1), name="conv2"
        )(y, tk=tk)
        with jax.named_scope("bn_act"):
            z = _batch_norm(train, "bn2", self.dtype)(z)
            z = z + y
            z = _activation(self.act, "act2")(z)
        return z

    def _float_forward(self, x: Array, *, train: bool) -> Array:
        """Torch-faithful torchvision BasicBlock forward for the FP
        teacher twin: relu(bn1(conv1(x))) → bn2(conv2(·)) → add the
        BLOCK INPUT (strided-1x1-conv downsample when shapes change) →
        relu. Structurally different from the Bi-Real units above —
        torchvision teacher checkpoints load weight-for-weight AND
        compute the same logits (torchvision resnet.py BasicBlock;
        reference builds teachers from torchvision at train.py:253-258).
        """
        identity = x
        y = FloatConv(
            self.features,
            kernel_size=(3, 3),
            strides=(self.strides, self.strides),
            name="conv1",
        )(x)
        y = _batch_norm(train, "bn1", self.dtype)(y)
        y = nn.relu(y)
        y = FloatConv(
            self.features, kernel_size=(3, 3), strides=(1, 1), name="conv2"
        )(y)
        y = _batch_norm(train, "bn2", self.dtype)(y)
        if self.strides != 1 or x.shape[-1] != self.features:
            identity = FloatConv(
                self.features,
                kernel_size=(1, 1),
                strides=(self.strides, self.strides),
                name="downsample_conv",
            )(x)
            identity = _batch_norm(train, "downsample_bn", self.dtype)(identity)
        return nn.relu(y + identity)


class FloatBottleneck(nn.Module):
    """Torch-faithful torchvision Bottleneck block for FP teachers.

    The reference's teacher builder accepts ANY torchvision constructor
    name (``train.py:44-48, 253-258``), which includes the
    bottleneck-family resnets (resnet50/101/152) — the most common
    ImageNet KD teachers. This closes that registry-surface gap for the
    float/teacher path (VERDICT r4 "Missing #4"); the *binary* lineage
    stays basic-block only, matching the paper + the 19-conv flagship
    constraint (reference ``train.py:467-475``).

    Forward (torchvision resnet.py Bottleneck, expansion 4):
    ``relu(bn1(conv1_1x1(x)))`` → ``relu(bn2(conv2_3x3_stride(·)))`` →
    ``bn3(conv3_1x1_4w(·))`` → add identity (strided-1x1 downsample when
    shapes change) → relu. Module names keep the torch-import key
    translation working unchanged (``conv3``/``bn3`` translate
    generically; ``downsample.0/.1`` → ``downsample_conv``/
    ``downsample_bn``).
    """

    features: int  # base width; block output is 4x this
    strides: int = 1
    dtype: Any = None

    EXPANSION = 4

    @nn.compact
    def __call__(self, x: Array, train: bool = True, tk=None) -> Array:
        # same positional-binding guard as BiBasicBlock (remat
        # static_argnums marks train by position)
        if not isinstance(train, bool):
            raise TypeError(
                f"train must be a bool, got {type(train).__name__} — "
                "did you pass tk positionally as the second argument?"
            )
        del tk  # float teachers have no binarizer schedule
        out_features = self.features * self.EXPANSION
        identity = x
        y = FloatConv(
            self.features, kernel_size=(1, 1), strides=(1, 1), name="conv1"
        )(x)
        y = _batch_norm(train, "bn1", self.dtype)(y)
        y = nn.relu(y)
        y = FloatConv(
            self.features,
            kernel_size=(3, 3),
            strides=(self.strides, self.strides),
            name="conv2",
        )(y)
        y = _batch_norm(train, "bn2", self.dtype)(y)
        y = nn.relu(y)
        y = FloatConv(
            out_features, kernel_size=(1, 1), strides=(1, 1), name="conv3"
        )(y)
        y = _batch_norm(train, "bn3", self.dtype)(y)
        if self.strides != 1 or x.shape[-1] != out_features:
            identity = FloatConv(
                out_features,
                kernel_size=(1, 1),
                strides=(self.strides, self.strides),
                name="downsample_conv",
            )(x)
            identity = _batch_norm(
                train, "downsample_bn", self.dtype
            )(identity)
        return nn.relu(y + identity)


class BiResNet(nn.Module):
    """Generic basic-block ResNet over binary or float conv variants.

    ``stage_sizes`` blocks per stage; channel widths double per stage
    from ``width``. ``stem='imagenet'`` is the 7x7/2 + maxpool stem,
    ``stem='cifar'`` the 3x3/1 stem. The stem conv and the final
    classifier stay full-precision in every variant — the universal BNN
    convention (first/last layers carry too much information to
    binarize; also why the kurtosis selector skips conv #0).
    """

    stage_sizes: Sequence[int]
    num_classes: int
    width: int = 64
    stem: str = "imagenet"  # imagenet | cifar
    variant: str = "react"  # react | step2 | cifar | float
    act: str = "rprelu"  # rprelu | hardtanh | identity
    dtype: Any = None  # compute dtype (e.g. jnp.bfloat16); params stay f32
    # --twoblock (reference train.py:143-144, consumed inside its missing
    # models package): mix TWO block types through the net — odd-position
    # blocks swap to the partner binary variant (react <-> step2; the two
    # binary-conv families the reference imports at train.py:30-31), with
    # the partner's matching activation. float twins ignore it.
    twoblock: bool = False
    # rematerialize each residual block on the backward pass
    # (jax.checkpoint via nn.remat): activations are recomputed instead
    # of stored, trading ~1/3 more FLOPs for O(depth) less live HBM —
    # the standard TPU recipe for raising per-chip batch on
    # memory-bound shapes (224x224 stem activations dominate).
    # Numerically identity; see tests/test_models.py::TestRemat.
    remat: bool = False
    # 'basic' | 'bottleneck'. Bottleneck is float-teacher only (the
    # torchvision resnet50/101/152 family the reference can name as a
    # teacher, train.py:44-48); the binary lineage is basic-block by
    # construction (19-conv flagship constraint).
    block: str = "basic"

    _TWOBLOCK_PARTNER = {"react": "step2", "step2": "react", "cifar": "react"}
    _VARIANT_ACT = {"react": "rprelu", "step2": "hardtanh", "cifar": "hardtanh"}

    @nn.compact
    def __call__(self, x: Array, *, train: bool = True, tk=None) -> Array:
        if self.dtype is not None:
            x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = FloatConv(
                self.width, kernel_size=(7, 7), strides=(2, 2), name="conv1"
            )(x)
            x = _batch_norm(train, "bn1", self.dtype)(x)
            x = nn.relu(x)
            # torch MaxPool2d(3, stride=2, padding=1) — padding goes to
            # lax.reduce_window NATIVELY (its init value is -inf, so
            # identical math) instead of materializing a -inf-padded
            # copy; the explicit jnp.pad cost a separate pad HLO + a
            # select_and_scatter backward over the enlarged buffer
            # (~10% of device step time in profiles/r04/PROFILE_r04).
            x = nn.max_pool(
                x, window_shape=(3, 3), strides=(2, 2),
                padding=((1, 1), (1, 1)),
            )
        elif self.stem == "cifar":
            x = FloatConv(
                self.width, kernel_size=(3, 3), strides=(1, 1), name="conv1"
            )(x)
            x = _batch_norm(train, "bn1", self.dtype)(x)
            x = nn.relu(x)
        else:
            raise ValueError(f"unknown stem: {self.stem!r}")

        if self.block not in ("basic", "bottleneck"):
            raise ValueError(f"unknown block: {self.block!r}")
        if self.block == "bottleneck" and self.variant != "float":
            raise ValueError(
                "bottleneck blocks are float-teacher only; the binary "
                "families are basic-block by construction"
            )
        # static_argnums=(2,): `train` (0=module, 1=x) selects python
        # branches (BN mode) and must stay static under jax.checkpoint
        base_cls = (
            FloatBottleneck if self.block == "bottleneck" else BiBasicBlock
        )
        block_cls = (
            nn.remat(base_cls, static_argnums=(2,)) if self.remat else base_cls
        )
        block_idx = 0
        for s, num_blocks in enumerate(self.stage_sizes):
            features = self.width * (2**s)
            for b in range(num_blocks):
                strides = 2 if (s > 0 and b == 0) else 1
                if self.block == "bottleneck":
                    variant_kwargs = {}
                else:
                    variant, act = self.variant, self.act
                    if (self.twoblock and variant != "float"
                            and block_idx % 2 == 1):
                        variant = self._TWOBLOCK_PARTNER[variant]
                        act = self._VARIANT_ACT[variant]
                    variant_kwargs = {"variant": variant, "act": act}
                x = block_cls(
                    features=features,
                    strides=strides,
                    dtype=self.dtype,
                    name=f"layer{s + 1}_{b}",
                    **variant_kwargs,
                )(x, train, tk)
                block_idx += 1

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        # logits in f32: softmax/CE and top-k stay numerically stable
        # regardless of the compute dtype
        return x.astype(jnp.float32)


class VGGSmallBinary(nn.Module):
    """Binary VGG-Small (the classic XNOR-Net/BNN CIFAR baseline:
    6 convs 128-128-256-256-512-512 + FC), plain-STE CIFAR variant.
    First conv full-precision as usual."""

    num_classes: int = 10
    variant: str = "cifar"
    dtype: Any = None

    @nn.compact
    def __call__(self, x: Array, *, train: bool = True, tk=None) -> Array:
        if self.dtype is not None:
            x = x.astype(self.dtype)
        conv_cls = _CONV_CLASSES[self.variant]
        widths = (128, 128, 256, 256, 512, 512)
        for i, w in enumerate(widths):
            name = f"conv{i + 1}"
            if i == 0:
                x = FloatConv(w, kernel_size=(3, 3), name=name)(x)
            else:
                x = conv_cls(w, kernel_size=(3, 3), name=name)(x, tk=tk)
            x = _batch_norm(train, f"bn{i + 1}", self.dtype)(x)
            if i % 2 == 1:
                x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
            x = jnp.clip(x, -1.0, 1.0)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return x.astype(jnp.float32)


# ---------------------------------------------------------------------------
# BatchNorm folding (serve-time eval apply)
# ---------------------------------------------------------------------------

# every _batch_norm() in this module uses eps 1e-5 (torch parity); the
# fold below must add back exactly this value
BN_EPS = 1e-5


def _bn_identity_var(eps: float = BN_EPS):
    """The running-variance value that makes flax's eval BatchNorm an
    exact per-channel affine: with ``var = 1 - eps`` the in-graph
    ``rsqrt(var + eps)`` computes ``rsqrt(f32(1 - eps) + f32(eps))``,
    which rounds to exactly 1.0 in float32 — so the folded ``scale`` and
    ``bias`` pass through unscaled."""
    import numpy as np

    return np.float32(1.0) - np.float32(eps)


def bn_identity_stats(channels: int, eps: float = BN_EPS):
    """Identity running stats (``mean`` 0, ``var`` 1-eps) for a folded
    BN of ``channels`` — what serve-time engines rebuild ``batch_stats``
    from (the artifact stores only the folded scale/bias)."""
    import numpy as np

    return {
        "mean": np.zeros((channels,), np.float32),
        "var": np.full((channels,), _bn_identity_var(eps), np.float32),
    }


def _is_bn_stats(node) -> bool:
    return (
        isinstance(node, dict)
        and set(node.keys()) == {"mean", "var"}
        and all(hasattr(v, "shape") for v in node.values())
    )


def fold_batch_norm(variables, eps: float = BN_EPS):
    """Fold every eval-mode BatchNorm into per-channel scale/bias.

    Eval BN computes ``(x - mean) * scale * rsqrt(var + eps) + bias``
    with frozen running stats — two of the four per-channel vectors are
    redundant at serve time. Returns new ``{params, batch_stats}`` where
    each BN's params carry the folded affine

        scale' = scale / sqrt(var + eps)
        bias'  = bias - mean * scale'

    and its running stats are the identity (:func:`bn_identity_stats`),
    so the SAME ``model.apply(..., train=False)`` computes exactly
    ``scale' * x + bias'`` — no model surgery, and the artifact needs to
    ship half the BN state. Within fp32 rounding of the original eval
    forward (pinned per arch in ``tests/test_serve.py``).

    BN nodes are found structurally: any ``batch_stats`` subtree of
    exactly ``{mean, var}`` arrays, whose ``params`` twin holds the
    matching ``{scale, bias}``. Non-BN params pass through untouched.
    """
    import numpy as np

    params = variables.get("params", {})
    stats = variables.get("batch_stats", {}) or {}

    def rec(p_node, s_node):
        if _is_bn_stats(s_node):
            mean = np.asarray(s_node["mean"], np.float32)
            var = np.asarray(s_node["var"], np.float32)
            scale = np.asarray(p_node["scale"], np.float32)
            bias = np.asarray(p_node["bias"], np.float32)
            mul = scale / np.sqrt(var + np.float32(eps))
            new_p = dict(p_node)
            new_p["scale"] = mul
            new_p["bias"] = (bias - mean * mul).astype(np.float32)
            return new_p, bn_identity_stats(len(mean), eps)
        if not isinstance(s_node, dict):
            return p_node, s_node
        new_p = dict(p_node) if isinstance(p_node, dict) else p_node
        new_s = {}
        for k, sv in s_node.items():
            sub_p = p_node.get(k) if isinstance(p_node, dict) else None
            fp, fs = rec(sub_p, sv)
            if isinstance(new_p, dict):
                new_p[k] = fp
            new_s[k] = fs
        return new_p, new_s

    folded_params, folded_stats = rec(params, stats)
    return {"params": folded_params, "batch_stats": folded_stats}


# ---------------------------------------------------------------------------
# Param-tree utilities (conv ordering, weight access)
# ---------------------------------------------------------------------------


def _natural_key(s: str):
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", s)]


def conv_weight_paths(params) -> list:
    """Ordered paths of all 4-D conv kernels (``float_weight`` or
    ``weight``) in the model, stem first — the analogue of the
    reference's ``named_parameters`` conv scan (``train.py:390-400``).

    Paths are tuples of dict keys, e.g. ``('layer1_0', 'conv1',
    'float_weight')``. Ordering is alphabetical-DFS with natural number
    ordering, which by construction of the module names reproduces torch
    conv order: conv1 < conv2 < downsample_conv within a block,
    stem conv1 < layer1_0 < layer1_1 < ... at the top.
    """
    out = []

    def rec(node, prefix):
        if isinstance(node, jax.Array) or hasattr(node, "ndim"):
            if prefix[-1] in ("float_weight", "weight") and node.ndim == 4:
                out.append(tuple(prefix))
            return
        for k in sorted(node.keys(), key=_natural_key):
            rec(node[k], prefix + [k])

    params = params.get("params", params) if isinstance(params, dict) else params
    rec(params, [])
    return out


def get_by_path(params, path):
    node = params.get("params", params) if isinstance(params, dict) else params
    for k in path:
        node = node[k]
    return node


def module_path_str(path) -> str:
    """'layer1_0.conv1' — path string without the trailing param name,
    used for student/teacher pair matching and hook selection."""
    return ".".join(path[:-1])
