"""ImageNet model namespace — reference-API parity shim.

The reference does ``from models import imagenet as imagenet_models``
and builds via ``imagenet_models.__dict__[args.arch](pretrained=...)``
(reference ``train.py:28, 54-56, 253, 285``). Same surface here; the
``pretrained`` flag is accepted (weights come from
``bdbnn_tpu.models.torch_import`` — no network egress).
"""

from bdbnn_tpu.models.registry import imagenet_model_factories

_factories = imagenet_model_factories(num_classes=1000)


def __getattr__(name: str):
    if name in _factories:
        return _factories[name]
    raise AttributeError(name)


def __dir__():
    return sorted(_factories)


globals().update(_factories)
