"""Model registry — constructor-by-name, mirroring the reference's
``models.cifar10.__dict__[arch]()`` / ``models.imagenet.__dict__[arch](
pretrained=...)`` contract (reference ``train.py:50-56, 257, 283-288``).

Binary model naming:

- ``resnet18`` / ``resnet34``   — binary (react variant on imagenet,
  EDE-able plain-STE variant on cifar); what ``--custom_resnet``
  selects in the reference.
- ``resnet18_step2`` etc.       — the "set_2_2" plain-STE variant
  (binarize weights and activations with plain STE).
- ``resnet18_float`` / ``resnet20_float`` — full-precision twins used
  as KD teachers (↔ torchvision models in the reference,
  ``train.py:253-258, 287-288``).
- ``resnet20`` / ``vgg_small``  — CIFAR extras from the classic BNN
  acceptance matrix (BASELINE config 1 uses binary ResNet-20).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict

from bdbnn_tpu.models.resnet import BiResNet, VGGSmallBinary


def _cifar_classes(dataset: str) -> int:
    return {"cifar10": 10, "cifar100": 100}[dataset]


def resolve_dtype(dtype):
    """'float32' | 'bfloat16' | None | jnp dtype → jnp dtype or None
    (None ≡ float32 compute; the RunConfig.dtype knob funnels here)."""
    if dtype is None or dtype == "float32":
        return None
    if isinstance(dtype, str):
        import jax.numpy as jnp

        if dtype == "bfloat16":
            return jnp.bfloat16
        raise ValueError(f"unknown dtype {dtype!r}; use float32|bfloat16")
    return dtype


def _make_cifar(name, stage_sizes, width, variant, act, num_classes,
                dtype=None, twoblock=False, remat=False):
    return BiResNet(
        stage_sizes=stage_sizes,
        num_classes=num_classes,
        width=width,
        stem="cifar",
        variant=variant,
        act=act,
        dtype=resolve_dtype(dtype),
        twoblock=twoblock,
        remat=remat,
    )


def _make_imagenet(name, stage_sizes, variant, act, num_classes=1000,
                   pretrained=False, dtype=None, twoblock=False,
                   remat=False, block="basic"):
    # ``pretrained`` accepted for reference-API parity (train.py:285-288);
    # the actual weight loading goes through create_model's caller via
    # bdbnn_tpu.models.torch_import (no network egress in this image).
    del pretrained
    return BiResNet(
        stage_sizes=stage_sizes,
        num_classes=num_classes,
        width=64,
        stem="imagenet",
        variant=variant,
        act=act,
        dtype=resolve_dtype(dtype),
        twoblock=twoblock,
        remat=remat,
        block=block,
    )


def _make_vgg(num_classes, variant="cifar", dtype=None, twoblock=False,
              remat=False):
    if twoblock:
        raise ValueError(
            "--twoblock mixes ResNet block types; vgg_small has no blocks"
        )
    if remat:
        raise ValueError(
            "--remat rematerializes ResNet residual blocks; vgg_small "
            "has none (its activations are small — remat buys nothing)"
        )
    return VGGSmallBinary(
        num_classes=num_classes, variant=variant, dtype=resolve_dtype(dtype)
    )


def cifar_model_factories(num_classes: int = 10) -> Dict[str, Callable]:
    f = functools.partial
    return {
        # binary (EDE-able plain-STE CIFAR convs, hardtanh blocks)
        "resnet18": f(_make_cifar, "resnet18", (2, 2, 2, 2), 64, "cifar", "hardtanh", num_classes),
        "resnet20": f(_make_cifar, "resnet20", (3, 3, 3), 16, "cifar", "hardtanh", num_classes),
        # 2-stage width-8 twig: compiles in seconds on a CPU backend —
        # the smoke/fault-injection arch (tests/test_faults.py launches
        # whole training subprocesses around it), NOT an acceptance
        # config
        "resnet8_tiny": f(_make_cifar, "resnet8_tiny", (1, 1), 8, "cifar", "hardtanh", num_classes),
        "resnet34": f(_make_cifar, "resnet34", (3, 4, 6, 3), 64, "cifar", "hardtanh", num_classes),
        # react-style CIFAR (RSign/RPReLU)
        "resnet18_react": f(_make_cifar, "resnet18_react", (2, 2, 2, 2), 64, "react", "rprelu", num_classes),
        "resnet20_react": f(_make_cifar, "resnet20_react", (3, 3, 3), 16, "react", "rprelu", num_classes),
        # FP teachers
        "resnet18_float": f(_make_cifar, "resnet18_float", (2, 2, 2, 2), 64, "float", "identity", num_classes),
        "resnet20_float": f(_make_cifar, "resnet20_float", (3, 3, 3), 16, "float", "identity", num_classes),
        "resnet34_float": f(_make_cifar, "resnet34_float", (3, 4, 6, 3), 64, "float", "identity", num_classes),
        "vgg_small": f(_make_vgg, num_classes),
        # FP twin of vgg_small (same topology, FloatConv in place of the
        # binary convs) — the KD teacher for VGG students; conv2..conv6
        # pair name- and shape-matched for the layer KL
        "vgg_small_float": f(_make_vgg, num_classes, variant="float"),
    }


def imagenet_model_factories(num_classes: int = 1000) -> Dict[str, Callable]:
    f = functools.partial
    return {
        # react variant == reference resnet_bi_imagenet_set_2
        "resnet18": f(_make_imagenet, "resnet18", (2, 2, 2, 2), "react", "rprelu", num_classes),
        "resnet34": f(_make_imagenet, "resnet34", (3, 4, 6, 3), "react", "rprelu", num_classes),
        "resnet18_react": f(_make_imagenet, "resnet18_react", (2, 2, 2, 2), "react", "rprelu", num_classes),
        "resnet34_react": f(_make_imagenet, "resnet34_react", (3, 4, 6, 3), "react", "rprelu", num_classes),
        # step-2 variant == reference resnet_bi_imagenet_set_2_2
        "resnet18_step2": f(_make_imagenet, "resnet18_step2", (2, 2, 2, 2), "step2", "hardtanh", num_classes),
        "resnet34_step2": f(_make_imagenet, "resnet34_step2", (3, 4, 6, 3), "step2", "hardtanh", num_classes),
        # FP teachers (↔ torchvision resnet18/34)
        "resnet18_float": f(_make_imagenet, "resnet18_float", (2, 2, 2, 2), "float", "identity", num_classes),
        "resnet34_float": f(_make_imagenet, "resnet34_float", (3, 4, 6, 3), "float", "identity", num_classes),
        # bottleneck FP teachers (↔ torchvision resnet50/101, the common
        # ImageNet KD teachers; reference names any torchvision ctor,
        # train.py:44-48) — float/teacher path only, see FloatBottleneck
        "resnet50_float": f(_make_imagenet, "resnet50_float", (3, 4, 6, 3), "float", "identity", num_classes, block="bottleneck"),
        "resnet101_float": f(_make_imagenet, "resnet101_float", (3, 4, 23, 3), "float", "identity", num_classes, block="bottleneck"),
    }


def create_model(arch: str, dataset: str = "cifar10", **kwargs):
    """Build a model by (arch, dataset) — the registry front door."""
    if dataset in ("cifar10", "cifar100"):
        factories = cifar_model_factories(_cifar_classes(dataset))
    elif dataset == "imagenet":
        factories = imagenet_model_factories(kwargs.pop("num_classes", 1000))
    else:
        raise ValueError(f"unknown dataset: {dataset!r}")
    if arch not in factories:
        raise ValueError(
            f"unknown arch {arch!r} for {dataset}; have {sorted(factories)}"
        )
    return factories[arch](**kwargs)


def list_models(dataset: str = "cifar10"):
    if dataset == "imagenet":
        return sorted(imagenet_model_factories())
    return sorted(cifar_model_factories())
