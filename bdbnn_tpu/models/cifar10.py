"""CIFAR model namespace — reference-API parity shim.

The reference does ``from models import cifar10 as cifar_models`` and
builds via ``cifar_models.__dict__[args.arch]()`` (reference
``train.py:27, 50-52, 257, 283``). This module exposes the same
constructor-by-name surface over the registry.
"""

from bdbnn_tpu.models.registry import cifar_model_factories

_factories = cifar_model_factories(num_classes=10)


def __getattr__(name: str):
    if name in _factories:
        return _factories[name]
    raise AttributeError(name)


def __dir__():
    return sorted(_factories)


globals().update(_factories)
