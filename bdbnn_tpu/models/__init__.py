from bdbnn_tpu.models import cifar10, imagenet, registry, resnet, torch_import
from bdbnn_tpu.models.registry import create_model, list_models
from bdbnn_tpu.models.resnet import (
    BN_EPS,
    BiBasicBlock,
    BiResNet,
    VGGSmallBinary,
    bn_identity_stats,
    conv_weight_paths,
    fold_batch_norm,
    get_by_path,
    module_path_str,
)
from bdbnn_tpu.models.torch_import import (
    convert_torch_state_dict,
    load_torch_checkpoint,
)

__all__ = [
    "cifar10",
    "imagenet",
    "registry",
    "resnet",
    "torch_import",
    "create_model",
    "list_models",
    "BN_EPS",
    "BiBasicBlock",
    "BiResNet",
    "VGGSmallBinary",
    "bn_identity_stats",
    "conv_weight_paths",
    "fold_batch_norm",
    "get_by_path",
    "module_path_str",
    "convert_torch_state_dict",
    "load_torch_checkpoint",
]
