"""Cross-run regression comparison: ``compare`` CLI engine.

The repo accumulates run directories and artifact JSONs
(``ACCURACY_*.json`` accuracy curves, ``BENCH_*.json`` bench lines)
that until now were compared by eyeball. This module turns "did this
change regress the run?" into a machine-checkable verdict:

- :func:`extract_run` normalizes any source — a telemetry run dir
  (manifest + events + scalars; serve-bench run dirs included), an
  ``ACCURACY_*``-shaped artifact, a ``BENCH_*``-shaped artifact, or a
  serve-bench SLO ``verdict.json`` — into one ``{provenance, metrics}``
  record;
- :func:`compare_runs` aligns candidates against a baseline on
  manifest provenance (arch, dataset, recipe fields — serve sources
  align on the recipe their export embedded), then judges each shared
  metric against a configurable tolerance: time-to-accuracy, best/final
  top-1, jit step ms, img/s, MFU, HBM peak, wall time, run-ending alert
  counts, and the serving SLO (p99 latency, throughput, shed rate);
- :func:`render_comparison` renders the human table; the verdict dict
  itself is strict JSON (``--json``) and deterministic — no clocks, no
  absolute paths beyond what the caller passed — so it can be diffed,
  committed, and used as a CI/perf gate (nonzero exit on regression).

Stdlib-only (obs-package rule): comparisons never initialize a JAX
backend.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from bdbnn_tpu.obs.events import jsonsafe, read_events
from bdbnn_tpu.obs.health import RUN_ENDING_SEVERITY
from bdbnn_tpu.obs.manifest import read_manifest
from bdbnn_tpu.obs.memory import hbm_watermark
from bdbnn_tpu.obs.trace import attribute_trace, find_trace_file

# config fields that define "the same experiment": two runs disagreeing
# on any of these are a recipe change, not a regression — compare
# refuses (exit 2) unless --allow-mismatch. Unknown (None/absent on
# either side) never counts as a mismatch: artifacts carry partial
# provenance.
RECIPE_FIELDS: Tuple[str, ...] = (
    "arch", "dataset", "ede", "w_kurtosis", "w_kurtosis_target",
    "kurtosis_mode", "imagenet_setting_step_2_ts", "react", "twoblock",
    "dtype", "batch_size", "epochs", "lr", "opt_policy",
    # the binarizer family spec (nn/binarize.py registry; config
    # validate() canonicalizes it, so "ste" vs "proximal:delta1=0.25"
    # runs never silently compare as same-recipe). Pre-registry
    # manifests lack the key -> None -> never a mismatch.
    "binarizer",
)

# metric -> (direction, tolerance kind). Directions: "higher" is
# better or "lower" is better. Tolerance kinds: "acc" = absolute
# percentage points (tol_acc_pp), "rel" = fraction of the baseline
# (tol_rel), "hbm" = fraction of the baseline (tol_hbm), "count" =
# any increase is a regression.
METRIC_SPECS: Tuple[Tuple[str, str, str], ...] = (
    ("best_acc1", "higher", "acc"),
    ("final_acc1", "higher", "acc"),
    ("time_to_common_acc_s", "lower", "rel"),
    ("time_to_target_s", "lower", "rel"),
    ("wall_s", "lower", "rel"),
    ("img_per_s", "higher", "rel"),
    ("jit_step_ms", "lower", "rel"),
    ("mfu", "higher", "rel"),
    ("hbm_peak_bytes", "lower", "hbm"),
    ("alerts_critical", "lower", "count"),
    # serving SLO metrics (serve-bench verdicts / serve run dirs):
    # judged under --tol-rel like the other perf metrics; a shed-rate
    # increase against a zero-shed baseline is always a regression
    # (rel tolerance of 0 is 0)
    ("serve_p99_ms", "lower", "rel"),
    ("serve_throughput_rps", "higher", "rel"),
    ("serve_shed_rate", "lower", "rel"),
    # v2 (serve-http) verdicts: PER-PRIORITY p99 for classes 0-2 (the
    # front end's default class count; the metric skeleton must stay
    # static for the deterministic golden verdict, so runs with MORE
    # classes are judged per-class only up to p2 — classes beyond that
    # are covered by the aggregate p99/shed_rate alone) — a regression
    # in a judged class exits 3 even when the aggregate hides it
    # behind a flood of cheap low-priority traffic — plus the max/min
    # tenant fairness ratio and the worst tenant's shed rate (both
    # lower = better, --tol-rel). v1 verdicts leave these None
    # (skipped).
    ("serve_p99_ms_p0", "lower", "rel"),
    ("serve_p99_ms_p1", "lower", "rel"),
    ("serve_p99_ms_p2", "lower", "rel"),
    ("serve_fairness_ratio", "lower", "rel"),
    ("serve_tenant_shed_rate_max", "lower", "rel"),
    # v3 (replica pool, serve/pool.py) verdicts: the --replicas sweep's
    # scaling efficiency (throughput at N_max over ideal linear scaling
    # from N_min — higher is better, --tol-rel) and the blue/green
    # rollout's shed+dropped total under ZERO tolerance: a hot-swap
    # that loses even one request is a regression no tolerance can
    # wave through. v1/v2 verdicts leave both None (skipped).
    ("serve_scaling_efficiency", "higher", "rel"),
    ("serve_swap_dropped", "lower", "count"),
    # packed residency (nn/packed.py, serve/engine.py packed mode):
    # resident device bytes per model (the multi-tenant capacity
    # figure — lower is better; a change that silently re-densifies
    # the resident set regresses here even when latency holds) and
    # the packed forward's measured per-step ms (the honest cost of
    # the on-the-fly unpack — lower, --tol-rel). v1/v2 and
    # v3-without-packed verdicts leave both None (skipped).
    ("serve_resident_bytes_per_model", "lower", "rel"),
    ("serve_packed_step_ms", "lower", "rel"),
    # v4 request-path attribution (obs/rtrace.py): the stage-share
    # regression gates. serve_p99_queue_ms / serve_p99_compute_ms are
    # the queue-wait and device-compute stage p99s (rolling windows,
    # merged across priorities); serve_queue_share is the
    # (queue + dispatch) share of the summed stage means. A p99 that
    # moved from device-bound to queue-bound regresses here — exit 3 —
    # even when the aggregate serve_p99_ms is flat. v1-v3 verdicts
    # (no attribution block) leave all three None (skipped).
    ("serve_p99_queue_ms", "lower", "rel"),
    ("serve_p99_compute_ms", "lower", "rel"),
    ("serve_queue_share", "lower", "rel"),
    # v5 canary rollouts (serve/canary.py): a canary that ROLLED BACK
    # is a regression no tolerance can wave through — the whole point
    # of the doctored-run gate is that the rollback is visible even
    # when the aggregate p99 is unchanged (the degradation hit only a
    # priority-class window). Shadow logit drift is likewise
    # zero-tolerance: packed inference is deterministic and
    # bitwise-exact, so ANY drift between an incumbent and a
    # republished-identical canary is a real defect, never float
    # noise. Promote wall seconds is an ordinary perf metric
    # (--tol-rel). v1-v4 verdicts (no canary block) leave all three
    # None (skipped).
    ("serve_canary_rollbacks", "lower", "count"),
    ("serve_shadow_logit_drift_max", "lower", "count"),
    ("serve_canary_promote_s", "lower", "rel"),
    # v6 fleet verdicts (serve/fleet.py): the summed-across-hosts
    # dropped count is the zero-tolerance drain contract one topology
    # level up — a fleet that lost even one request to a host failure
    # is a regression no tolerance can wave through. The cross-host
    # retry rate (retries per routed request, --tol-rel) catches a
    # build that quietly started burning peer retries to hide a flaky
    # host, and the per-host p99 spread (max/min host p99, --tol-rel)
    # catches dispatch skew — one slow host hiding behind a healthy
    # fleet aggregate. v1-v5 verdicts (no fleet block) leave all
    # three None, so they skip cleanly in BOTH directions.
    ("serve_fleet_dropped", "lower", "count"),
    ("serve_fleet_retry_rate", "lower", "rel"),
    ("serve_fleet_host_p99_spread", "lower", "rel"),
    # v7 fleet_attribution (obs/rtrace.py FleetTracer): the cross-host
    # waterfall's three tail-attribution gates. Network-stage p99
    # catches proxy/transport regressions the backend's own stages
    # can't see; retry-hop share catches tails minted by re-dispatch
    # (a clean baseline's share is 0.0, so ANY wedged increase is a
    # regression regardless of --tol-rel — rel tolerance of a zero
    # baseline is zero); per-host stage-spread max catches one host
    # going slow in one stage behind a healthy fleet aggregate. v1-v6
    # verdicts (no fleet_attribution block) leave all three None, so
    # they skip cleanly in BOTH directions.
    ("serve_fleet_p99_network_ms", "lower", "rel"),
    ("serve_fleet_retry_hop_share", "lower", "rel"),
    ("serve_fleet_stage_spread_max", "lower", "rel"),
    # v8 capacity block (obs/capacity.py): the capacity observatory's
    # three flat gates. Worst burn rate catches a build that started
    # torching its error budget (burn is already normalized against
    # the objective, so ANY wedged increase against a calm baseline is
    # a regression — rel tolerance of a zero baseline is zero);
    # headroom rps (higher — shrinking saturation margin at the same
    # offered load is a capacity regression even when the p99 held);
    # worst per-key shed ratio catches one (model, tenant, priority)
    # key being starved behind a healthy aggregate. v1-v7 verdicts
    # (no capacity block) leave all three None, so they skip cleanly
    # in BOTH directions.
    ("serve_burn_rate_max", "lower", "rel"),
    ("serve_headroom_rps", "higher", "rel"),
    ("serve_demand_shed_ratio_max", "lower", "rel"),
    # recipe-search leaderboards (bdbnn_tpu/search/): the winning
    # trial's best top-1 (absolute pp tolerance, like the training
    # accuracies) and its time to the sweep's common-accuracy level —
    # the same time-to-common-accuracy judgment compare applies
    # run-vs-run, here sweep-vs-sweep. Non-search sources leave both
    # None, so they skip cleanly in both directions.
    ("search_best_top1", "higher", "acc"),
    ("search_time_to_common_acc_s", "lower", "rel"),
    # v8 perf verdicts (obs/roofline.py): the performance
    # observatory's flat aggregates — best/dense/packed step ms at the
    # summary bucket (lower, --tol-rel), the mean per-layer roofline
    # efficiency (higher — a drop means kernels moved AWAY from their
    # roof even if walls held), and the attributed share of device
    # time (higher — a drop means the trace join degraded and the
    # per-layer gates below are seeing less of the step). On top of
    # these STATIC keys, compare_runs judges every (layer, bucket,
    # impl) ms the two perf sources share as a dynamic
    # ``perf_ms[...]`` row under --tol-rel — the per-layer regression
    # gate: a kernel swap that holds the aggregate while regressing
    # one layer exits 3. Non-perf sources leave all of these None, so
    # they skip cleanly in both directions.
    ("perf_step_ms_best", "lower", "rel"),
    ("perf_step_ms_dense", "lower", "rel"),
    ("perf_step_ms_packed", "lower", "rel"),
    ("perf_efficiency_mean", "higher", "rel"),
    ("perf_attributed_share", "higher", "rel"),
)

# serve-verdict field -> compare metric name (flat v1 aggregates)
_SERVE_METRIC_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("p99_ms", "serve_p99_ms"),
    ("throughput_rps", "serve_throughput_rps"),
    ("shed_rate", "serve_shed_rate"),
)

# how many priority classes get their own compare metric (the default
# class count of the serve-http front end; verdicts with fewer classes
# simply leave the tail None)
_SERVE_PRIORITY_CLASSES = 3


def _serve_metrics(verdict: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten one serve verdict (v1 or v2) into the compare metric
    namespace — shared by the run-dir and artifact extraction paths."""
    out: Dict[str, Any] = {}
    for field, name in _SERVE_METRIC_FIELDS:
        out[name] = verdict.get(field)
    per_priority = verdict.get("per_priority") or {}
    for p in range(_SERVE_PRIORITY_CLASSES):
        out[f"serve_p99_ms_p{p}"] = (
            (per_priority.get(str(p)) or {}).get("p99_ms")
        )
    out["serve_fairness_ratio"] = verdict.get("fairness_ratio")
    shed_rates = [
        t.get("shed_rate")
        for t in (verdict.get("per_tenant") or {}).values()
        if t.get("shed_rate") is not None
    ]
    out["serve_tenant_shed_rate_max"] = (
        max(shed_rates) if shed_rates else None
    )
    # v3 blocks: replica-pool scaling + swap disposition
    out["serve_scaling_efficiency"] = (
        (verdict.get("scaling") or {}).get("efficiency")
    )
    # packed-residency blocks: resident bytes per model from the
    # `resident` block (max over models — the binding per-chip figure),
    # packed step ms from the A/B `packed` block's packed side
    resident = verdict.get("resident")
    out["serve_resident_bytes_per_model"] = (
        (resident or {}).get("bytes_per_model_max")
    )
    packed = verdict.get("packed")
    out["serve_packed_step_ms"] = (
        ((packed or {}).get("packed") or {}).get("step_ms")
    )
    # v4 attribution block (obs/rtrace.py): the stage decomposition's
    # queue/compute p99s + the queue share — None on v1-v3 verdicts
    # and traced-off v4 runs, so they skip cleanly
    att = verdict.get("attribution")
    stages = (att or {}).get("stages") or {}
    out["serve_p99_queue_ms"] = (
        (stages.get("queue") or {}).get("p99_ms")
    )
    out["serve_p99_compute_ms"] = (
        (stages.get("compute") or {}).get("p99_ms")
    )
    out["serve_queue_share"] = (att or {}).get("queue_share")
    # v5 canary block (serve/canary.py): rollback count, the shadow
    # probe's max-abs logit drift (None when no mirror ever compared —
    # "not measured", never a fabricated 0.0), and the promote wall
    # seconds (None on rollbacks). Absent block -> all None, so v1-v4
    # verdicts skip cleanly.
    can = verdict.get("canary")
    out["serve_canary_rollbacks"] = (
        None if can is None else int(can.get("rollbacks") or 0)
    )
    out["serve_shadow_logit_drift_max"] = (
        (can or {}).get("shadow") or {}
    ).get("max_abs_drift")
    out["serve_canary_promote_s"] = (can or {}).get("promote_s")
    # v6 fleet block (serve/fleet.py): the summed-across-hosts dropped
    # count (None when no client observed the run — "not measured",
    # never a fabricated 0), the cross-host retry rate and the
    # per-host p99 spread. Absent block -> all None, so v1-v5
    # verdicts skip the fleet gates cleanly.
    fleet = verdict.get("fleet")
    fleet_dropped = (fleet or {}).get("dropped")
    out["serve_fleet_dropped"] = (
        None if fleet is None or fleet_dropped is None
        else int(fleet_dropped)
    )
    out["serve_fleet_retry_rate"] = (fleet or {}).get("retry_rate")
    out["serve_fleet_host_p99_spread"] = (
        (fleet or {}).get("host_p99_spread")
    )
    # v7 fleet_attribution block (obs/rtrace.py): network-stage p99
    # from the router's stitched cross-host windows, the retry-hop
    # share of cumulative e2e, and the max per-stage cross-host p99
    # spread. Absent block -> all None, so v1-v6 verdicts skip the
    # attribution gates cleanly.
    fa = verdict.get("fleet_attribution")
    out["serve_fleet_p99_network_ms"] = (
        ((fa or {}).get("stages") or {}).get("network") or {}
    ).get("p99_ms")
    out["serve_fleet_retry_hop_share"] = (
        (fa or {}).get("retry_hop_share")
    )
    out["serve_fleet_stage_spread_max"] = (
        (fa or {}).get("host_stage_spread_max")
    )
    # v8 capacity block (obs/capacity.py): the observatory publishes
    # its three gates FLAT at the block's top level (host and fleet
    # producers alike) exactly so these reads stay constant-subscript.
    # Absent block -> all None, so v1-v7 verdicts skip the capacity
    # gates cleanly in both directions.
    cap = verdict.get("capacity")
    out["serve_burn_rate_max"] = (cap or {}).get("burn_rate_max")
    out["serve_headroom_rps"] = (cap or {}).get("headroom_rps")
    out["serve_demand_shed_ratio_max"] = (
        (cap or {}).get("demand_shed_ratio_max")
    )
    swap = verdict.get("swap")
    if swap is None:
        out["serve_swap_dropped"] = None
    else:
        # everything a rollout may have cost: requests shed while the
        # swap rolled plus client-observed drops on a swap run — the
        # zero-tolerance number. A rollout that never COMPLETED counts
        # as at least one lost unit: a failed swap must not score 0
        # and slip past the gate just because traffic stayed on vN.
        dropped = (verdict.get("client") or {}).get("dropped") or 0
        not_performed = 0 if swap.get("performed") else 1
        out["serve_swap_dropped"] = (
            (swap.get("shed") or 0) + dropped + not_performed
        )
    return out

# perf-verdict summary field -> compare metric name (obs/roofline.py
# ``summary`` block; the table shape keeps the flattener AST-scannable
# by analysis/verdictcheck.py)
_PERF_METRIC_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("step_ms_best", "perf_step_ms_best"),
    ("step_ms_dense", "perf_step_ms_dense"),
    ("step_ms_packed", "perf_step_ms_packed"),
    ("efficiency_mean", "perf_efficiency_mean"),
    ("attributed_share", "perf_attributed_share"),
)


def _perf_metrics(verdict: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten one perf verdict (obs/roofline.py schema v1) into the
    compare metric namespace — shared by the run-dir and artifact
    extraction paths. A static-only run has no summary aggregates:
    every key stays None (skipped), never a fabricated 0."""
    summary = verdict.get("summary") or {}
    out: Dict[str, Any] = {}
    for field, name in _PERF_METRIC_FIELDS:
        out[name] = summary.get(field)
    return out


def _search_metrics(leaderboard: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten one recipe-search leaderboard (bdbnn_tpu/search/) into
    the compare metric namespace — shared by the sweep-dir and
    leaderboard-artifact extraction paths. A sweep with no completed
    trial has no winner: both metrics stay None (skipped), never a
    fabricated 0."""
    winner = leaderboard.get("winner") or {}
    return {
        "search_best_top1": winner.get("best_top1"),
        "search_time_to_common_acc_s": winner.get(
            "time_to_common_acc_s"
        ),
    }


# the metric-key skeleton every extracted source carries (None = the
# source does not know this metric; _judge skips it). time_to_common_acc
# is derived pairwise in compare_runs, never stored per source.
_EMPTY_METRICS: Dict[str, Any] = {
    name: None
    for name, _, _ in METRIC_SPECS
    if name != "time_to_common_acc_s"
}
_EMPTY_METRICS["alerts_total"] = None


def _recipe_from_config(cfg: Dict[str, Any]) -> Dict[str, Any]:
    return {k: cfg.get(k) for k in RECIPE_FIELDS}


def _extract_run_dir(path: str) -> Dict[str, Any]:
    from bdbnn_tpu.obs.summarize import resolve_run_dir

    run_dir = resolve_run_dir(path)
    manifest = read_manifest(run_dir) or {}
    events = read_events(run_dir)
    cfg = manifest.get("config") or {}

    evals = [e for e in events if e.get("kind") == "eval"]
    intervals = [e for e in events if e.get("kind") == "train_interval"]
    memory = [e for e in events if e.get("kind") == "memory"]
    alerts = [e for e in events if e.get("kind") == "alert"]
    end = next((e for e in events if e.get("kind") == "run_end"), None)
    t0 = events[0]["t"] if events else None

    best_acc1 = None
    if end is not None and end.get("best_acc1") is not None:
        best_acc1 = float(end["best_acc1"])
    elif evals:
        best_acc1 = max(float(e.get("acc1") or 0.0) for e in evals)
    final_acc1 = float(evals[-1]["acc1"]) if evals else None

    # time-to-accuracy curve (elapsed seconds vs run start) kept raw so
    # compare_runs can evaluate it at whatever level both runs reached
    acc_curve = [
        (float(e.get("acc1") or 0.0), round(float(e["t"]) - t0, 1))
        for e in evals
        if t0 is not None and e.get("t") is not None
    ]

    img_rates = [
        float(e["img_per_s"])
        for e in intervals[1:]  # skip the compile-tainted first interval
        if isinstance(e.get("img_per_s"), (int, float))
    ] or [
        float(e["img_per_s"])
        for e in intervals
        if isinstance(e.get("img_per_s"), (int, float))
    ]
    img_per_s = (
        round(sorted(img_rates)[len(img_rates) // 2], 2)
        if img_rates else None
    )

    jit_step_ms = mfu = None
    profile_evs = [e for e in events if e.get("kind") == "profile"]
    if profile_evs:
        pe = profile_evs[-1]
        trace = None
        for root in (run_dir, pe.get("trace_dir") or ""):
            if root and os.path.isdir(root):
                trace = find_trace_file(root)
                if trace:
                    break
        if trace:
            from bdbnn_tpu.obs.trace import BF16_PEAK_TFLOPS

            att = attribute_trace(
                trace,
                pe.get("steps") or 1,
                flops_per_step=pe.get("flops_per_step"),
                peak_tflops=BF16_PEAK_TFLOPS.get(
                    manifest.get("device_kind", "")
                ),
            )
            jit_step_ms = att.get("step_total_ms")
            mfu = att.get("mfu")

    wm = hbm_watermark(memory)
    metrics = dict(_EMPTY_METRICS)
    metrics.update({
        "best_acc1": best_acc1,
        "final_acc1": final_acc1,
        "time_to_target_s": (end or {}).get("time_to_target_s"),
        "wall_s": (end or {}).get("wall_s"),
        "img_per_s": img_per_s,
        "jit_step_ms": jit_step_ms,
        "mfu": mfu,
        "hbm_peak_bytes": (wm or {}).get("peak_bytes"),
        "alerts_total": len(alerts),
        "alerts_critical": sum(
            1 for a in alerts
            if a.get("severity") == RUN_ENDING_SEVERITY
        ),
    })
    # a serve-bench run dir: the final `serve` verdict event carries
    # the SLO metrics; alignment uses the recipe the serve manifest
    # copied from the export's provenance
    from bdbnn_tpu.obs.events import serve_digest

    serve_verdict = serve_digest(events)["verdict"]
    if serve_verdict is not None:
        metrics.update(_serve_metrics(serve_verdict))
    # a recipe-search sweep dir: the final `search` verdict event
    # carries the leaderboard (bdbnn_tpu/search/); judged on the
    # winner's metrics, aligned on the sweep's shared recipe
    search_verdict = next(
        (
            e for e in reversed(events)
            if e.get("kind") == "search" and e.get("phase") == "verdict"
        ),
        None,
    )
    if search_verdict is not None:
        metrics.update(_search_metrics(search_verdict))
    # a perf run dir (obs/roofline.py): the final `perf` verdict event
    # embeds the full perf_verdict; alignment uses the recipe the
    # verdict copied from the artifact's provenance (the PerfConfig
    # manifest itself carries no arch/dataset)
    perf_ev = next(
        (
            e for e in reversed(events)
            if e.get("kind") == "perf" and e.get("phase") == "verdict"
        ),
        None,
    )
    perf_layers: Dict[str, Any] = {}
    recipe = _recipe_from_config(cfg)
    if perf_ev is not None:
        pv = perf_ev.get("verdict") or {}
        metrics.update(_perf_metrics(pv))
        perf_layers = pv.get("perf_layers") or {}
        pv_recipe = (pv.get("provenance") or {}).get("recipe")
        if pv_recipe:
            recipe = _recipe_from_config(pv_recipe)
    fmt = "run_dir"
    if serve_verdict is not None:
        fmt = "serve_run_dir"
    elif search_verdict is not None:
        fmt = "search_run_dir"
    elif perf_ev is not None:
        fmt = "perf_run_dir"
    return {
        "source": path,
        "format": fmt,
        "provenance": {
            "config_hash": manifest.get("config_hash"),
            "device_kind": manifest.get("device_kind"),
            "recipe": recipe,
        },
        "metrics": metrics,
        "acc_curve": acc_curve,
        "perf_layers": perf_layers,
    }


def _extract_artifact(path: str) -> Dict[str, Any]:
    with open(path) as f:
        d = json.load(f)
    if "serve_verdict" in d:
        # a serve-bench SLO verdict (serve/loadgen.py): aligned on the
        # export provenance it embeds, judged on p99/throughput/shed
        prov = d.get("provenance") or {}
        metrics = dict(_EMPTY_METRICS)
        metrics.update(_serve_metrics(d))
        return {
            "source": path,
            "format": "serve_verdict",
            "provenance": {
                "config_hash": prov.get("config_hash"),
                "device_kind": None,
                "recipe": _recipe_from_config(prov.get("recipe") or {}),
            },
            "metrics": metrics,
            "acc_curve": [],
        }
    if "search_verdict" in d:
        # a recipe-search leaderboard JSON (bdbnn_tpu/search/): judged
        # on the winner's best top-1 + time-to-common-accuracy,
        # aligned on the sweep's shared recipe provenance
        prov = d.get("provenance") or {}
        metrics = dict(_EMPTY_METRICS)
        metrics.update(_search_metrics(d))
        return {
            "source": path,
            "format": "search_leaderboard",
            "provenance": {
                "config_hash": prov.get("config_hash"),
                "device_kind": None,
                "recipe": _recipe_from_config(prov.get("recipe") or {}),
            },
            "metrics": metrics,
            "acc_curve": [],
        }
    if "perf_verdict" in d:
        # a roofline perf verdict (obs/roofline.py): aligned on the
        # artifact provenance it embeds, judged on summary aggregates
        # plus per-(layer, bucket, impl) device ms via perf_layers
        prov = d.get("provenance") or {}
        metrics = dict(_EMPTY_METRICS)
        metrics.update(_perf_metrics(d))
        return {
            "source": path,
            "format": "perf_verdict",
            "provenance": {
                "config_hash": prov.get("config_hash"),
                "device_kind": prov.get("device_kind"),
                "recipe": _recipe_from_config(prov.get("recipe") or {}),
            },
            "metrics": metrics,
            "acc_curve": [],
            "perf_layers": d.get("perf_layers") or {},
        }
    parsed = d.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        # BENCH_*.json shape: a bench harness line under "parsed"
        recipe = _recipe_from_config(
            {"dtype": parsed.get("dtype")}
        )
        metrics = dict(_EMPTY_METRICS)
        metrics.update({
            "img_per_s": parsed.get("value") or None,
            "jit_step_ms": parsed.get("device_ms_per_step"),
            "mfu": parsed.get("device_mfu"),
        })
        return {
            "source": path,
            "format": "bench_artifact",
            "provenance": {
                "config_hash": None,
                "device_kind": parsed.get("device_kind"),
                "recipe": recipe,
            },
            "metrics": metrics,
            "acc_curve": [],
        }
    if "best_val_top1" in d:
        # ACCURACY_*.json shape
        recipe = _recipe_from_config(d)
        curve = d.get("val_top1_curve") or []
        metrics = dict(_EMPTY_METRICS)
        metrics.update({
            "best_acc1": d.get("best_val_top1"),
            "final_acc1": curve[-1] if curve else None,
            "time_to_target_s": d.get("time_to_target_s"),
            "wall_s": d.get("wall_seconds"),
        })
        return {
            "source": path,
            "format": "accuracy_artifact",
            "provenance": {
                "config_hash": None,
                "device_kind": d.get("device_kind"),
                "recipe": recipe,
            },
            "metrics": metrics,
            "acc_curve": [],
        }
    raise ValueError(
        f"{path!r}: not a recognized artifact (want a BENCH_*.json "
        "'parsed' bench line, an ACCURACY_*.json with best_val_top1, "
        "a serve-bench verdict.json, a search leaderboard.json, or a "
        "perf_verdict.json)"
    )


def extract_run(path: str) -> Dict[str, Any]:
    """Normalize one source (run dir OR artifact JSON) into
    ``{source, format, provenance, metrics, acc_curve}``. Directories
    go through ``resolve_run_dir`` (which raises on a dir with no run
    files); files must be a recognized artifact shape."""
    if os.path.isdir(path):
        return _extract_run_dir(path)
    if os.path.isfile(path):
        return _extract_artifact(path)
    raise FileNotFoundError(f"compare source not found: {path!r}")


def _time_to_acc(curve: List, level: float) -> Optional[float]:
    for acc, elapsed in curve:
        if acc >= level:
            return elapsed
    return None


def _mismatches(base: Dict[str, Any], cand: Dict[str, Any]) -> List[str]:
    """Recipe fields where BOTH sides know a value and they differ."""
    out = []
    br = base["provenance"]["recipe"]
    cr = cand["provenance"]["recipe"]
    for field in RECIPE_FIELDS:
        b, c = br.get(field), cr.get(field)
        if b is not None and c is not None and b != c:
            out.append(f"{field}: {b!r} vs {c!r}")
    return out


def _judge(
    name: str, direction: str, kind: str,
    base: Optional[float], cand: Optional[float],
    *, tol_acc_pp: float, tol_rel: float, tol_hbm: float,
) -> Optional[Dict[str, Any]]:
    if base is None or cand is None:
        return None
    base, cand = float(base), float(cand)
    tol = {
        "acc": tol_acc_pp,
        "rel": tol_rel * abs(base),
        "hbm": tol_hbm * abs(base),
        "count": 0.0,
    }[kind]
    delta = round(cand - base, 6)
    worse = -delta if direction == "higher" else delta
    if worse > tol:
        verdict = "regression"
    elif worse < -tol:
        verdict = "improvement"
    else:
        verdict = "ok"
    return {
        "metric": name,
        "baseline": base,
        "candidate": cand,
        "delta": delta,
        "tolerance": round(tol, 6),
        "direction": direction,
        "verdict": verdict,
    }


def compare_runs(
    paths: Sequence[str],
    *,
    tol_acc_pp: float = 0.5,
    tol_rel: float = 0.10,
    tol_hbm: float = 0.05,
    allow_mismatch: bool = False,
) -> Dict[str, Any]:
    """First path is the baseline; every other path is judged against
    it. Returns the full verdict dict (strict JSON, deterministic)."""
    if len(paths) < 2:
        raise ValueError("compare needs a baseline and >= 1 candidate")
    runs = [extract_run(p) for p in paths]
    base, cands = runs[0], runs[1:]

    comparisons = []
    any_regression = False
    any_incomparable = False
    for cand in cands:
        mism = _mismatches(base, cand)
        comparable = not mism or allow_mismatch
        metrics: List[Dict[str, Any]] = []
        if comparable:
            # time-to-common-accuracy: elapsed seconds to the highest
            # top-1 BOTH runs reached — the run-vs-run version of the
            # north-star time-to-accuracy metric
            bb = base["metrics"].get("best_acc1")
            cb = cand["metrics"].get("best_acc1")
            ttca_b = ttca_c = None
            if (
                bb is not None and cb is not None
                and base["acc_curve"] and cand["acc_curve"]
            ):
                level = min(float(bb), float(cb))
                ttca_b = _time_to_acc(base["acc_curve"], level)
                ttca_c = _time_to_acc(cand["acc_curve"], level)
            for name, direction, kind in METRIC_SPECS:
                if name == "time_to_common_acc_s":
                    b, c = ttca_b, ttca_c
                else:
                    b = base["metrics"].get(name)
                    c = cand["metrics"].get(name)
                row = _judge(
                    name, direction, kind, b, c,
                    tol_acc_pp=tol_acc_pp, tol_rel=tol_rel,
                    tol_hbm=tol_hbm,
                )
                if row is not None:
                    metrics.append(row)
            # dynamic per-(layer, bucket, impl) device-ms rows from the
            # perf observatory: a single layer can regress while every
            # aggregate above stays flat, so each shared key gets its
            # own lower-is-better relative gate
            bl = base.get("perf_layers") or {}
            cl = cand.get("perf_layers") or {}
            for key in sorted(set(bl) & set(cl)):
                row = _judge(
                    f"perf_ms[{key}]", "lower", "rel",
                    bl[key], cl[key],
                    tol_acc_pp=tol_acc_pp, tol_rel=tol_rel,
                    tol_hbm=tol_hbm,
                )
                if row is not None:
                    metrics.append(row)
        regressed = any(m["verdict"] == "regression" for m in metrics)
        if not comparable:
            verdict = "incomparable"
            any_incomparable = True
        elif regressed:
            verdict = "regression"
            any_regression = True
        elif not metrics:
            # zero shared metrics means zero validation happened — a CI
            # gate must NOT report green for a comparison that compared
            # nothing (e.g. an accuracy artifact against a bench
            # artifact, or a run dir whose events are torn)
            verdict = "no_shared_metrics"
            any_incomparable = True
        else:
            verdict = "pass"
        comparisons.append({
            "source": cand["source"],
            "format": cand["format"],
            "mismatches": mism,
            "metrics": metrics,
            "verdict": verdict,
        })

    overall = (
        "incomparable" if any_incomparable
        else "regression" if any_regression
        else "pass"
    )
    out = {
        "baseline": {
            k: base[k] for k in ("source", "format", "provenance", "metrics")
        },
        "tolerances": {
            "acc_pp": tol_acc_pp,
            "rel": tol_rel,
            "hbm": tol_hbm,
        },
        "comparisons": comparisons,
        "verdict": overall,
    }
    return jsonsafe(out)


def render_comparison(result: Dict[str, Any]) -> str:
    """The human-readable table for one compare_runs() verdict."""
    lines = [f"== Run comparison (baseline: {result['baseline']['source']})"]
    tol = result["tolerances"]
    lines.append(
        f"tolerances: acc {tol['acc_pp']:g}pp  rel {tol['rel']:.0%}  "
        f"hbm {tol['hbm']:.0%}"
    )
    for comp in result["comparisons"]:
        lines.append(f"candidate: {comp['source']}")
        if comp["mismatches"]:
            tag = (
                "compared anyway (--allow-mismatch)"
                if comp["verdict"] != "incomparable"
                else "NOT comparable (pass --allow-mismatch to force)"
            )
            lines.append(f"  !! recipe mismatch — {tag}:")
            for m in comp["mismatches"]:
                lines.append(f"     {m}")
        if comp["metrics"]:
            lines.append(
                f"  {'metric':<22} {'baseline':>12} {'candidate':>12} "
                f"{'delta':>10}  verdict"
            )
            for m in comp["metrics"]:
                mark = {
                    "regression": "REGRESSION",
                    "improvement": "improvement",
                    "ok": "ok",
                }[m["verdict"]]
                lines.append(
                    f"  {m['metric']:<22} {m['baseline']:>12.4g} "
                    f"{m['candidate']:>12.4g} {m['delta']:>+10.4g}  {mark}"
                )
        lines.append(f"  verdict: {comp['verdict'].upper()}")
    lines.append(f"overall verdict: {result['verdict'].upper()}")
    return "\n".join(lines)


__all__ = [
    "METRIC_SPECS",
    "RECIPE_FIELDS",
    "compare_runs",
    "extract_run",
    "render_comparison",
]
