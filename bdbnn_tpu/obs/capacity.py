"""Capacity & demand observatory: traffic ledgers, utilization
windows, and the SLO error-budget burn-rate plane.

The serving stack can attribute a slow request to a lifecycle stage
(obs/rtrace.py) and a slow layer to a roofline (obs/roofline.py), but
none of that answers the question every scale/placement decision
starts from: *is this host (or the fleet) about to run out of
capacity, for whom, and how fast?* This module produces exactly those
signals from accounting sites the front end already owns — zero new
device syncs, stdlib-only (obs-package rule):

- :class:`DemandLedger` — rolling per-(model, tenant, priority)
  traffic windows: offered vs admitted vs completed vs shed rps, with
  the ledger identity ``offered == admitted + rejected + shed``
  enforced per key (``completed``/``failed`` are terminal outcomes of
  the admitted population, not entry dispositions). Fed once per
  request at the dispositions serve/http.py already records; the
  identity delta is the number of requests still mid-decision, so at
  any quiescent point (drain, end of a test) it is exactly zero.
- :class:`UtilizationWindows` — rolling host-utilization gauges:
  replica busy fraction and batch occupancy (serve/pool.py /
  serve/batching.py), rtrace queue share (obs/rtrace.py), admission
  token headroom (serve/admission.py), plus the engine's static
  packed-residency block (``engine.residency()``) captured once at
  startup.
- :class:`SLOBudget` — the per-priority error-budget plane. Each
  (priority, objective) pair runs a fast AND a slow burn-rate window
  through the shared :class:`~bdbnn_tpu.obs.health.DetectorState`
  warmup -> debounce -> hysteresis machine; objectives come from
  ``--slo-p99-ms`` (latency: a p99 target budgets 1% of requests
  over it) and ``--slo-shed-rate`` (shed fraction). A breach emits a
  ``capacity`` event (phase ``breach``; ``recovered`` closes the
  episode) and the episode ledger lands in the verdict.
- :func:`saturation_headroom` — the autoscaler's number: estimated
  capacity (completed rps over busy fraction), headroom rps
  (capacity minus offered demand — negative exactly while demand
  exceeds what the host can serve), and seconds-to-saturation at the
  observed demand slope.
- :class:`CapacityPlane` — one host's composition of the three,
  producing the live ``/statsz`` ``capacity`` block and the verdict's
  nullable v8 ``capacity`` block.
- :class:`FleetCapacityWindows` — the router-side merge: per-host
  scraped capacity blocks under the same staleness discipline as the
  rtrace metrics plane (obs/rtrace.py HostStatsWindows) — a wedged
  host's frozen numbers are excluded from the merged view, never
  rendered as live data.

Burn-rate semantics (the Google-SRE multi-window form): burn rate =
(observed bad fraction) / (budgeted bad fraction). 1.0 means the
budget is being spent exactly at the allowed rate; a breach requires
BOTH windows over the threshold — the fast window proves it is
happening *now*, the slow window proves it is not a blip.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from bdbnn_tpu.obs.health import DetectorState

# rolling demand window (rps figures are computed over it)
DEFAULT_WINDOW_S = 30.0
# burn-rate windows: fast proves "now", slow proves "not a blip"
DEFAULT_FAST_WINDOW_S = 5.0
DEFAULT_SLOW_WINDOW_S = 30.0
# a p99 objective budgets exactly 1% of requests over the target
P99_BUDGET_FRACTION = 0.01
# budget spent exactly at the allowed rate; above this both windows
# must agree before the detector machine sees a breach
BURN_RATE_THRESHOLD = 1.0
# a zero-traffic denominator or a zero budget could mint inf; burn
# rates are capped so every emitted figure stays finite JSON
BURN_RATE_CAP = 1000.0
DEFAULT_WARMUP = 2
DEFAULT_DEBOUNCE = 2
# below this measured busy fraction a capacity estimate would divide
# by noise — report "unmeasurable" (None), never a fabricated figure
MIN_BUSY_FRACTION = 0.01

LATENCY_OBJECTIVE = "latency"
SHED_OBJECTIVE = "shed"

# entry dispositions (the identity's right-hand side) and terminal
# outcomes of the admitted population
DISPOSITIONS = ("admitted", "rejected", "shed")
COUNTERS = ("offered",) + DISPOSITIONS + ("completed", "failed")


def demand_key(model: str, tenant: str, priority: int) -> str:
    """The ledger's composite key: ``model|tenant|p<priority>`` —
    stable, sortable, and JSON-safe as a dict key."""
    return f"{model}|{tenant}|p{int(priority)}"


class DemandLedger:
    """Rolling per-(model, tenant, priority) traffic windows.

    One call per request at the disposition site the front end already
    owns: ``offered`` at arrival, then exactly one of ``admitted`` /
    ``rejected`` / ``shed`` once the request's fate at the
    admission/queue boundary is known — ``rejected`` and ``shed`` at
    their response sites, ``admitted`` when the request actually
    reached an engine (bumped at its terminal ``completed`` /
    ``failed``, so a queued request that a late shed turns away is
    never double-counted). The per-key identity delta
    ``offered - (admitted + rejected + shed)`` is therefore exactly
    the number of requests currently queued or computing: a live
    in-flight gauge while serving, zero at any quiescent point.
    Totals are monotonic; the rolling windows hold event stamps pruned
    to ``window_s`` so ``snapshot`` can report rps per key with no
    background thread.
    """

    def __init__(
        self,
        *,
        window_s: float = DEFAULT_WINDOW_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        # guarded-by: _lock: _totals, _stamps
        # {key: {counter: monotonic total}}
        self._totals: Dict[str, Dict[str, int]] = {}
        # {key: {counter: deque[monotonic stamp]}}
        self._stamps: Dict[str, Dict[str, deque]] = {}

    def _entry(self, key: str) -> Tuple[Dict[str, int], Dict[str, deque]]:  # requires-lock: _lock
        totals = self._totals.get(key)
        if totals is None:
            totals = self._totals[key] = {c: 0 for c in COUNTERS}
            self._stamps[key] = {c: deque() for c in COUNTERS}
        return totals, self._stamps[key]

    def _bump(
        self, model: str, tenant: str, priority: int, counter: str
    ) -> None:
        now = self._clock()
        key = demand_key(model, tenant, priority)
        horizon = now - self.window_s
        with self._lock:
            totals, stamps = self._entry(key)
            totals[counter] += 1
            win = stamps[counter]
            win.append(now)
            while win and win[0] < horizon:
                win.popleft()

    # -- the per-request feed (one call per disposition) ---------------

    def offered(self, model: str, tenant: str, priority: int) -> None:
        """A request arrived (the ``submitted`` site)."""
        self._bump(model, tenant, priority, "offered")

    def admitted(self, model: str, tenant: str, priority: int) -> None:
        """The request genuinely reached an engine (called alongside
        its terminal ``completed``/``failed``)."""
        self._bump(model, tenant, priority, "admitted")

    def rejected(self, model: str, tenant: str, priority: int) -> None:
        """Turned away as the tenant's own doing: over-quota (429) or
        a malformed body (400)."""
        self._bump(model, tenant, priority, "rejected")

    def shed(self, model: str, tenant: str, priority: int) -> None:
        """Server-side shed: draining, queue full, or no healthy
        replica (the 503 family)."""
        self._bump(model, tenant, priority, "shed")

    def completed(self, model: str, tenant: str, priority: int) -> None:
        self._bump(model, tenant, priority, "completed")

    def failed(self, model: str, tenant: str, priority: int) -> None:
        self._bump(model, tenant, priority, "failed")

    # -- reporting -----------------------------------------------------

    @staticmethod
    def _rps(win: deque, horizon: float, span: float) -> float:  # requires-lock: _lock
        # span = min(window_s, elapsed): a 2-second-old run reporting
        # over the full window would dilute every rate toward zero
        n = 0
        for t in reversed(win):
            if t < horizon:
                break
            n += 1
        return round(n / span, 4)

    def offered_slope_rps_per_s(self) -> Optional[float]:
        """The observed demand slope: offered rps in the newest half
        of the window minus the older half, over half a window — the
        d(demand)/dt figure :func:`saturation_headroom` extrapolates
        along. None until a full window of history exists."""
        now = self._clock()
        half = self.window_s / 2.0
        mid = now - half
        horizon = now - self.window_s
        with self._lock:
            oldest = None
            recent = older = 0
            for stamps in self._stamps.values():
                win = stamps["offered"]
                if win:
                    oldest = win[0] if oldest is None else min(oldest, win[0])
                for t in win:
                    if t < horizon:
                        continue
                    if t >= mid:
                        recent += 1
                    else:
                        older += 1
        if oldest is None or oldest > mid:
            return None  # not even the older half has history yet
        return round(((recent / half) - (older / half)) / half, 4)

    def snapshot(self) -> Dict[str, Any]:
        """The live demand table: per-key totals + windowed rps, the
        per-key identity check, and by-model / by-tenant rollups."""
        now = self._clock()
        horizon = now - self.window_s
        span = min(self.window_s, max(now - self._t0, 1e-9))
        with self._lock:
            keys = {
                key: (
                    dict(totals),
                    {c: self._rps(self._stamps[key][c], horizon, span)
                     for c in COUNTERS},
                )
                for key, totals in self._totals.items()
            }
        table: Dict[str, Any] = {}
        by_model: Dict[str, Dict[str, int]] = {}
        by_tenant: Dict[str, Dict[str, int]] = {}
        in_flight = 0
        identity_ok = True
        shed_ratio_max: Optional[float] = None
        offered_rps_total = 0.0
        for key in sorted(keys):
            totals, rps = keys[key]
            delta = totals["offered"] - (
                totals["admitted"] + totals["rejected"] + totals["shed"]
            )
            in_flight += max(delta, 0)
            if delta != 0:
                identity_ok = False
            model, tenant, _ = key.split("|", 2)
            for roll, name in ((by_model, model), (by_tenant, tenant)):
                agg = roll.setdefault(name, {c: 0 for c in COUNTERS})
                for c in COUNTERS:
                    agg[c] += totals[c]
            if totals["offered"]:
                ratio = round(totals["shed"] / totals["offered"], 6)
                shed_ratio_max = (
                    ratio if shed_ratio_max is None
                    else max(shed_ratio_max, ratio)
                )
            offered_rps_total += rps["offered"]
            table[key] = {
                **totals,
                "identity_delta": delta,
                **{f"{c}_rps": rps[c]
                   for c in ("offered", "admitted", "completed", "shed")},
            }
        return {
            "window_s": self.window_s,
            "keys": table,
            "by_model": by_model,
            "by_tenant": by_tenant,
            "offered_rps": round(offered_rps_total, 4),
            "in_flight_decisions": in_flight,
            "identity_ok": identity_ok,
            "demand_shed_ratio_max": shed_ratio_max,
        }


class UtilizationWindows:
    """Rolling host-utilization gauges, sampled by the stats pump at
    the cadence it already runs. Every gauge is optional per sample —
    a non-pooled front end has no replica busy fraction, a traced-off
    run has no queue share — and absent gauges report None, never a
    fabricated figure."""

    GAUGES = (
        "busy_fraction", "occupancy", "queue_share",
        "admission_headroom",
    )

    def __init__(self, *, window: int = 64):
        if window < 1:
            raise ValueError("window must be >= 1")
        self.window = int(window)
        self._lock = threading.Lock()
        # guarded-by: _lock: _win, _residency
        self._win: Dict[str, deque] = {
            g: deque(maxlen=self.window) for g in self.GAUGES
        }
        self._residency: Optional[Dict[str, Any]] = None

    def set_residency(self, block: Optional[Dict[str, Any]]) -> None:
        """The engine's packed-residency block (resident bytes,
        per-bucket activation bytes) — static after warmup, captured
        once at startup."""
        with self._lock:
            self._residency = block

    def sample(self, **gauges: Optional[float]) -> None:
        unknown = set(gauges) - set(self.GAUGES)
        if unknown:
            raise ValueError(f"unknown utilization gauge(s): {unknown}")
        with self._lock:
            for g, v in gauges.items():
                if v is None:
                    continue
                v = float(v)
                if math.isfinite(v):
                    self._win[g].append(v)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            wins = {g: list(w) for g, w in self._win.items()}
            residency = self._residency
        out: Dict[str, Any] = {}
        for g, w in wins.items():
            out[g] = {
                "last": round(w[-1], 4) if w else None,
                "mean": round(sum(w) / len(w), 4) if w else None,
                "n": len(w),
            }
        out["residency"] = residency
        return out


def saturation_headroom(
    *,
    offered_rps: Optional[float],
    completed_rps: Optional[float],
    busy_fraction: Optional[float],
    slope_rps_per_s: Optional[float] = None,
) -> Dict[str, Any]:
    """The saturation-headroom estimate.

    The host completes ``completed_rps`` using ``busy_fraction`` of
    its serving capacity, so at full utilization it could serve about
    ``completed_rps / busy_fraction`` — the capacity estimate.
    Headroom is capacity minus offered demand: negative exactly while
    demand exceeds what the host can serve (a flash crowd), positive
    in steady state. At the observed demand slope, the budget runs
    out in ``headroom / slope`` seconds. Every figure is None when
    its inputs are unmeasurable — an autoscaler must never act on a
    fabricated estimate."""
    capacity = None
    if (
        completed_rps is not None
        and busy_fraction is not None
        and busy_fraction >= MIN_BUSY_FRACTION
    ):
        capacity = round(float(completed_rps) / float(busy_fraction), 4)
    headroom = None
    if capacity is not None and offered_rps is not None:
        headroom = round(capacity - float(offered_rps), 4)
    seconds = None
    if (
        headroom is not None and headroom > 0
        and slope_rps_per_s is not None and slope_rps_per_s > 0
    ):
        seconds = round(headroom / slope_rps_per_s, 2)
    return {
        "capacity_rps_est": capacity,
        "headroom_rps": headroom,
        "demand_slope_rps_per_s": slope_rps_per_s,
        "seconds_to_saturation": seconds,
    }


def _burn(bad: int, total: int, budget_fraction: float) -> Optional[float]:
    """Burn rate over one window: observed bad fraction over the
    budgeted fraction, capped (finite JSON, always). None with no
    traffic — an empty window is "not measured", never a clean bill."""
    if total <= 0:
        return None
    frac = bad / total
    if budget_fraction <= 0:
        return BURN_RATE_CAP if frac > 0 else 0.0
    return round(min(frac / budget_fraction, BURN_RATE_CAP), 4)


class SLOBudget:
    """The per-priority error-budget burn-rate plane.

    One detector per (priority class, objective), each the shared
    :class:`~bdbnn_tpu.obs.health.DetectorState` machine — warmup
    (first ticks are never judged), debounce (a breach must persist),
    hysteresis (fires once, re-arms on recovery). ``feed`` records
    one terminal request event (latency for completions, the shed
    flag for sheds); ``evaluate`` is called by the stats pump at its
    existing cadence and returns the fired/recovered transitions for
    the caller to emit as ``capacity`` events.
    """

    def __init__(
        self,
        *,
        slo_p99_ms: float = 0.0,
        slo_shed_rate: float = 0.0,
        priorities: int = 3,
        fast_window_s: float = DEFAULT_FAST_WINDOW_S,
        slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
        warmup: int = DEFAULT_WARMUP,
        debounce: int = DEFAULT_DEBOUNCE,
        burn_threshold: float = BURN_RATE_THRESHOLD,
        clock: Callable[[], float] = time.monotonic,
    ):
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                "need 0 < fast_window_s <= slow_window_s, got "
                f"{fast_window_s}/{slow_window_s}"
            )
        self.slo_p99_ms = float(slo_p99_ms)
        self.slo_shed_rate = float(slo_shed_rate)
        self.priorities = int(priorities)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._lock = threading.Lock()
        # guarded-by: _lock: _events, _states, _peaks, _open, _episodes
        # per priority: deque[(stamp, latency_ms or None, shed)]
        self._events: Dict[int, deque] = {
            p: deque() for p in range(self.priorities)
        }
        self._states: Dict[str, DetectorState] = {}
        self._peaks: Dict[str, float] = {}
        self._open: Dict[str, Dict[str, Any]] = {}
        self._episodes: List[Dict[str, Any]] = []
        for p in range(self.priorities):
            for objective in self.objectives():
                self._states[self._detector(p, objective)] = (
                    DetectorState(warmup, debounce)
                )

    def objectives(self) -> Tuple[str, ...]:
        out = []
        if self.slo_p99_ms > 0:
            out.append(LATENCY_OBJECTIVE)
        if self.slo_shed_rate > 0:
            out.append(SHED_OBJECTIVE)
        return tuple(out)

    @staticmethod
    def _detector(priority: int, objective: str) -> str:
        return f"p{priority}:{objective}"

    def feed(
        self, priority: int, *, latency_ms: Optional[float] = None,
        shed: bool = False,
    ) -> None:
        """One terminal request event: a completion carries its
        latency, a shed carries the flag. Cheap append under the lock
        — safe at the front end's response sites."""
        p = int(priority)
        if not 0 <= p < self.priorities:
            return
        now = self._clock()
        horizon = now - self.slow_window_s
        with self._lock:
            win = self._events[p]
            win.append((now, latency_ms, bool(shed)))
            while win and win[0][0] < horizon:
                win.popleft()

    def _window_counts(
        self, win: deque, horizon: float
    ) -> Tuple[int, int, int]:  # requires-lock: _lock
        """(total, over-latency-target, shed) at or after horizon."""
        total = bad_lat = shed = 0
        for t, lat, was_shed in reversed(win):
            if t < horizon:
                break
            total += 1
            if was_shed:
                shed += 1
            elif lat is not None and lat > self.slo_p99_ms:
                bad_lat += 1
        return total, bad_lat, shed

    def _burn_rows(self, now: float) -> List[Tuple]:  # requires-lock: _lock
        """(name, priority, objective, burn_fast, burn_slow, breach,
        calm, worst) per detector — the shared computation ``peek``
        reads and ``evaluate`` feeds the machines."""
        rows: List[Tuple] = []
        for p in range(self.priorities):
            win = self._events[p]
            fast = self._window_counts(win, now - self.fast_window_s)
            slow = self._window_counts(win, now - self.slow_window_s)
            for objective in self.objectives():
                name = self._detector(p, objective)
                if objective == LATENCY_OBJECTIVE:
                    burn_fast = _burn(fast[1], fast[0], P99_BUDGET_FRACTION)
                    burn_slow = _burn(slow[1], slow[0], P99_BUDGET_FRACTION)
                else:
                    burn_fast = _burn(fast[2], fast[0], self.slo_shed_rate)
                    burn_slow = _burn(slow[2], slow[0], self.slo_shed_rate)
                breach = (
                    burn_fast is not None and burn_slow is not None
                    and burn_fast > self.burn_threshold
                    and burn_slow > self.burn_threshold
                )
                # recovery = the fast window back under budget (the
                # slow window may legitimately lag an ended burst)
                calm = burn_fast is not None and (
                    burn_fast <= self.burn_threshold
                )
                worst = max(
                    b for b in (burn_fast, burn_slow, 0.0)
                    if b is not None
                )
                rows.append(
                    (name, p, objective, burn_fast, burn_slow, breach,
                     calm, worst)
                )
        return rows

    def _row_dict(
        self, name: str, p: int, objective: str, burn_fast, burn_slow,
        breach: bool,
    ) -> Dict[str, Any]:  # requires-lock: _lock
        state = self._states[name]
        return {
            "priority": p,
            "objective": objective,
            "burn_rate_fast": burn_fast,
            "burn_rate_slow": burn_slow,
            "threshold": self.burn_threshold,
            "breach": breach,
            "latched": state.latched,
            "eligible": state.seen > state.warmup,
        }

    def peek(self) -> Dict[str, Any]:
        """The current per-detector burn-rate table WITHOUT ticking the
        detector machines — what ``/statsz`` serves. Only the stats
        pump's ``evaluate`` advances warmup/debounce state; a client
        scraping fast must not accelerate the debounce clock."""
        now = self._clock()
        with self._lock:
            return {
                name: self._row_dict(name, p, obj, bf, bs, breach)
                for name, p, obj, bf, bs, breach, _, _ in self._burn_rows(
                    now
                )
            }

    def evaluate(self) -> Dict[str, Any]:
        """One budget tick: burn rates per detector over both windows,
        run through the detector machines. Returns the live table plus
        the ``fired`` / ``recovered`` transitions of THIS tick (what
        the caller emits as ``capacity`` events)."""
        now = self._clock()
        fired: List[Dict[str, Any]] = []
        recovered: List[Dict[str, Any]] = []
        detectors: Dict[str, Any] = {}
        with self._lock:
            for (name, p, objective, burn_fast, burn_slow, breach,
                 calm, worst) in self._burn_rows(now):
                state = self._states[name]
                was_latched = state.latched
                just_fired = state.update(breach, recovered=calm)
                if worst > self._peaks.get(name, 0.0):
                    self._peaks[name] = worst
                row = self._row_dict(
                    name, p, objective, burn_fast, burn_slow, breach
                )
                detectors[name] = row
                if just_fired:
                    episode = {
                        "detector": name,
                        "priority": p,
                        "objective": objective,
                        "t_start": round(time.time(), 3),
                        "t_end": None,
                        "peak_burn_rate": worst,
                    }
                    self._open[name] = episode
                    fired.append({**row, "detector": name})
                elif name in self._open:
                    episode = self._open[name]
                    episode["peak_burn_rate"] = max(
                        episode["peak_burn_rate"], worst
                    )
                    if was_latched and not state.latched:
                        episode["t_end"] = round(time.time(), 3)
                        self._episodes.append(self._open.pop(name))
                        recovered.append({**row, "detector": name})
        return {
            "detectors": detectors,
            "fired": fired,
            "recovered": recovered,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The post-hoc budget ledger: objectives, per-detector peak
        burn rates, every closed episode plus the still-open ones."""
        with self._lock:
            peaks = {k: round(v, 4) for k, v in sorted(self._peaks.items())}
            episodes = [dict(e) for e in self._episodes]
            episodes += [dict(e) for _, e in sorted(self._open.items())]
        burn_max = max(peaks.values()) if peaks else None
        return {
            "objectives": {
                "slo_p99_ms": self.slo_p99_ms or None,
                "slo_shed_rate": self.slo_shed_rate or None,
            },
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "threshold": self.burn_threshold,
            "burn_rate_peaks": peaks,
            "burn_rate_max": burn_max,
            "episodes": episodes,
            "breaches": sum(
                1 for e in episodes if e.get("t_start") is not None
            ),
        }


class CapacityPlane:
    """One host's capacity observatory: the ledger + the utilization
    windows + the budget plane, composed into the live ``/statsz``
    block and the verdict's v8 ``capacity`` block. The front end feeds
    the parts directly (``plane.ledger.offered(...)``,
    ``plane.budget.feed(...)``); the stats pump calls ``sample`` +
    ``evaluate`` at its existing cadence."""

    def __init__(
        self,
        *,
        slo_p99_ms: float = 0.0,
        slo_shed_rate: float = 0.0,
        priorities: int = 3,
        window_s: float = DEFAULT_WINDOW_S,
        fast_window_s: float = DEFAULT_FAST_WINDOW_S,
        slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
        warmup: int = DEFAULT_WARMUP,
        debounce: int = DEFAULT_DEBOUNCE,
        util_window: int = 64,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.ledger = DemandLedger(window_s=window_s, clock=clock)
        self.utilization = UtilizationWindows(window=util_window)
        self.budget = SLOBudget(
            slo_p99_ms=slo_p99_ms,
            slo_shed_rate=slo_shed_rate,
            priorities=priorities,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
            warmup=warmup,
            debounce=debounce,
            clock=clock,
        )

    def sample(self, **gauges: Optional[float]) -> None:
        self.utilization.sample(**gauges)

    def evaluate(self) -> Dict[str, Any]:
        return self.budget.evaluate()

    def _headroom(self, demand: Dict[str, Any]) -> Dict[str, Any]:
        util = self.utilization.snapshot()
        completed_rps = sum(
            row.get("completed_rps") or 0.0
            for row in (demand.get("keys") or {}).values()
        )
        return saturation_headroom(
            offered_rps=demand.get("offered_rps"),
            completed_rps=round(completed_rps, 4),
            busy_fraction=(util.get("busy_fraction") or {}).get("mean"),
            slope_rps_per_s=self.ledger.offered_slope_rps_per_s(),
        )

    def live_block(self) -> Dict[str, Any]:
        """The ``/statsz`` ``capacity`` block: current demand table,
        utilization gauges, burn-rate state (a read-only ``peek`` —
        scrapes must not tick the detector machines) and the headroom
        estimate."""
        demand = self.ledger.snapshot()
        return {
            "demand": demand,
            "utilization": self.utilization.snapshot(),
            "slo_budget": {
                "detectors": self.budget.peek(),
                "objectives": {
                    "slo_p99_ms": self.budget.slo_p99_ms or None,
                    "slo_shed_rate": self.budget.slo_shed_rate or None,
                },
            },
            "headroom": self._headroom(demand),
        }

    def verdict_block(self) -> Dict[str, Any]:
        """The verdict's v8 ``capacity`` block. The three flat gates
        ``compare`` judges (``burn_rate_max``, ``headroom_rps``,
        ``demand_shed_ratio_max``) ride at the top level next to the
        full tables they summarize."""
        demand = self.ledger.snapshot()
        budget = self.budget.snapshot()
        headroom = self._headroom(demand)
        return {
            "demand": demand,
            "utilization": self.utilization.snapshot(),
            "slo_budget": budget,
            "headroom": headroom,
            "burn_rate_max": budget.get("burn_rate_max"),
            "headroom_rps": headroom.get("headroom_rps"),
            "demand_shed_ratio_max": demand.get("demand_shed_ratio_max"),
        }


class FleetCapacityWindows:
    """The router-side merge of scraped per-host ``capacity`` blocks,
    under the same staleness discipline as the rtrace metrics plane
    (obs/rtrace.py HostStatsWindows): ``stale_after`` consecutive
    scrape failures freeze a host out of the merged view — an
    autoscaler must never act on a wedged host's frozen numbers."""

    def __init__(self, *, stale_after: int = 3):
        if stale_after < 1:
            raise ValueError("stale_after must be >= 1")
        self.stale_after = int(stale_after)
        self._lock = threading.Lock()
        # guarded-by: _lock: _last, _scrapes, _failures, _fail_streak
        self._last: Dict[str, Optional[Dict[str, Any]]] = {}
        self._scrapes: Dict[str, int] = {}
        self._failures: Dict[str, int] = {}
        self._fail_streak: Dict[str, int] = {}

    def record(
        self, host: str, capacity_block: Optional[Dict[str, Any]]
    ) -> None:
        """One good scrape carrying the host's live capacity block (a
        host running without objectives still reports demand +
        utilization). A payload with no block is a failure — the host
        is not producing the plane."""
        if not isinstance(capacity_block, dict):
            return self.record_failure(host)
        with self._lock:
            self._last[host] = capacity_block
            self._scrapes[host] = self._scrapes.get(host, 0) + 1
            self._fail_streak[host] = 0

    def record_failure(self, host: str) -> None:
        with self._lock:
            self._failures[host] = self._failures.get(host, 0) + 1
            self._fail_streak[host] = self._fail_streak.get(host, 0) + 1

    def snapshot(self) -> Dict[str, Any]:
        """Per-host summaries with staleness plus the merged view over
        FRESH hosts only: offered/headroom rps summed (the fleet's
        aggregate demand and remaining capacity), burn-rate max taken
        as the worst fresh host's (one saturated host is a fleet
        problem even when peers are idle)."""
        with self._lock:
            last = dict(self._last)
            scrapes = dict(self._scrapes)
            failures = dict(self._failures)
            streaks = dict(self._fail_streak)
        for h in set(failures) - set(last):
            last[h] = None
        hosts: Dict[str, Any] = {}
        fresh = stale = 0
        merged_offered: Optional[float] = None
        merged_headroom: Optional[float] = None
        merged_burn: Optional[float] = None
        merged_shed: Optional[float] = None
        for h in sorted(last):
            block = last[h]
            is_stale = streaks.get(h, 0) >= self.stale_after
            if is_stale:
                stale += 1
            else:
                fresh += 1
            demand = (block or {}).get("demand") or {}
            headroom = (block or {}).get("headroom") or {}
            budget = (block or {}).get("slo_budget") or {}
            burn_vals = [
                b
                for row in (budget.get("detectors") or {}).values()
                for b in (row.get("burn_rate_fast"),
                          row.get("burn_rate_slow"))
                if isinstance(b, (int, float)) and math.isfinite(b)
            ]
            row = {
                "stale": is_stale,
                "scrapes": scrapes.get(h, 0),
                "failures": failures.get(h, 0),
                "fail_streak": streaks.get(h, 0),
                "offered_rps": demand.get("offered_rps"),
                "headroom_rps": headroom.get("headroom_rps"),
                "capacity_rps_est": headroom.get("capacity_rps_est"),
                "burn_rate_max": max(burn_vals) if burn_vals else None,
                "demand_shed_ratio_max": demand.get(
                    "demand_shed_ratio_max"
                ),
            }
            hosts[h] = row
            if is_stale or block is None:
                continue
            if row["offered_rps"] is not None:
                merged_offered = (merged_offered or 0.0) + row[
                    "offered_rps"
                ]
            if row["headroom_rps"] is not None:
                merged_headroom = (merged_headroom or 0.0) + row[
                    "headroom_rps"
                ]
            if row["burn_rate_max"] is not None:
                merged_burn = (
                    row["burn_rate_max"] if merged_burn is None
                    else max(merged_burn, row["burn_rate_max"])
                )
            if row["demand_shed_ratio_max"] is not None:
                merged_shed = (
                    row["demand_shed_ratio_max"] if merged_shed is None
                    else max(merged_shed, row["demand_shed_ratio_max"])
                )
        return {
            "stale_after": self.stale_after,
            "hosts_fresh": fresh,
            "hosts_stale": stale,
            "hosts": hosts,
            "merged": {
                "offered_rps": (
                    round(merged_offered, 4)
                    if merged_offered is not None else None
                ),
                "headroom_rps": (
                    round(merged_headroom, 4)
                    if merged_headroom is not None else None
                ),
                "burn_rate_max": merged_burn,
                "demand_shed_ratio_max": merged_shed,
            },
        }


__all__ = [
    "BURN_RATE_CAP",
    "BURN_RATE_THRESHOLD",
    "COUNTERS",
    "DISPOSITIONS",
    "LATENCY_OBJECTIVE",
    "P99_BUDGET_FRACTION",
    "SHED_OBJECTIVE",
    "CapacityPlane",
    "DemandLedger",
    "FleetCapacityWindows",
    "SLOBudget",
    "UtilizationWindows",
    "demand_key",
    "saturation_headroom",
]
