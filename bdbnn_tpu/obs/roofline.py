"""Kernel-grade performance observatory: per-layer roofline + perf CLI.

The ROADMAP's two highest-value open items — the Pallas XNOR-popcount
kernel and end-to-end packed activations (arXiv:1603.05279) — are
blocked on measurement, not code: nothing could say, per conv layer and
per batch bucket, whether the packed paths are memory-bound or
compute-bound, or what the popcount lowering's ceiling actually is
(arXiv:1911.04477's kernel tricks only pay off on memory-bound layers).
This module is that instrument, in three parts:

1. **Static cost model** (:func:`model_layer_table`,
   :func:`layer_regimes`) — per-layer FLOPs and HBM bytes for any
   registry arch, derived generically by walking the flax module tree
   under ``jax.eval_shape`` (zero device work, zero FLOPs executed)
   with ``nn.intercept_methods`` capturing each conv/dense call's
   abstract in/out shapes. Bytes are priced under three regimes —
   ``dense`` (f32 weights + f32 activations), ``packed_weight``
   (XNOR-Net 1-bit weights + alpha, the engine's packed residency) and
   ``packed_act`` (1-bit weights AND 1-bit binary-conv inputs, the
   activation-packing target) — using the SAME byte hooks
   (nn/packed.py) ``engine.residency()`` reports, so the cost model
   and the residency ledger cannot drift. Each (layer, regime) gets an
   arithmetic intensity, a memory/compute bound class against a
   hardware-ceilings table, and a roof ms.

2. **Measured side** (:func:`run_perf`) — sweeps ``InferenceEngine``
   buckets x ``packed_impl`` variants (dense, unpack-dot, popcount —
   and any future Pallas impl for free, it's one more engine ctor
   flag), captures a profiler window per (impl, bucket) with
   ``engine.trace_step``, joins per-layer device ms back to the model
   via the compiled-HLO ``op_name`` metadata
   (``obs.trace.hlo_op_scopes`` — the join that works on CPU, whose
   trace events carry no ``tf_op``), and reports per-layer efficiency
   (roof/achieved) plus a reconciliation of the trace's device-op sum
   against the very ``time_step``-style wall it was captured under.

3. **Perf ledger** — a strict-JSON ``perf_verdict`` (schema v1) in a
   stamped run dir (manifest provenance + ``perf`` events) plus one
   line appended to ``<log_path>/PERF_LEDGER.jsonl``; ``compare``
   judges the flat aggregates AND every shared (layer, bucket, impl)
   ms under ``--tol-rel`` (exit 3 on regression), so a kernel swap
   that wins the aggregate while regressing one layer is caught.

Module-level imports are stdlib-only (obs-package rule — ``summarize``
and ``compare`` import siblings freely); jax/flax load inside the
functions that need them.
"""

from __future__ import annotations

import datetime
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from bdbnn_tpu.obs.trace import BF16_PEAK_TFLOPS

PERF_SCHEMA_VERSION = 1
PERF_VERDICT_NAME = "perf_verdict.json"
PERF_LEDGER_NAME = "PERF_LEDGER.jsonl"
BENCH_ARTIFACT_NAME = "BENCH_perf.json"

# Published per-chip HBM bandwidths (GB/s), keyed like
# trace.BF16_PEAK_TFLOPS on jax.devices()[0].device_kind. Sources:
# Google Cloud TPU system-architecture docs (v2-v6e product pages).
_HBM_GBS: Dict[str, float] = {
    "TPU v2": 700.0,
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,  # v5e
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,      # v5p reports device_kind "TPU v5"
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,  # v6e (Trillium)
    "TPU v6e": 1640.0,
}

# device_kind -> roofline ceilings. TPU rows reuse the SAME peak table
# bench/profile/summarize already cite (obs/trace.py); the cpu row is a
# deliberately conservative host-class stand-in so CPU-mesh perf runs
# (CI, dev boxes) still classify and never divide by zero — real CPU
# studies should pass --ceilings with the host's measured numbers.
CEILINGS: Dict[str, Dict[str, Any]] = {
    **{
        kind: {
            "peak_flops": tf * 1e12,
            "hbm_gbs": _HBM_GBS[kind],
            "source": "cloud TPU system-architecture docs",
        }
        for kind, tf in BF16_PEAK_TFLOPS.items()
    },
    "cpu": {
        "peak_flops": 2.0e11,
        "hbm_gbs": 50.0,
        "source": "conservative host-class default; override --ceilings",
    },
}

# packed_impl -> the byte regime whose roof it is judged against.
# popcount maps to packed_act: the XNOR-popcount dot is the lowering
# the packed-activation regime's roof describes (1-bit operands on
# both sides) — its roof is the idealized ceiling arXiv:1911.04477's
# tricks chase, so efficiency against it shows how far the current
# im2col+pack lowering is from that ceiling.
IMPL_REGIME: Dict[str, str] = {
    "dense": "dense",
    "unpack": "packed_weight",
    "popcount": "packed_act",
}


# ---------------------------------------------------------------------------
# ceilings + pure roofline math
# ---------------------------------------------------------------------------


def resolve_ceilings(
    device_kind: str, override: Any = None
) -> Dict[str, Any]:
    """The ceilings row for ``device_kind``: exact key, else substring
    match (``"TPU v5 lite"`` vs ``"TPU v5e"`` style aliases), else the
    ``cpu`` fallback. ``override`` (a dict, or a path to a JSON file)
    either IS a ceilings row (has ``peak_flops``/``hbm_gbs``) or is a
    table merged over the built-in one before lookup."""
    table = dict(CEILINGS)
    if isinstance(override, str) and override:
        with open(override) as f:
            override = json.load(f)
    if isinstance(override, dict):
        if "peak_flops" in override or "hbm_gbs" in override:
            row = {**table["cpu"], "source": "--ceilings", **override}
            return _ceilings_row(device_kind, device_kind, row)
        table.update(override)
    kind = str(device_kind or "")
    if kind in table:
        return _ceilings_row(kind, kind, table[kind])
    low = kind.lower()
    for k in sorted(table):
        kl = k.lower()
        if kl != "cpu" and (kl in low or low in kl):
            return _ceilings_row(kind, k, table[k])
    return _ceilings_row(kind, "cpu", table["cpu"])


def _ceilings_row(
    device_kind: str, matched: str, row: Dict[str, Any]
) -> Dict[str, Any]:
    peak = float(row["peak_flops"])
    bw = float(row["hbm_gbs"])
    return {
        "device_kind": device_kind,
        "matched": matched,
        "peak_flops": peak,
        "hbm_gbs": bw,
        "ridge_intensity": round(peak / (bw * 1e9), 3),
        "source": row.get("source", "unknown"),
    }


def arithmetic_intensity(flops: float, nbytes: float) -> float:
    """FLOPs per HBM byte — the roofline x-axis."""
    return float(flops) / max(float(nbytes), 1.0)


def ridge_intensity(ceilings: Dict[str, Any]) -> float:
    """The intensity where the memory roof meets the compute roof:
    ``peak_flops / hbm_bytes_per_s``. Below it a kernel is
    bandwidth-limited no matter how good its compute schedule is."""
    return float(ceilings["peak_flops"]) / (
        float(ceilings["hbm_gbs"]) * 1e9
    )


def classify_bound(intensity: float, ceilings: Dict[str, Any]) -> str:
    """``"compute"`` at or above the ridge, else ``"memory"`` — the
    bound class that decides whether a popcount/Pallas compute trick
    can pay off on a layer at all."""
    return (
        "compute" if float(intensity) >= ridge_intensity(ceilings)
        else "memory"
    )


def roof_ms(
    flops: float, nbytes: float, ceilings: Dict[str, Any]
) -> float:
    """Best-case ms for ``flops`` of work moving ``nbytes`` of HBM
    traffic: ``max(compute time, memory time)`` — the classic roofline
    bound, never zero-divided (ceilings are validated positive)."""
    t_compute = float(flops) / float(ceilings["peak_flops"])
    t_memory = float(nbytes) / (float(ceilings["hbm_gbs"]) * 1e9)
    return max(t_compute, t_memory) * 1e3


# ---------------------------------------------------------------------------
# static per-layer cost model
# ---------------------------------------------------------------------------


def model_layer_table(
    arch: str,
    dataset: str,
    batch: int,
    *,
    image_size: Optional[int] = None,
    dtype: str = "float32",
    twoblock: bool = False,
) -> List[Dict[str, Any]]:
    """One row per conv/dense call of ``arch`` at batch ``batch``:
    shapes, FLOPs, and bytes under every packing regime — derived
    GENERICALLY (any registry arch, present or future) by intercepting
    the flax apply under ``jax.eval_shape``, so no weights exist and
    nothing executes.

    Binary-vs-float conv classification reads the variable tree the
    modules themselves declared: binary convs param ``float_weight``
    (nn/layers.py ``_BinaryConvBase``), float convs param ``weight``,
    ``nn.Dense`` param ``kernel``. Rows come back in call order; a
    weight-shared module recorded once (first call)."""
    import flax.linen as fnn
    import jax
    import numpy as np

    from bdbnn_tpu.models.registry import create_model
    from bdbnn_tpu.nn.packed import (
        dense_weight_bytes,
        packed_activation_bytes,
        packed_weight_bytes,
        popcount_word_bytes,
    )

    model = create_model(
        arch, dataset, dtype=dtype, twoblock=bool(twoblock)
    )
    size = (
        int(image_size)
        if image_size
        else (224 if dataset == "imagenet" else 32)
    )
    n = int(batch)
    x = jax.ShapeDtypeStruct((n, size, size, 3), np.float32)
    variables = jax.eval_shape(model.init, jax.random.PRNGKey(0), x)
    params = variables.get("params", {})
    act_bpe = 2 if str(dtype) == "bfloat16" else 4

    rows: List[Dict[str, Any]] = []
    seen: set = set()

    def _params_node(path: Tuple[str, ...]) -> Dict[str, Any]:
        node: Any = params
        for p in path:
            try:
                node = node[p]
            except (KeyError, TypeError):
                return {}
        return node if hasattr(node, "keys") else {}

    def _record(mod, in_shape, out_shape) -> None:
        path = tuple(mod.path)
        if not path or path in seen:
            return
        seen.add(path)
        node = _params_node(path)
        n_in = 1
        for d in in_shape:
            n_in *= int(d)
        n_out = 1
        for d in out_shape:
            n_out *= int(d)
        if isinstance(mod, fnn.Dense):
            kshape = tuple(int(d) for d in node["kernel"].shape)
            row = {
                "name": ".".join(path),
                "scope": "/".join(path),
                "kind": "dense",
                "batch": n,
                "in_shape": [int(d) for d in in_shape],
                "out_shape": [int(d) for d in out_shape],
                "kernel": None,
                "strides": None,
                "flops": 2 * n_out * kshape[0],
                "weight_dense_bytes": dense_weight_bytes(kshape),
                "weight_packed_bytes": dense_weight_bytes(kshape),
                "act_in_bytes": n_in * act_bpe,
                "act_out_bytes": n_out * act_bpe,
                "act_in_packed_bytes": n_in * act_bpe,
                "popcount_word_bytes": None,
            }
        else:
            binary = "float_weight" in node
            w = node["float_weight" if binary else "weight"]
            kh, kw, ci, co = (int(d) for d in w.shape)
            row = {
                "name": ".".join(path),
                "scope": "/".join(path),
                "kind": "binary" if binary else "float",
                "batch": n,
                "in_shape": [int(d) for d in in_shape],
                "out_shape": [int(d) for d in out_shape],
                "kernel": [kh, kw],
                "strides": [int(s) for s in mod.strides],
                # 2 * output elements * kernel volume (MAC = 2 FLOPs)
                "flops": 2 * n_out * kh * kw * ci,
                "weight_dense_bytes": dense_weight_bytes(w.shape),
                "weight_packed_bytes": (
                    packed_weight_bytes(w.shape)
                    if binary
                    else dense_weight_bytes(w.shape)
                ),
                "act_in_bytes": n_in * act_bpe,
                "act_out_bytes": n_out * act_bpe,
                "act_in_packed_bytes": (
                    packed_activation_bytes(n_in)
                    if binary
                    else n_in * act_bpe
                ),
                "popcount_word_bytes": (
                    (n_out // co) * popcount_word_bytes(kh, kw, ci)
                    if binary
                    else None
                ),
            }
        rows.append(row)

    def _interceptor(next_fun, args, kwargs, context):
        out = next_fun(*args, **kwargs)
        mod = context.module
        if (
            getattr(context, "method_name", "__call__") == "__call__"
            and args
            and hasattr(args[0], "shape")
            and hasattr(out, "shape")
            and (
                isinstance(mod, fnn.Dense)
                or (
                    hasattr(mod, "kernel_size")
                    and hasattr(mod, "features")
                )
            )
        ):
            _record(mod, tuple(args[0].shape), tuple(out.shape))
        return out

    with fnn.intercept_methods(_interceptor):
        jax.eval_shape(
            lambda v, xx: model.apply(v, xx, train=False), variables, x
        )
    return rows


def layer_regimes(
    row: Dict[str, Any], ceilings: Dict[str, Any]
) -> Dict[str, Any]:
    """The three byte regimes of one layer row: total HBM bytes,
    intensity, bound class, roof ms. Non-binary layers price all three
    regimes identically (packing does not apply), so regime deltas are
    exactly the binary convs' — the table stays honest about where the
    packing wins live."""
    flops = float(row["flops"])
    wd = int(row["weight_dense_bytes"])
    wp = int(row["weight_packed_bytes"])
    ai = int(row["act_in_bytes"])
    ao = int(row["act_out_bytes"])
    aip = int(row["act_in_packed_bytes"])
    out: Dict[str, Any] = {}
    for name, nbytes in (
        ("dense", wd + ai + ao),
        ("packed_weight", wp + ai + ao),
        ("packed_act", wp + aip + ao),
    ):
        inten = arithmetic_intensity(flops, nbytes)
        out[name] = {
            "bytes": int(nbytes),
            "intensity": round(inten, 3),
            "bound": classify_bound(inten, ceilings),
            "roof_ms": round(roof_ms(flops, nbytes, ceilings), 6),
        }
    return out


def static_table(
    rows: List[Dict[str, Any]], ceilings: Dict[str, Any]
) -> List[Dict[str, Any]]:
    """Layer rows + their :func:`layer_regimes` — the static half of
    the verdict, one list per bucket."""
    return [{**r, "regimes": layer_regimes(r, ceilings)} for r in rows]


# ---------------------------------------------------------------------------
# measured sweep + verdict
# ---------------------------------------------------------------------------


def _write_json_atomic(path: str, payload: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def run_perf(cfg) -> Dict[str, Any]:
    """The ``perf`` subcommand: static roofline + measured bucket/impl
    sweep + persisted ledger. Returns ``{"verdict", "run_dir"}``."""
    import jax

    from bdbnn_tpu.obs.events import EventWriter, jsonsafe
    from bdbnn_tpu.obs.manifest import write_manifest
    from bdbnn_tpu.obs.trace import (
        attribute_trace_layers,
        find_trace_file,
        hlo_module_name,
        hlo_op_scopes,
    )
    from bdbnn_tpu.serve.export import read_artifact

    artifact = read_artifact(cfg.artifact)
    arch = artifact["arch"]
    dataset = artifact["dataset"]
    model_dtype = artifact.get("model", {}).get("dtype", "float32")
    twoblock = bool(artifact.get("model", {}).get("twoblock", False))
    buckets = tuple(sorted({int(b) for b in cfg.buckets}))

    stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    run_dir = os.path.join(cfg.log_path, stamp)
    os.makedirs(run_dir, exist_ok=True)
    manifest = write_manifest(run_dir, cfg, extra={"mode": "perf"})
    writer = EventWriter(
        run_dir, max_bytes=int(cfg.events_max_mb * 2**20)
    )
    try:
        dev = jax.devices()[0]
        ceilings = resolve_ceilings(
            dev.device_kind, cfg.ceilings or None
        )
        writer.emit(
            "perf",
            phase="start",
            run_dir=run_dir,
            artifact=cfg.artifact,
            arch=arch,
            dataset=dataset,
            device_kind=dev.device_kind,
            buckets=list(buckets),
            impls=list(cfg.impls),
            iters=int(cfg.iters),
        )

        # static side: the cost model per bucket (batch size changes
        # activation bytes, hence intensity and bound class)
        layer_rows: Dict[int, List[Dict[str, Any]]] = {}
        static: Dict[str, Any] = {}
        for b in buckets:
            rows = model_layer_table(
                arch,
                dataset,
                b,
                image_size=int(artifact["image_size"]),
                dtype=model_dtype,
                twoblock=twoblock,
            )
            layer_rows[b] = rows
            static[str(b)] = static_table(rows, ceilings)

        measured: Dict[str, Any] = {}
        skipped: List[Dict[str, Any]] = []
        perf_layers: Dict[str, float] = {}
        if not cfg.static_only:
            from bdbnn_tpu.serve.engine import InferenceEngine

            for impl in cfg.impls:
                if impl == "popcount" and model_dtype == "bfloat16":
                    skipped.append({
                        "impl": impl,
                        "reason": (
                            "popcount needs a float32 artifact; this "
                            "one records dtype=bfloat16"
                        ),
                    })
                    continue
                engine = InferenceEngine(
                    cfg.artifact,
                    buckets=buckets,
                    packed=impl != "dense",
                    packed_impl=impl if impl != "dense" else "unpack",
                )
                regime = IMPL_REGIME.get(impl, "packed_weight")
                per_bucket: Dict[str, Any] = {}
                for b in buckets:
                    tdir = os.path.join(
                        run_dir, "traces", f"{impl}_b{b}"
                    )
                    t = engine.trace_step(
                        tdir, bucket=b, iters=int(cfg.iters)
                    )
                    trace_file = find_trace_file(tdir)
                    hlo = engine.hlo_text(b)
                    att = (
                        attribute_trace_layers(
                            trace_file,
                            t["iters"],
                            layers={
                                r["name"]: r["scope"]
                                for r in layer_rows[b]
                            },
                            op_scopes=hlo_op_scopes(hlo),
                            module=hlo_module_name(hlo),
                        )
                        if trace_file
                        else None
                    )
                    stat_by_name = {
                        r["name"]: r for r in static[str(b)]
                    }
                    layers_out: Dict[str, Any] = {}
                    if att:
                        for name, ms in att["layers"].items():
                            reg = stat_by_name[name]["regimes"][regime]
                            eff = (
                                reg["roof_ms"] / ms if ms > 0 else None
                            )
                            layers_out[name] = {
                                "ms": ms,
                                "roof_ms": reg["roof_ms"],
                                "efficiency": (
                                    round(eff, 4)
                                    if eff is not None
                                    else None
                                ),
                                "bound": reg["bound"],
                                "intensity": reg["intensity"],
                            }
                            perf_layers[f"{name}|b{b}|{impl}"] = ms
                    wall = t["wall_ms"]
                    recon = None
                    if att and wall:
                        attributed = round(
                            sum(att["layers"].values()), 4
                        )
                        total = att["total_ms"]
                        err = abs(total - wall) / wall
                        recon = {
                            "wall_ms": wall,
                            "attributed_ms": attributed,
                            "device_total_ms": total,
                            "unattributed_ms": att["unattributed"],
                            "abs_err_pct": round(err * 100.0, 2),
                            "ok": err <= float(cfg.tol_reconcile),
                        }
                    per_bucket[str(b)] = {
                        "wall_ms": wall,
                        "traced": trace_file is not None,
                        "layers": layers_out,
                        "reconciliation": recon,
                    }
                    writer.emit(
                        "perf",
                        phase="bucket",
                        impl=impl,
                        bucket=b,
                        wall_ms=wall,
                        attributed_ms=(recon or {}).get(
                            "attributed_ms"
                        ),
                        reconciled=(recon or {}).get("ok"),
                    )
                measured[impl] = per_bucket

        summary = _summarize_measured(
            measured, buckets, static, ceilings
        )
        verdict = jsonsafe({
            "perf_verdict": PERF_SCHEMA_VERSION,
            "artifact": cfg.artifact,
            "arch": arch,
            "dataset": dataset,
            "dtype": model_dtype,
            "device_kind": dev.device_kind,
            "backend": dev.platform,
            "buckets": list(buckets),
            "impls": list(cfg.impls),
            "iters": int(cfg.iters),
            "ceilings": ceilings,
            "static": static,
            "measured": measured,
            "skipped": skipped,
            "perf_layers": perf_layers,
            "summary": summary,
            "provenance": {
                "config_hash": manifest.get("config_hash"),
                "device_kind": manifest.get("device_kind"),
                "recipe": {
                    "arch": arch,
                    "dataset": dataset,
                    "dtype": model_dtype,
                    "twoblock": twoblock,
                },
            },
        })
        _write_json_atomic(
            os.path.join(run_dir, PERF_VERDICT_NAME), verdict
        )
        if getattr(cfg, "out", ""):
            _write_json_atomic(cfg.out, verdict)
        _write_json_atomic(
            os.path.join(run_dir, BENCH_ARTIFACT_NAME),
            _bench_artifact(verdict),
        )
        ledger_line = jsonsafe({
            "t": round(time.time(), 3),
            "schema": PERF_SCHEMA_VERSION,
            "run_dir": run_dir,
            "config_hash": manifest.get("config_hash"),
            "device_kind": dev.device_kind,
            "arch": arch,
            "dataset": dataset,
            "dtype": model_dtype,
            "summary": summary,
            "perf_layers": perf_layers,
            "skipped": [s["impl"] for s in skipped],
        })
        with open(
            os.path.join(cfg.log_path, PERF_LEDGER_NAME), "a"
        ) as f:
            f.write(json.dumps(ledger_line, sort_keys=True) + "\n")
        writer.emit(
            "perf", phase="verdict", run_dir=run_dir, verdict=verdict
        )
    finally:
        writer.close()
    return {"verdict": verdict, "run_dir": run_dir}


def _summarize_measured(
    measured: Dict[str, Any],
    buckets: Tuple[int, ...],
    static: Dict[str, Any],
    ceilings: Dict[str, Any],
) -> Dict[str, Any]:
    """Flat aggregates ``compare`` judges (the per-layer keys are
    judged separately): best/dense/packed step ms at the LARGEST
    bucket (the throughput-representative point), mean per-layer
    efficiency, mean attributed share, and an MFU estimate at the
    best step."""
    big = str(buckets[-1]) if buckets else None
    walls = {
        impl: (pb.get(big) or {}).get("wall_ms")
        for impl, pb in measured.items()
    }
    vals = [v for v in walls.values() if v is not None]
    packed_vals = [
        v for k, v in walls.items() if k != "dense" and v is not None
    ]
    effs = [
        lay["efficiency"]
        for pb in measured.values()
        for bkt in pb.values()
        for lay in bkt["layers"].values()
        if lay["efficiency"] is not None
    ]
    shares = []
    for pb in measured.values():
        for bkt in pb.values():
            recon = bkt.get("reconciliation")
            if recon and recon.get("device_total_ms"):
                shares.append(
                    recon["attributed_ms"] / recon["device_total_ms"]
                )
    step_best = min(vals) if vals else None
    mfu = None
    if step_best and big:
        flops = sum(float(r["flops"]) for r in static.get(big, []))
        if flops:
            mfu = round(
                flops
                / (step_best / 1e3)
                / float(ceilings["peak_flops"]),
                4,
            )
    return {
        "bucket": int(big) if big else None,
        "step_ms_best": step_best,
        "step_ms_dense": walls.get("dense"),
        "step_ms_packed": min(packed_vals) if packed_vals else None,
        "efficiency_mean": (
            round(sum(effs) / len(effs), 4) if effs else None
        ),
        "attributed_share": (
            round(sum(shares) / len(shares), 4) if shares else None
        ),
        "mfu_best": mfu,
    }


def _bench_artifact(verdict: Dict[str, Any]) -> Dict[str, Any]:
    """``BENCH_*``-compatible top-level summary: the ``parsed`` line
    compare's bench-artifact path already reads (value = img/s at the
    summary bucket, device_ms_per_step = best step ms) — so perf runs
    populate the bench trajectory from schema'd data instead of
    hand-rolled harness output."""
    s = verdict.get("summary") or {}
    step = s.get("step_ms_best")
    bucket = s.get("bucket")
    value = (
        round(float(bucket) * 1000.0 / float(step), 2)
        if step and bucket
        else None
    )
    return {
        "bench": "perf",
        "schema": PERF_SCHEMA_VERSION,
        "parsed": {
            "metric": "img_per_s",
            "value": value,
            "device_ms_per_step": step,
            "device_mfu": s.get("mfu_best"),
            "device_kind": verdict.get("device_kind"),
            "dtype": verdict.get("dtype"),
        },
        "provenance": (verdict.get("provenance") or {}),
    }


# ---------------------------------------------------------------------------
# rendering (CLI + summarize share it)
# ---------------------------------------------------------------------------


def render_perf(verdict: Dict[str, Any]) -> str:
    """Human tables for one perf verdict: ceilings line, per-bucket
    bound-class table (static), and per-(impl, bucket) layer
    efficiency with reconciliation."""
    c = verdict.get("ceilings") or {}
    lines = [
        f"== Perf roofline: {verdict.get('arch')}/"
        f"{verdict.get('dataset')} on {verdict.get('device_kind')} "
        f"({verdict.get('dtype')})"
    ]
    if c:
        lines.append(
            f"ceilings[{c.get('matched')}]: "
            f"{c.get('peak_flops', 0) / 1e12:.4g} TFLOP/s, "
            f"{c.get('hbm_gbs', 0):.4g} GB/s "
            f"(ridge {c.get('ridge_intensity')} FLOP/byte)"
        )
    for b, rows in sorted(
        (verdict.get("static") or {}).items(), key=lambda kv: int(kv[0])
    ):
        counts: Dict[str, Any] = {}
        for r in rows:
            for reg, info in r["regimes"].items():
                counts.setdefault(reg, {"memory": 0, "compute": 0})
                counts[reg][info["bound"]] += 1
        parts = ", ".join(
            f"{reg}: {v['memory']}M/{v['compute']}C"
            for reg, v in sorted(counts.items())
        )
        lines.append(f"bucket {b} bound classes ({parts})")
    for impl, pb in sorted((verdict.get("measured") or {}).items()):
        for b, bkt in sorted(pb.items(), key=lambda kv: int(kv[0])):
            recon = bkt.get("reconciliation") or {}
            ok = recon.get("ok")
            lines.append(
                f"-- {impl} b{b}: wall {bkt.get('wall_ms')} ms, "
                f"attributed {recon.get('attributed_ms')} ms, "
                f"reconcile "
                f"{'ok' if ok else 'MISS' if ok is not None else 'n/a'}"
                f" (err {recon.get('abs_err_pct')}%)"
            )
            layers = bkt.get("layers") or {}
            if layers:
                lines.append(
                    f"   {'layer':<24} {'ms':>8} {'roof':>9} "
                    f"{'eff':>6}  bound"
                )
                for name, lay in sorted(
                    layers.items(), key=lambda kv: -kv[1]["ms"]
                ):
                    eff = lay.get("efficiency")
                    lines.append(
                        f"   {name:<24} {lay['ms']:>8.3f} "
                        f"{lay['roof_ms']:>9.4f} "
                        f"{eff if eff is not None else '-':>6} "
                        f" {lay['bound']}"
                    )
    s = verdict.get("summary") or {}
    if s:
        lines.append(
            f"summary: best {s.get('step_ms_best')} ms @ bucket "
            f"{s.get('bucket')} (dense {s.get('step_ms_dense')}, "
            f"packed {s.get('step_ms_packed')}), efficiency mean "
            f"{s.get('efficiency_mean')}, attributed share "
            f"{s.get('attributed_share')}"
        )
    for skip in verdict.get("skipped") or []:
        lines.append(
            f"skipped {skip.get('impl')}: {skip.get('reason')}"
        )
    return "\n".join(lines)


__all__ = [
    "BENCH_ARTIFACT_NAME",
    "CEILINGS",
    "IMPL_REGIME",
    "PERF_LEDGER_NAME",
    "PERF_SCHEMA_VERSION",
    "PERF_VERDICT_NAME",
    "arithmetic_intensity",
    "classify_bound",
    "layer_regimes",
    "model_layer_table",
    "render_perf",
    "resolve_ceilings",
    "ridge_intensity",
    "roof_ms",
    "run_perf",
    "static_table",
]
