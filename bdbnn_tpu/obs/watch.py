"""``watch`` — live status of a running (or finished) run directory.

``python -m bdbnn_tpu.cli watch RUN_DIR [--interval S] [--once]``
tails ``events.jsonl`` and re-renders a compact status block whenever
the file grows: current epoch/step, last eval accuracy, flip-rate
drift, the input-starvation flag, non-finite incidents, checkpoint
freshness (seconds since the last committed checkpoint — the work a
preemption RIGHT NOW would throw away — plus the run's restart count),
live health alerts (count by detector + seconds since the newest,
obs/health.py), and the final verdict once ``run_end`` lands. Where ``summarize`` is
the post-mortem, ``watch`` is the heartbeat — same files, no JAX
backend, so it can run on a laptop against a pod run's synced log dir.

Stdlib-only (obs-package rule).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from bdbnn_tpu.obs.events import EVENTS_NAME, read_events
from bdbnn_tpu.obs.manifest import read_manifest
from bdbnn_tpu.obs.summarize import INPUT_BOUND_SHARE


def _mean(vals: List[float]) -> Optional[float]:
    return sum(vals) / len(vals) if vals else None


def _serve_lines(events) -> List[str]:
    """The serving view: when a timeline carries ``serve`` events (a
    ``serve-bench`` run dir) render live queue depth, batch occupancy,
    rolling p99 and shed count; a ``serve-http`` run dir additionally
    gets the front end's readiness state, per-priority queue depths
    and per-tenant shed counters (the ``http``/``admission`` trail);
    ``export`` events on a TRAINING run's timeline get a one-line
    hand-off note."""
    from bdbnn_tpu.obs.events import serve_digest

    digest = serve_digest(events)
    lines: List[str] = []
    for e in digest["exports"]:
        lines.append(
            f"export: {e.get('artifact')} (arch {e.get('arch')}, "
            f"{e.get('binarized_convs')} binary convs, "
            f"{e.get('compression_ratio')}x smaller, recorded acc1 "
            f"{e.get('checkpoint_acc1')})"
        )
    start = digest["start"]
    stats = digest["stats"]
    verdict = digest["verdict"]
    http_start = digest["http_start"]
    http_stats = digest["http_stats"]
    http_drain = digest["http_drain"]
    if start:
        lines.append(
            f"serve: {start.get('mode')} load on {start.get('arch')} | "
            f"buckets {start.get('buckets')} | queue bound "
            f"{start.get('queue_depth')} | {start.get('requests')} requests"
        )
    if http_start:
        lines.append(
            f"http:  {http_start.get('host')}:{http_start.get('port')} "
            f"on {http_start.get('arch')} | "
            f"{http_start.get('priorities')} priority classes x queue "
            f"{http_start.get('queue_depth')} | buckets "
            f"{http_start.get('buckets')}"
            + (
                f" | scenario {http_start.get('scenario')}"
                if http_start.get("scenario")
                else ""
            )
        )
    fleet_start = digest["fleet_start"]
    fleet_stats = digest["fleet_stats"]
    if fleet_start:
        lines.append(
            f"fleet: router {fleet_start.get('host')}:"
            f"{fleet_start.get('port')} over "
            f"{len(fleet_start.get('hosts') or [])} host(s)"
            + (
                f" | scenario {fleet_start.get('scenario')}"
                if fleet_start.get("scenario") else ""
            )
        )
    if fleet_stats and verdict is None:
        # the live per-host health/occupancy table: one row per host —
        # state, in-flight, proxied/completed, retries burned — plus a
        # loud banner for every host the prober has declared dead
        age = time.time() - float(fleet_stats.get("t", time.time()))
        lines.append(
            f"hosts: {fleet_stats.get('hosts_ready')}/"
            f"{fleet_stats.get('hosts_total')} ready | inflight "
            f"{fleet_stats.get('inflight')} | unrouteable "
            f"{fleet_stats.get('unrouteable')} | {age:.0f}s ago"
        )
        lines.append(
            f"  {'id':<4} {'host':<18} {'state':<9} {'infl':>5} "
            f"{'proxied':>8} {'done':>8} {'retries':>8}"
        )
        for label in sorted(fleet_stats.get("hosts") or {}):
            h = (fleet_stats.get("hosts") or {})[label]
            retries = sum((h.get("retries") or {}).values())
            lines.append(
                f"  {label:<4} "
                f"{str(h.get('host')) + ':' + str(h.get('port')):<18} "
                f"{str(h.get('state')):<9} {h.get('inflight'):>5} "
                f"{h.get('proxied'):>8} {h.get('completed'):>8} "
                f"{retries:>8}"
            )
            if h.get("state") == "dead":
                lines.append(
                    f"  !! host {label} DEAD — its traffic is being "
                    "answered by peers (retry ledger above)"
                )
        fswap = fleet_stats.get("swap")
        if fswap and fswap.get("state") in ("replicating", "shifting"):
            lines.append(
                f">> FLEET SWAP {fswap.get('state')}: "
                f"{len(fswap.get('hosts_shifted') or [])}/"
                f"{fswap.get('hosts_total')} hosts shifted "
                "(one at a time — dispatch never loses two hosts)"
            )
        frt = fleet_stats.get("rtrace")
        if frt:
            # the live cross-host waterfall: the router's own stage
            # windows (probe_wait/pick/connect/retry_hop/network) plus
            # the stitched backend decomposition, WHILE it happens
            parts = [
                f"{stage} {ms:.1f}"
                for stage, ms in (frt.get("stage_p99_ms") or {}).items()
                if ms is not None
            ]
            share = frt.get("retry_hop_share")
            lines.append(
                "trace: fleet p99/stage ms  " + " > ".join(parts)
                + (
                    f" | retry-hop share {share:.1%}"
                    if share is not None else ""
                )
                + f" | stitched {frt.get('stitched')}"
                + f"/{frt.get('requests')}"
            )
            bparts = [
                f"{stage} {ms:.1f}"
                for stage, ms in (
                    frt.get("backend_stage_p99_ms") or {}
                ).items()
                if ms is not None
            ]
            if bparts:
                lines.append(
                    "       backend p99/stage ms  " + " > ".join(bparts)
                )
        fwin = fleet_stats.get("host_windows")
        if fwin and fwin.get("hosts"):
            # the scraped per-host stage table — a host whose /statsz
            # stopped answering is marked STALE (its window is frozen
            # and excluded from the merged view), never rendered as
            # live data
            lines.append(
                f"scrape: {fwin.get('hosts_fresh')} fresh / "
                f"{fwin.get('hosts_stale')} stale host window(s)"
            )
            for label in sorted(fwin.get("hosts") or {}):
                hw = (fwin.get("hosts") or {})[label]
                parts = [
                    f"{stage} {ms:.1f}"
                    for stage, ms in (
                        hw.get("stage_p99_ms") or {}
                    ).items()
                    if ms is not None
                ]
                lines.append(
                    f"  {label:<4} "
                    + (
                        "STALE "
                        f"({hw.get('fail_streak')} failed scrape(s))"
                        if hw.get("stale")
                        else " > ".join(parts) if parts
                        else "no samples yet"
                    )
                )
        fcap = fleet_stats.get("capacity")
        if fcap and fcap.get("hosts"):
            # the fleet-merged capacity view: summed demand/headroom
            # over FRESH hosts, worst burn rate across the fleet
            merged = fcap.get("merged") or {}
            lines.append(
                f"capacity: fleet offered {merged.get('offered_rps')} "
                f"rps | headroom {merged.get('headroom_rps')} rps | "
                f"burn max {merged.get('burn_rate_max')} "
                f"({fcap.get('hosts_fresh')} fresh / "
                f"{fcap.get('hosts_stale')} stale)"
            )
    if digest["fleet_drain"] and verdict is None:
        lines.append(
            f"!! fleet draining (signal "
            f"{digest['fleet_drain'].get('signum')}) — in-flight "
            "proxies finishing, router readyz is 503"
        )
    replica_stats = digest["replica_stats"]
    swap_last = digest["swap_last"]
    if replica_stats and verdict is None:
        # the live per-replica table: one row per replica — version,
        # health state, queue depth, completed — plus the
        # completed-by-version ledger once a swap has split it
        lines.append(
            f"replicas ({replica_stats.get('version')}): "
            f"{replica_stats.get('completed')} done | "
            f"{replica_stats.get('restarts')} restart(s)"
        )
        lines.append(
            f"  {'id':<4} {'device':<14} {'version':<10} {'state':<10} "
            f"{'queue':>5} {'done':>8}"
        )
        for r in replica_stats.get("replicas") or []:
            lines.append(
                f"  {r.get('replica'):<4} {str(r.get('device')):<14} "
                f"{str(r.get('version')):<10} {str(r.get('state')):<10} "
                f"{r.get('queue_depth'):>5} {r.get('completed'):>8}"
            )
        by_version = replica_stats.get("completed_by_version") or {}
        if len(by_version) > 1:
            lines.append(
                "  answered by: "
                + "  ".join(
                    f"{v}: {n}" for v, n in sorted(by_version.items())
                )
            )
    if swap_last and verdict is None:
        phase = swap_last.get("phase")
        if phase == "trigger" and swap_last.get("status") not in (
            None, 202,
        ):
            # a rejected scheduled trigger (400/404/409) is TERMINAL
            # for THIS trigger — no start/failed event ever follows it,
            # so an in-progress banner here would stick for the rest of
            # the run. But a 409 can mean a swap is ALREADY in flight
            # (operator-initiated), so only the other statuses may
            # claim no rollout is running.
            status = swap_last.get("status")
            tail = (
                "this trigger started nothing (another rollout "
                "may be mid-flight)"
                if status == 409 else "no rollout is running"
            )
            lines.append(
                f"!! swap trigger REJECTED (HTTP {status}): "
                f"{swap_last.get('error')} — {tail}"
            )
        elif phase in ("trigger", "start", "warm", "shift"):
            progress = ""
            swap_state = (replica_stats or {}).get("swap") or {}
            if swap_state.get("replicas_total"):
                progress = (
                    f" [{swap_state.get('replicas_shifted', 0)}/"
                    f"{swap_state.get('replicas_total')} shifted]"
                )
            lines.append(
                f">> SWAP in progress: "
                f"{swap_last.get('version_from') or '...'} -> "
                f"{swap_last.get('version_to')}{progress} "
                f"(phase {phase}) — traffic keeps flowing"
            )
        elif phase == "done":
            lines.append(
                f"swap: {swap_last.get('version_from')} -> "
                f"{swap_last.get('version_to')} DONE in "
                f"{swap_last.get('seconds')}s "
                f"({swap_last.get('replicas_shifted')} replicas)"
            )
        elif phase == "failed":
            lines.append(
                f"!! swap to {swap_last.get('version_to')} FAILED "
                f"({swap_last.get('error')}) — old version kept serving"
            )
        elif phase == "rolled_back":
            lines.append(
                f"!! CANARY ROLLBACK: {swap_last.get('version_to')} "
                f"rejected (trigger {swap_last.get('trigger')}) in "
                f"{swap_last.get('seconds')}s — "
                f"{swap_last.get('version_from')} kept serving, "
                "registry untouched"
            )
    canary_last = digest["canary_last"]
    canary_eval = digest["canary_last_evaluate"]
    if canary_last and verdict is None:
        phase = canary_last.get("phase")
        if phase in ("start", "observing", "evaluate", "decision"):
            # the live canary banner: fraction + windows from the
            # newest evaluate tick, one status mark per detector
            ev = canary_eval or {}
            dets = ev.get("detectors") or {}
            marks = []
            for name in sorted(dets):
                d = dets[name] or {}
                if d.get("fired"):
                    marks.append(f"{name}:FIRED")
                elif d.get("breach"):
                    marks.append(f"{name}:breach")
                elif not d.get("eligible"):
                    marks.append(f"{name}:warming")
                else:
                    marks.append(f"{name}:ok")
            start = next(
                (
                    e for e in digest["canary_events"]
                    if e.get("phase") == "start"
                ),
                {},
            )
            lines.append(
                f">> CANARY {start.get('version_from')} -> "
                f"{start.get('version_to')}: observing | fraction "
                f"{start.get('fraction')} | replicas "
                f"{start.get('replicas_canary')} | eval "
                f"#{ev.get('evaluation', 0)} | served canary "
                f"{ev.get('canary_served', 0)} / incumbent "
                f"{ev.get('incumbent_served', 0)}"
            )
            if marks:
                lines.append("   detectors: " + "  ".join(marks))
        elif phase == "rollback":
            lines.append(
                f"!! CANARY ROLLBACK in progress: replica "
                f"{canary_last.get('replica')} restoring "
                f"{canary_last.get('version_restored')}"
            )
        elif phase == "promote":
            lines.append(
                f"canary: {canary_last.get('version_from')} -> "
                f"{canary_last.get('version_to')} PROMOTED in "
                f"{canary_last.get('seconds')}s "
                f"({canary_last.get('evaluations')} evaluations)"
            )
    if http_stats and verdict is None:
        s = http_stats[-1]
        age = time.time() - float(s.get("t", time.time()))
        state = s.get("state")
        mark = {"ready": "READY", "warming": "WARMING",
                "draining": "DRAINING"}.get(state, str(state))
        lines.append(
            f"state: {mark} | inflight {s.get('inflight')} | "
            f"queues/prio {s.get('queue_depth_by_priority')} | "
            f"done/prio {s.get('completed_by_priority')} | "
            f"shed/prio {s.get('shed_by_priority')} | {age:.0f}s ago"
        )
        tenants = s.get("tenants") or {}
        if tenants:
            lines.append(
                "tenants: "
                + "  ".join(
                    f"{t}: {c.get('admitted')} ok / "
                    f"{c.get('over_quota')} over-quota / "
                    f"{c.get('shed')} shed"
                    for t, c in sorted(tenants.items())
                )
            )
    if http_drain and verdict is None:
        lines.append(
            f"!! draining (signal {http_drain.get('signum')}) — "
            "accepted requests finishing, readyz is 503"
        )
    if stats and verdict is None:
        s = stats[-1]
        age = time.time() - float(s.get("t", time.time()))
        occ = float(s.get("occupancy") or 0.0)
        lines.append(
            f"live:  queue {s.get('queue_depth')} | occupancy "
            f"{occ:.0%} | rolling p99 {s.get('rolling_p99_ms')} ms | "
            f"shed {s.get('shed')} | {s.get('completed')} done | "
            f"{age:.0f}s ago"
        )
    rtrace = digest["rtrace_stats"]
    if rtrace and verdict is None:
        # the live waterfall: per-stage p99 over the rolling windows —
        # queue-bound vs device-bound, WHILE it happens
        stage_p99 = rtrace.get("stage_p99_ms") or {}
        parts = [
            f"{stage} {ms:.1f}"
            for stage, ms in stage_p99.items()
            if ms is not None
        ]
        share = rtrace.get("queue_share")
        lines.append(
            "trace: p99/stage ms  " + " > ".join(parts)
            + (
                f" | queue share {share:.0%}"
                if share is not None else ""
            )
        )
    cap_stats = digest["capacity_stats"]
    if cap_stats and verdict is None:
        # the live capacity gauges (obs/capacity.py heartbeat): demand
        # rate, in-flight, headroom estimate and the worst burn rate —
        # WHILE the run serves
        hr = cap_stats.get("headroom") or {}
        burns = [
            b
            for row in (cap_stats.get("detectors") or {}).values()
            for b in (row.get("burn_rate_fast"),
                      row.get("burn_rate_slow"))
            if b is not None
        ]
        lines.append(
            f"capacity: offered {cap_stats.get('offered_rps')} rps | "
            f"in-flight {cap_stats.get('in_flight')}"
            + (
                f" | headroom {hr.get('headroom_rps')} rps"
                if hr.get("headroom_rps") is not None else ""
            )
            + (f" | burn max {max(burns)}" if burns else "")
        )
        latched = sorted(
            name
            for name, row in (cap_stats.get("detectors") or {}).items()
            if row.get("latched")
        )
        if latched:
            lines.append(
                "!! SLO BUDGET BURNING: " + ", ".join(latched)
            )
    if verdict:
        shed_rate = float(verdict.get("shed_rate") or 0.0)
        lines.append(
            f"SLO:   p50 {verdict.get('p50_ms')} / p95 "
            f"{verdict.get('p95_ms')} / p99 {verdict.get('p99_ms')} ms | "
            f"{verdict.get('throughput_rps')} req/s | occupancy "
            f"{verdict.get('mean_batch_occupancy')} | shed "
            f"{shed_rate:.1%}"
            + (" | PREEMPTED, drained" if verdict.get("preempted") else "")
        )
        per_priority = verdict.get("per_priority") or {}
        for p in sorted(per_priority, key=int):
            v = per_priority[p]
            lines.append(
                f"  p{p}: p99 {v.get('p99_ms')} ms | "
                f"{v.get('completed')}/{v.get('submitted')} ok | "
                f"shed {v.get('shed')}"
            )
        fr = verdict.get("fairness_ratio")
        if fr is not None:
            lines.append(f"  fairness: max/min tenant service {fr}")
        replicas = verdict.get("replicas")
        if replicas:
            lines.append(
                f"  replicas: {replicas.get('n')} on "
                f"{replicas.get('version')} | "
                f"{replicas.get('restarts')} restart(s) | shares "
                + " ".join(
                    f"r{r.get('replica')}:{r.get('share'):.0%}"
                    for r in replicas.get("per_replica") or []
                )
            )
        scaling = verdict.get("scaling")
        if scaling:
            lines.append(
                "  scaling: "
                + " -> ".join(
                    f"{n}x {scaling['throughput_rps'].get(str(n))}rps"
                    for n in scaling.get("replicas") or []
                )
                + f" | efficiency {scaling.get('efficiency')}"
                + ("" if scaling.get("monotone") else " | NOT MONOTONE")
            )
        swap = verdict.get("swap")
        if swap:
            lines.append(
                f"  swap: {swap.get('version_from')} -> "
                f"{swap.get('version_to')} "
                + ("DONE" if swap.get("performed")
                   else f"{swap.get('state')}")
                + f" | shed {swap.get('shed')} | answered by "
                + "  ".join(
                    f"{v}: {n}"
                    for v, n in sorted(
                        (swap.get("answered_by") or {}).items()
                    )
                )
            )
        can = verdict.get("canary")
        if can:
            decision = can.get("decision")
            shadow = can.get("shadow") or {}
            lines.append(
                f"  canary: fraction {can.get('fraction')} | "
                + (
                    f"ROLLED BACK (trigger {can.get('trigger')})"
                    if decision == "rollback"
                    else f"promoted in {can.get('promote_s')}s"
                    if decision == "promote"
                    else str(decision)
                )
                + f" after {can.get('evaluations')} evaluation(s) | "
                f"shadow drift "
                f"{shadow.get('max_abs_drift')} over "
                f"{shadow.get('compared')} mirror(s)"
            )
            fired = [
                name
                for name, d in sorted(
                    (can.get("detectors") or {}).items()
                )
                if (d or {}).get("fired")
            ]
            if fired:
                lines.append(
                    "    fired detectors: " + ", ".join(fired)
                )
        fleet = verdict.get("fleet")
        if fleet:
            lines.append(
                f"  fleet: {fleet.get('n_hosts')} host(s) | "
                f"{fleet.get('completed_total')} completed | "
                f"{fleet.get('retries_total')} retries "
                f"(rate {fleet.get('retry_rate')}) | p99 spread "
                f"{fleet.get('host_p99_spread')} | dropped "
                f"{fleet.get('dropped')} | ledger "
                + (
                    "CONSISTENT"
                    if fleet.get("ledger_consistent")
                    else "TORN" if fleet.get("ledger_consistent") is False
                    else "unchecked"
                )
            )
            for label in sorted(fleet.get("hosts") or {}):
                h = (fleet.get("hosts") or {})[label]
                lines.append(
                    f"    {label} [{h.get('state')}]: "
                    f"{h.get('completed')} done / "
                    f"{h.get('proxied')} proxied | p99 "
                    f"{h.get('p99_ms')} ms | retried away "
                    f"{h.get('retried_away')}"
                )
        fa = verdict.get("fleet_attribution")
        if fa:
            # the final cross-host waterfall: router stages + network
            # + the stitched backend block, the retry-hop share and
            # the cross-hop reconciliation disposition
            stage_parts = [
                f"{stage} {b['p99_ms']:.1f}"
                for stage, b in (fa.get("stages") or {}).items()
                if b is not None and b.get("p99_ms") is not None
            ]
            share = fa.get("retry_hop_share")
            recon = fa.get("reconciliation") or {}
            lines.append(
                "  fleet trace: p99/stage ms  " + " > ".join(stage_parts)
                + (
                    f" | retry-hop share {share:.1%}"
                    if share is not None else ""
                )
                + (
                    f" | stage spread {fa.get('host_stage_spread_max')}"
                    if fa.get("host_stage_spread_max") is not None
                    else ""
                )
                + (
                    "" if recon.get("ok") in (True, None)
                    else " | CROSS-HOP RECONCILIATION BROKEN"
                )
            )
            bparts = [
                f"{stage} {b['p99_ms']:.1f}"
                for stage, b in (fa.get("backend_stages") or {}).items()
                if b is not None and b.get("p99_ms") is not None
            ]
            if bparts:
                lines.append(
                    "    backend p99/stage ms  " + " > ".join(bparts)
                )
            for p, wfs in sorted((fa.get("tail") or {}).items()):
                if not wfs:
                    continue
                wf = wfs[0]  # the slowest cross-host exemplar
                waterfall = " + ".join(
                    f"{stage} {ms:.1f}"
                    for stage, ms in (wf.get("stages") or {}).items()
                )
                lines.append(
                    f"    slowest p{p}: {wf.get('trace')} on "
                    f"{wf.get('host')} ({wf.get('attempts')} "
                    f"attempt(s)) {wf.get('total_ms')}ms = {waterfall}"
                    f" | slowest stage {wf.get('slowest_stage')}"
                )
        att = verdict.get("attribution")
        if att:
            # the final waterfall: where the p99 went, stage by stage,
            # plus the slowest request's full decomposition
            stage_parts = [
                f"{stage} {b['p99_ms']:.1f}"
                for stage, b in (att.get("stages") or {}).items()
                if b is not None and b.get("p99_ms") is not None
            ]
            share = att.get("queue_share")
            recon = att.get("reconciliation") or {}
            lines.append(
                "  trace: p99/stage ms  " + " > ".join(stage_parts)
                + (
                    f" | queue share {share:.0%}"
                    if share is not None else ""
                )
                + (
                    "" if recon.get("ok") in (True, None)
                    else " | RECONCILIATION BROKEN"
                )
            )
            for p, wfs in sorted((att.get("tail") or {}).items()):
                if not wfs:
                    continue
                wf = wfs[0]  # the slowest exemplar of this class
                waterfall = " + ".join(
                    f"{stage} {ms:.1f}"
                    for stage, ms in (wf.get("stages") or {}).items()
                )
                lines.append(
                    f"    slowest p{p}: #{wf.get('seq')} "
                    f"{wf.get('total_ms')}ms = {waterfall}"
                )
        cap = verdict.get("capacity")
        if cap:
            # the v8 capacity disposition: the three compare gates plus
            # the budget's burn episodes
            lines.append(
                f"  capacity: burn max {cap.get('burn_rate_max')} | "
                f"headroom {cap.get('headroom_rps')} rps | worst shed "
                f"ratio {cap.get('demand_shed_ratio_max')}"
            )
            budget = cap.get("slo_budget") or {}
            for ep in budget.get("episodes") or []:
                t_end = ep.get("t_end")
                lines.append(
                    f"    burn episode: {ep.get('detector')} peak "
                    f"{ep.get('peak_burn_rate')}"
                    + (
                        f" ({t_end - ep.get('t_start'):.1f}s)"
                        if t_end is not None else " (still open)"
                    )
                )
    return lines


def _perf_lines(events) -> List[str]:
    """The perf-observatory view: when a timeline carries ``perf``
    events (a ``perf`` run dir, obs/roofline.py) render the sweep
    header, one line per measured (impl, bucket) cell as it lands,
    and the roofline summary once the verdict event arrives."""
    perf = [e for e in events if e.get("kind") == "perf"]
    if not perf:
        return []
    lines: List[str] = []
    start = next((e for e in perf if e.get("phase") == "start"), None)
    verdict_ev = next(
        (e for e in reversed(perf) if e.get("phase") == "verdict"), None
    )
    if start:
        lines.append(
            f"perf: roofline sweep on {start.get('arch')} | buckets "
            f"{start.get('buckets')} x impls {start.get('impls')} | "
            f"{start.get('iters')} iters on {start.get('device_kind')}"
        )
    if verdict_ev is None:
        for e in perf:
            if e.get("phase") != "bucket":
                continue
            recon = e.get("reconciled")
            mark = (
                "reconciled" if recon
                else "RECONCILIATION BROKEN" if recon is False
                else "unreconciled"
            )
            lines.append(
                f"  {e.get('impl')} b{e.get('bucket')}: "
                f"{e.get('wall_ms')} ms/step (attributed "
                f"{e.get('attributed_ms')} ms, {mark})"
            )
        return lines
    v = verdict_ev.get("verdict") or {}
    s = v.get("summary") or {}
    lines.append(
        f"  VERDICT: best {s.get('step_ms_best')} ms/step @ b"
        f"{s.get('bucket')} | dense {s.get('step_ms_dense')} / packed "
        f"{s.get('step_ms_packed')} ms | roof efficiency "
        f"{s.get('efficiency_mean')} | attributed "
        f"{s.get('attributed_share')} | mfu {s.get('mfu_best')}"
    )
    for skip in v.get("skipped") or []:
        lines.append(
            f"  skipped {skip.get('impl')}: {skip.get('reason')}"
        )
    return lines


def _search_lines(events) -> List[str]:
    """The recipe-search view: when a timeline carries ``search``/
    ``trial`` events (a sweep dir, bdbnn_tpu/search/) render the live
    trial states and the current best; at the verdict, the final
    leaderboard summary."""
    from bdbnn_tpu.search.harness import search_digest

    digest = search_digest(events)
    start = digest["start"]
    if start is None and digest["verdict"] is None:
        return []
    lines: List[str] = []
    if start:
        lines.append(
            f"search: {start.get('trials_total')} trial(s) over "
            f"{len(start.get('families') or [])} famil"
            f"{'y' if len(start.get('families') or []) == 1 else 'ies'}"
            f" | {start.get('workers')} worker(s)"
            + (
                " | resumed sweep"
                if start.get("phase") == "resume"
                else ""
            )
        )
    verdict = digest["verdict"]
    if verdict is not None:
        winner = verdict.get("winner") or {}
        lines.append(
            f"  VERDICT: {verdict.get('completed')}/"
            f"{verdict.get('trials_total')} completed, "
            f"{verdict.get('failed')} failed | winner "
            f"{winner.get('trial')} ({winner.get('family')} @ lr "
            f"{winner.get('lr')}) best {winner.get('best_top1')}"
        )
        return lines
    # live: latest phase per trial + the running best
    for tid in sorted(digest["trial_latest"]):
        ev = digest["trial_latest"][tid]
        phase = ev.get("phase")
        mark = {
            "done": "done",
            "failed": "FAILED",
            "preempted": "preempted",
            "interrupted": "interrupted",
        }.get(phase, "running")
        extra = (
            f" best {ev.get('best_top1')}" if phase == "done" else ""
        )
        lines.append(
            f"  {tid}: {mark} ({ev.get('family')} @ lr "
            f"{ev.get('lr')}){extra}"
        )
    best = digest["best_done"]
    if best:
        lines.append(
            f"  best so far: {best.get('trial')} best_top1 "
            f"{best.get('best_top1')}"
        )
    if digest["preempted"]:
        lines.append(
            f"  !! sweep preempted (signal "
            f"{digest['preempted'].get('signum')}) — "
            f"{digest['preempted'].get('completed')} trial(s) done; "
            "resume with `search --resume`"
        )
    return lines


def render_status(
    events: List[Dict[str, Any]],
    manifest: Optional[Dict[str, Any]] = None,
) -> str:
    """The status block for one snapshot of a run's event timeline
    (``manifest`` adds the restart count when available)."""
    if not events:
        return "(no events yet)"
    start = next((e for e in events if e.get("kind") == "run_start"), None)
    intervals = [e for e in events if e.get("kind") == "train_interval"]
    evals = [e for e in events if e.get("kind") == "eval"]
    nonfinite = [e for e in events if e.get("kind") == "nonfinite"]
    end = next((e for e in events if e.get("kind") == "run_end"), None)
    memory = [e for e in events if e.get("kind") == "memory"]
    alerts = [e for e in events if e.get("kind") == "alert"]
    ckpts = [e for e in events if e.get("kind") == "checkpoint"]
    preempts = [e for e in events if e.get("kind") == "preempt"]
    restores = [e for e in events if e.get("kind") == "restore"]
    data_errors = [e for e in events if e.get("kind") == "data_error"]
    restarts = len((manifest or {}).get("restart_lineage") or [])

    lines = []
    lines += _search_lines(events)
    lines += _perf_lines(events)
    lines += _serve_lines(events)
    if start:
        lines.append(
            f"run: epochs {start.get('start_epoch', 0)}->"
            f"{start.get('epochs')} | {start.get('steps_per_epoch')} "
            f"steps/epoch | config {start.get('config_hash', '?')}"
            + (f" | restart #{restarts}" if restarts else "")
        )
    # elastic-resume lineage: a resharded restore is the one resume
    # variant worth calling out live (the run now executes on a
    # different topology than wrote its checkpoint)
    resharded = next(
        (r for r in reversed(restores) if r.get("resharded")), None
    )
    if resharded:
        tf = resharded.get("topology_from") or {}
        tt = resharded.get("topology_to") or {}
        lines.append(
            "elastic: resumed "
            f"{tf.get('processes')}p x {tf.get('devices')}d -> "
            f"{tt.get('processes')}p x {tt.get('devices')}d "
            "(checkpoint resharded onto this mesh)"
        )
    last = intervals[-1] if intervals else None
    if last:
        age = time.time() - float(last.get("t", time.time()))
        share = float(last.get("data_wait_share", 0.0) or 0.0)
        starved = " [INPUT-BOUND]" if share > INPUT_BOUND_SHARE else ""
        lines.append(
            f"train: epoch {last.get('epoch')} step {last.get('step')} | "
            f"loss {last.get('loss')} | top1 {last.get('top1')} | "
            f"{last.get('img_per_s')} img/s | data-wait "
            f"{share:.0%}{starved} | {age:.0f}s ago"
        )
    if evals:
        ev = evals[-1]
        best = max(evals, key=lambda e: float(e.get("acc1", 0.0) or 0.0))
        lines.append(
            f"eval:  epoch {ev.get('epoch')} acc1 {ev.get('acc1')} "
            f"(best {best.get('acc1')} @ epoch {best.get('epoch')})"
        )
    # flip-rate drift: mean over layers, first interval vs newest — the
    # live view of "are binarized weights settling or still churning?"
    flips_first = _mean(
        [v for v in (intervals[0].get("flip_rate") or {}).values()
         if v is not None]
    ) if intervals else None
    flips_last = _mean(
        [v for v in (last.get("flip_rate") or {}).values() if v is not None]
    ) if last else None
    if flips_first is not None and flips_last is not None:
        lines.append(
            f"flips: mean rate {flips_first:.2e} -> {flips_last:.2e}"
            + (" (settling)" if flips_last < flips_first else " (churning)")
        )
    if memory:
        peaks = [e.get("peak_bytes") for e in memory if e.get("peak_bytes")]
        if peaks:
            lines.append(f"hbm:   peak {max(peaks) / 2**30:.2f} GiB")
    # checkpoint freshness: the at-a-glance answer to "is this run
    # preemption-safe right now, and how much work would a kill cost?"
    if ckpts:
        c = ckpts[-1]
        if end is not None:
            age_txt = "final"
        else:
            age_txt = f"{time.time() - float(c.get('t', 0.0)):.0f}s ago"
        lines.append(
            f"ckpt:  last saved {age_txt} (reason {c.get('reason')}, "
            f"epoch {c.get('epoch')} step {c.get('step_in_epoch')}, "
            f"{len(ckpts)} total)"
        )
    elif start and end is None:
        lines.append("ckpt:  NONE yet — a preemption now loses everything")
    # live health: alert counts by detector + freshness of the newest
    # one, right next to the checkpoint-age readout it complements
    if alerts:
        by: Dict[str, int] = {}
        for a in alerts:
            det = str(a.get("detector", "?"))
            by[det] = by.get(det, 0) + 1
        last_alert = alerts[-1]
        if end is not None:
            age_txt = "final"
        else:
            age_txt = (
                f"{time.time() - float(last_alert.get('t', 0.0)):.0f}s ago"
            )
        lines.append(
            f"!! alerts: {len(alerts)} ("
            + ", ".join(f"{k} x{v}" for k, v in sorted(by.items()))
            + f") | last {age_txt} [{last_alert.get('severity')} "
            f"{last_alert.get('detector')}]"
        )
    if preempts:
        p = preempts[-1]
        lines.append(
            f"!! preempted (signal {p.get('signum')}"
            + (", coordinated pod-wide" if p.get("coordinated") else "")
            + f") at epoch {p.get('epoch')} step "
            f"{p.get('step_in_epoch')} — resume with --resume"
        )
    if data_errors:
        lines.append(f"!! corrupt samples substituted: {len(data_errors)}")
    if nonfinite:
        lines.append(f"!! non-finite incidents: {len(nonfinite)}")
    if end:
        lines.append(
            f"DONE: best acc1 {end.get('best_acc1')} @ epoch "
            f"{end.get('best_epoch')} in {end.get('wall_s')}s"
        )
    return "\n".join(lines)


def watch_run(
    run_dir: str,
    *,
    interval: float = 2.0,
    once: bool = False,
    out=print,
) -> int:
    """Tail ``run_dir/events.jsonl``; re-render on growth; return once
    ``run_end`` is seen (or immediately with ``once``)."""
    path = os.path.join(run_dir, EVENTS_NAME)
    last_size = -1
    while True:
        manifest = read_manifest(run_dir)
        size = os.path.getsize(path) if os.path.exists(path) else 0
        if size != last_size:
            last_size = size
            events = read_events(run_dir)
            out(render_status(events, manifest))
            # a serve-bench run ends at its verdict, a search sweep at
            # its leaderboard verdict, a perf sweep at its roofline
            # verdict, a training run at run_end — any of them
            # terminates the tail
            if once or any(
                e.get("kind") == "run_end"
                or (e.get("kind") == "serve" and e.get("phase") == "verdict")
                or (
                    e.get("kind") == "search"
                    and e.get("phase") == "verdict"
                )
                or (
                    e.get("kind") == "perf"
                    and e.get("phase") == "verdict"
                )
                for e in events
            ):
                return 0
            out("---")
        elif once:
            out(render_status(read_events(run_dir), manifest))
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0
