"""Host-side step-phase timing for the hot loop.

Under XLA's async dispatch the host loop has exactly three places it
spends wall time per step: waiting on the input pipeline (``data_wait``),
sharding + enqueueing the step (``dispatch``), and the ONE blocking
metric fetch per print interval (``drain``). Accounting those phases on
the host — plain ``perf_counter`` deltas, no device syncs added —
separates input starvation from slow compute after the fact: a starved
run shows ``data_wait`` dominating the interval; a compute-bound run
shows the wall time parked in ``drain`` (the device still executing
queued steps when the host asks for sums).

First-step compile time rides along: the first ``train_step`` call
blocks the host on trace+compile, so its host-side duration IS the
compile cost (to within one dispatch, microseconds against seconds).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

PHASES = ("data_wait", "dispatch", "drain")


class StepPhaseTimer:
    """Accumulates per-phase host seconds between interval snapshots."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = dict.fromkeys(PHASES, 0.0)
        self._t_interval = time.perf_counter()
        self.compile_s: Optional[float] = None

    def add(self, phase: str, seconds: float) -> None:
        self._acc[phase] += seconds

    def record_compile(self, seconds: float) -> None:
        """First call wins — the only step that compiles is the first.

        Called after the same duration was ``add``-ed as dispatch:
        compile is accounted separately (the ``compile`` event), so it
        is backed OUT of the dispatch accumulator and the interval wall
        — otherwise the first interval's phase shares are compile, not
        training, and a genuinely input-bound short run reads as 'not
        starved'."""
        if self.compile_s is None:
            self.compile_s = seconds
            self._acc["dispatch"] -= seconds
            self._t_interval += seconds

    def reset(self) -> None:
        """Start a fresh interval. Called at each epoch's first batch:
        the wall between epochs (validation, checkpointing) would
        otherwise leak into the first interval's denominator and dilute
        the data-wait share the starvation verdict keys on."""
        self._acc = dict.fromkeys(PHASES, 0.0)
        self._t_interval = time.perf_counter()

    def snapshot(self) -> Dict[str, float]:
        """Per-phase seconds + shares since the previous snapshot;
        resets the accumulators (per-interval semantics, matching the
        DeviceMetrics drain cadence)."""
        now = time.perf_counter()
        wall = max(now - self._t_interval, 1e-9)
        out: Dict[str, float] = {
            f"{k}_s": round(v, 6) for k, v in self._acc.items()
        }
        out["interval_s"] = round(wall, 6)
        out["data_wait_share"] = round(self._acc["data_wait"] / wall, 4)
        self._acc = dict.fromkeys(PHASES, 0.0)
        self._t_interval = now
        return out
