"""Request-path tracing: per-request lifecycle spans with tail
attribution across the serving stack.

The serving verdicts (PRs 5-9) say *that* p99 regressed — per-priority
percentiles, fairness ratios, packed-vs-dense deltas — but never
*where a request spent its time*: queue wait, batch-formation wait,
pool-dispatch backpressure, device compute and response write all fold
into one aggregate latency. This module is the measurement substrate
that decomposes it, the serving analogue of the training side's
``jax.named_scope`` attribution (obs/trace.py): cheap span stamps at
the owning sites, rolled up into per-stage histograms, tail-exemplar
waterfalls and a reconciliation identity the SLO verdict (v4) carries
in its ``attribution`` block.

Stage taxonomy (one linear timeline per request; every duration is the
gap between consecutive stamps on ONE ``time.perf_counter`` clock —
never mixed-clock arithmetic):

==============  =========================================================
``read``        request line received -> body read + parsed
                (serve/http.py; slow-client body dribble lands here)
``admit``       parse -> admission decision (serve/admission.py quota)
``queue``       post-admission -> picked out of the batcher's
                per-priority queue (serve/batching.py ``_Request``
                enqueue; includes body decode + submit overhead and,
                on the pooled path, any ``max_pending_batches``
                backpressure hold — the front-queue half of
                "queue-bound")
``coalesce``    picked -> the coalesced batch dispatches to the runner
                (the micro-batcher's deadline window)
``dispatch``    runner dispatch -> a replica worker picks the batch up
                (serve/pool.py replica-queue wait; empty/null on the
                single-engine path — no pool, no dispatch hop)
``compute``     the engine call itself — blocked device compute as the
                host observes it (serve/engine.py; cross-checked by
                ``InferenceEngine.step_stats``/``time_step``)
``respond``     results delivered -> response written (serve/http.py;
                absent on the in-process serve-bench path)
==============  =========================================================

Recording is deliberately cheap (the <2%-overhead budget): one shared
``perf_counter`` base per process, append-only per-request stage
stamps (a dict write + one clock read per boundary), and bounded
rollups — rolling per-(priority, stage) sample windows, a slowest-K
min-heap per priority (tail exemplars are ALWAYS kept; you only know a
request was slow at the end), and deterministic seeded sampling
(splitmix64 over the request sequence number) deciding which full
waterfalls are emitted as ``rtrace`` events.

Percentiles reuse the hardened None-propagating ``percentile``/``_pct``
helpers from serve/loadgen.py (imported lazily — loadgen imports the
batcher, which imports this module for the future-timing handoff), so
an empty stage window lands as ``null`` in the verdict, never a
``TypeError``.

Two clocks meet in a serving verdict and they are NOT the same number:

- **server** spans (this module) start at request receipt on the
  server's ``perf_counter`` — they cannot see connect/accept backlog.
- **client** latency (serve/loadgen.py) is charged from the SCHEDULED
  arrival (no coordinated omission) — it includes network + backlog
  wait the server never observes.

The verdict's ``attribution.clocks`` block documents both; the
reconciliation identity (per-request stage sum == server-side
end-to-end latency, within tolerance) is checked against the SERVER
clock only.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

# the canonical stage order — every consumer (verdict, /statsz, watch,
# summarize, compare) renders stages in this order
STAGES = (
    "read", "admit", "queue", "coalesce", "dispatch", "compute",
    "respond",
)

# reconciliation tolerance: stage sum within this fraction of the
# measured end-to-end latency (the acceptance gate), with an absolute
# floor below which the residual is scheduler slop (settle-callback and
# future-wakeup gaps), not misattribution
RECON_TOL_PCT = 5.0
RECON_FLOOR_MS = 0.25

_M64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """Deterministic 64-bit mix (same construction as
    data/pipeline.py's per-sample keying): the sampling decision for
    request ``seq`` is a pure function of (seed, seq) — reproducible
    across runs, no RNG state to contend on in the request path."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


# ---------------------------------------------------------------------------
# future-timing handoff: the replica pool measures the dispatch/compute
# split (replica-queue wait vs engine run) at the worker, one layer
# below the batcher that settles the per-request futures — the split
# rides the batch Future itself so no signature on the runner contract
# changes. concurrent.futures.Future is not slotted; a private
# attribute is the cheapest thread-safe channel (set before set_result,
# read in the settle callback).
# ---------------------------------------------------------------------------


def set_future_timing(
    fut: Any, dispatch_ms: float, compute_ms: float
) -> None:
    """Attach a (dispatch_ms, compute_ms) split to a batch Future —
    called by the replica worker BEFORE it resolves the future, so the
    batcher's settle callback always observes it."""
    fut._rtrace_timing = (float(dispatch_ms), float(compute_ms))


def pop_future_timing(fut: Any) -> Optional[tuple]:
    """The split attached by :func:`set_future_timing`, or None (the
    sync single-engine path, or a pool built before this module)."""
    timing = getattr(fut, "_rtrace_timing", None)
    if timing is not None:
        try:
            del fut._rtrace_timing
        except AttributeError:
            pass
    return timing


def set_future_answered_by(fut: Any, version: str) -> None:
    """Attach the artifact version that ANSWERED a future — the replica
    worker labels its batch Future before resolving it, the batcher
    relabels each per-request future at settle, and the HTTP front end
    reads it to feed the canary monitor's per-cohort latency windows
    (serve/canary.py). Same private-attribute channel as the timing
    split: thread-safe because it is written strictly before
    ``set_result`` and read strictly after the wait returns."""
    fut._rtrace_answered_by = str(version)


def pop_future_answered_by(fut: Any) -> Optional[str]:
    """The version label attached by :func:`set_future_answered_by`,
    or None (single-engine paths, pre-canary pools)."""
    version = getattr(fut, "_rtrace_answered_by", None)
    if version is not None:
        try:
            del fut._rtrace_answered_by
        except AttributeError:
            pass
    return version


class RequestTrace:
    """One request's append-only stage stamps.

    ``stamp(stage)`` charges the time since the previous stamp to
    ``stage`` and advances the cursor; ``add(stage, ms)`` records an
    externally measured duration (the pool's dispatch/compute split)
    WITHOUT advancing the cursor; ``sync()`` advances the cursor to
    now (after ``add``s, so the next ``stamp`` only charges its own
    gap). All stamps are on one ``perf_counter`` clock."""

    __slots__ = ("seq", "priority", "tenant", "t0", "_last", "stages")

    def __init__(
        self, seq: int, priority: int, tenant: Optional[str],
        t0: float,
    ):
        self.seq = seq
        self.priority = priority
        self.tenant = tenant
        self.t0 = t0
        self._last = t0
        self.stages: Dict[str, float] = {}

    def stamp(self, stage: str) -> None:
        now = time.perf_counter()
        self.stages[stage] = (
            self.stages.get(stage, 0.0) + (now - self._last) * 1000.0
        )
        self._last = now

    def add(self, stage: str, ms: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + float(ms)

    def sync(self) -> None:
        self._last = time.perf_counter()

    def waterfall(self) -> Dict[str, Any]:
        """The exemplar payload shape ``rtrace`` events and the
        verdict's tail table carry (strict-JSON-safe after jsonsafe)."""
        return {
            "seq": self.seq,
            "priority": self.priority,
            "tenant": self.tenant,
            "total_ms": round((self._last - self.t0) * 1000.0, 3),
            "stages": {
                s: round(self.stages[s], 3)
                for s in STAGES if s in self.stages
            },
        }


class RequestTracer:
    """Per-process span recorder: hands out :class:`RequestTrace`
    objects, rolls finished ones into bounded live statistics, and
    assembles the verdict's ``attribution`` block.

    - ``sample_every`` — deterministic seeded sampling: request ``seq``
      is SAMPLED when ``splitmix64(seed ^ seq) % sample_every == 0``;
      sampled waterfalls fire ``on_sample`` (the orchestrations wire it
      to an ``rtrace`` event emit). 1 = every request.
    - ``tail_k`` — slowest-K exemplars per priority, kept ALWAYS
      (independent of sampling — the tail is the point).
    - ``window`` — rolling per-(priority, stage) sample windows the
      live histograms and verdict percentiles are computed over.

    Thread-safe: ``begin``/``finish``/``abort`` run on the event-loop
    thread, batcher worker and settle callbacks; ``stats`` and
    ``attribution`` snapshot under the same lock.
    """

    def __init__(
        self,
        *,
        seed: int = 0,
        sample_every: int = 16,
        tail_k: int = 5,
        window: int = 1024,
        on_sample: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        if tail_k < 0:
            raise ValueError("tail_k must be >= 0")
        if window < 1:
            raise ValueError("window must be >= 1")
        self.seed = int(seed)
        self.sample_every = int(sample_every)
        self.tail_k = int(tail_k)
        self.window = int(window)
        self.on_sample = on_sample
        # ONE shared clock base per process: every span in every layer
        # stamps perf_counter deltas against the same timeline
        self.t_base = time.perf_counter()
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        # guarded-by: _lock: finished, aborted, sampled
        self.finished = 0
        self.aborted = 0
        self.sampled = 0
        # rolling sample windows (bounded deques — C-implemented
        # eviction keeps the request-path cost flat):
        # {priority: {stage: deque[ms]}} plus the end-to-end window
        self._stage_win: Dict[int, Dict[str, Any]] = {}  # guarded-by: _lock
        self._e2e_win: Dict[int, Any] = {}  # guarded-by: _lock
        # slowest-K min-heap per priority: (total_ms, seq, trace) —
        # the trace object itself; waterfalls render at REPORT time,
        # never in the request path
        self._tail: Dict[int, List[tuple]] = {}  # guarded-by: _lock
        # reconciliation accumulators over EVERY finished request
        # guarded-by: _lock: _recon_n, _recon_sum_err_ms,
        # guarded-by: _lock: _recon_sum_err_pct, _recon_max_err_pct
        self._recon_n = 0
        self._recon_sum_err_ms = 0.0
        self._recon_sum_err_pct = 0.0
        self._recon_max_err_pct = 0.0

    # -- request path --------------------------------------------------

    def begin(
        self,
        priority: int = 0,
        tenant: Optional[str] = None,
        t_start: Optional[float] = None,
    ) -> RequestTrace:
        """A new trace; ``t_start`` (a perf_counter reading — e.g. the
        moment the request line arrived) backdates the clock so the
        first stamp charges the read that already happened."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        return RequestTrace(
            seq, int(priority), tenant,
            time.perf_counter() if t_start is None else float(t_start),
        )

    def _keep(self, seq: int) -> bool:
        if self.sample_every <= 1:
            return True
        return (
            _splitmix64(self.seed ^ seq) % self.sample_every == 0
        )

    def finish(self, trace: RequestTrace) -> None:
        """Roll one completed request into the live statistics. The
        trace's cursor must already cover its last stage (the caller
        stamps ``respond`` — or the bench done-callback lands right
        after settle). Kept lean on purpose (the <2% budget): deque
        appends, one heap push, no rendering — waterfalls materialize
        only for sampled exemplars and at report time."""
        now = trace._last
        total_ms = (now - trace.t0) * 1000.0
        stage_sum = sum(trace.stages.values())
        err_ms = abs(total_ms - stage_sum)
        err_pct = (
            err_ms / total_ms * 100.0 if total_ms > 0 else 0.0
        )
        sampled = self._keep(trace.seq)
        with self._lock:
            self.finished += 1
            p = trace.priority
            wins = self._stage_win.get(p)
            if wins is None:
                wins = self._stage_win[p] = {}
            for stage, ms in trace.stages.items():
                win = wins.get(stage)
                if win is None:
                    win = wins[stage] = deque(maxlen=self.window)
                win.append(ms)
            e2e = self._e2e_win.get(p)
            if e2e is None:
                e2e = self._e2e_win[p] = deque(maxlen=self.window)
            e2e.append(total_ms)
            self._recon_n += 1
            self._recon_sum_err_ms += err_ms
            self._recon_sum_err_pct += err_pct
            if err_pct > self._recon_max_err_pct:
                self._recon_max_err_pct = err_pct
            if self.tail_k > 0:
                tail = self._tail.get(p)
                if tail is None:
                    tail = self._tail[p] = []
                heapq.heappush(tail, (total_ms, trace.seq, trace))
                if len(tail) > self.tail_k:
                    heapq.heappop(tail)
            if sampled:
                self.sampled += 1
        if sampled and self.on_sample is not None:
            try:
                self.on_sample(trace.waterfall())
            except Exception:
                pass  # telemetry must never break the request path

    def abort(self, trace: Optional[RequestTrace]) -> None:
        """A request that ended without a served response (shed,
        rejected, failed): counted, never rolled into the stage
        statistics — a 503 written in 50us must not read as a fast
        serve."""
        if trace is None:
            return
        with self._lock:
            self.aborted += 1

    def bind(
        self,
        submit_fn: Callable[..., Any],
        *,
        priority: int = 0,
    ) -> Callable[[Any], Any]:
        """Wrap a ``submit(payload, trace=...) -> Future`` callable so
        every submission carries a trace finished on the future's
        resolution — the in-process serve-bench wiring (no socket, so
        no read/admit/respond stages; queue -> coalesce -> dispatch ->
        compute is the whole waterfall)."""

        def submit(payload):
            tr = self.begin(priority)
            try:
                fut = submit_fn(payload, trace=tr)
            except Exception:
                self.abort(tr)
                raise

            def _done(f, tr=tr):
                if not f.cancelled() and f.exception() is None:
                    self.finish(tr)
                else:
                    self.abort(tr)

            fut.add_done_callback(_done)
            return fut

        return submit

    # -- reporting -----------------------------------------------------

    @staticmethod
    def _pcts(win: List[float]) -> Optional[Dict[str, Any]]:
        # lazy: loadgen imports the batcher which imports this module —
        # by any call time the cycle is long resolved
        from bdbnn_tpu.serve.loadgen import _pct

        if not win:
            return None
        s = sorted(win)
        return {
            "p50_ms": _pct(s, 50.0),
            "p99_ms": _pct(s, 99.0),
            "mean_ms": round(sum(s) / len(s), 3),
            "n": len(s),
        }

    def _merged_stage_windows(self) -> Dict[str, List[float]]:  # requires-lock: _lock
        merged: Dict[str, List[float]] = {}
        for wins in self._stage_win.values():
            for stage, win in wins.items():
                merged.setdefault(stage, []).extend(win)
        return merged

    @staticmethod
    def _queue_share(
        stage_blocks: Dict[str, Optional[Dict[str, Any]]],
    ) -> Optional[float]:
        """Queue-boundedness: (queue + dispatch) mean over the summed
        stage means — the share `compare` judges so a p99 that moved
        from device-bound to queue-bound regresses even when the
        aggregate p99 is flat."""
        means = {
            s: b["mean_ms"] for s, b in stage_blocks.items()
            if b is not None
        }
        total = sum(means.values())
        if total <= 0:
            return None
        waiting = means.get("queue", 0.0) + means.get("dispatch", 0.0)
        return round(waiting / total, 4)

    def stats(self) -> Dict[str, Any]:
        """The live snapshot ``/statsz`` and the periodic ``rtrace``
        stats events carry: per-stage p50/p99 over the rolling windows
        (merged across priorities — compact on purpose), end-to-end
        p99 per priority, counts."""
        from bdbnn_tpu.serve.loadgen import _pct

        with self._lock:
            # _merged_stage_windows already builds fresh lists — no
            # second copy under the lock the request path contends on
            merged = self._merged_stage_windows()
            e2e = {p: list(w) for p, w in self._e2e_win.items()}
            finished, aborted, sampled = (
                self.finished, self.aborted, self.sampled
            )
        stage_blocks = {
            s: self._pcts(merged.get(s)) for s in STAGES
        }
        return {
            "requests": finished,
            "aborted": aborted,
            "sampled": sampled,
            "stage_p99_ms": {
                s: (b or {}).get("p99_ms") for s, b in stage_blocks.items()
            },
            "e2e_p99_ms_by_priority": {
                str(p): _pct(sorted(w), 99.0)
                for p, w in sorted(e2e.items())
            },
            "queue_share": self._queue_share(stage_blocks),
        }

    def attribution(
        self, *, device: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """The v4 verdict's ``attribution`` block: per-priority
        p50/p99 decomposed by stage, the reconciliation identity
        (stage sum vs server-side end-to-end, over every finished
        request), the slowest-K tail-exemplar table per priority, and
        the clock documentation. ``device`` (optional) attaches the
        engine's own blocked-step statistics as the compute-stage
        cross-check."""
        with self._lock:
            per_p_windows = {
                p: {s: list(w) for s, w in wins.items()}
                for p, wins in self._stage_win.items()
            }
            e2e = {p: list(w) for p, w in self._e2e_win.items()}
            tails = {
                p: [
                    tr.waterfall()
                    for _, _, tr in sorted(
                        t, key=lambda x: (x[0], x[1]), reverse=True
                    )
                ]
                for p, t in self._tail.items()
            }
            merged = self._merged_stage_windows()
            finished, aborted, sampled = (
                self.finished, self.aborted, self.sampled
            )
            recon_n = self._recon_n
            mean_err_ms = (
                self._recon_sum_err_ms / recon_n if recon_n else None
            )
            mean_err_pct = (
                self._recon_sum_err_pct / recon_n if recon_n else None
            )
            max_err_pct = (
                self._recon_max_err_pct if recon_n else None
            )
        stage_blocks = {s: self._pcts(merged.get(s)) for s in STAGES}
        per_priority: Dict[str, Any] = {}
        for p in sorted(set(per_p_windows) | set(e2e)):
            blocks = {
                s: self._pcts(per_p_windows.get(p, {}).get(s))
                for s in STAGES
            }
            per_priority[str(p)] = {
                "e2e": self._pcts(e2e.get(p, [])),
                "stages": blocks,
                "queue_share": self._queue_share(blocks),
            }
        ok = None
        if recon_n:
            ok = bool(
                mean_err_pct <= RECON_TOL_PCT
                or mean_err_ms <= RECON_FLOOR_MS
            )
        return {
            # both clocks a serving verdict mixes, named explicitly so
            # nobody subtracts a client latency from a server span:
            "clocks": {
                "server": (
                    "time.perf_counter, one shared base per process; "
                    "spans stamped from request receipt — cannot see "
                    "connect/accept backlog"
                ),
                "client": (
                    "time.perf_counter charged from the SCHEDULED "
                    "arrival (serve/loadgen.py, no coordinated "
                    "omission) — includes network + backlog wait the "
                    "server never observes"
                ),
            },
            "sample_every": self.sample_every,
            "tail_k": self.tail_k,
            "window": self.window,
            "requests": finished,
            "aborted": aborted,
            "sampled": sampled,
            "stages": stage_blocks,
            "queue_share": self._queue_share(stage_blocks),
            "per_priority": per_priority,
            "reconciliation": {
                "requests": recon_n,
                "mean_abs_err_ms": (
                    round(mean_err_ms, 4)
                    if mean_err_ms is not None else None
                ),
                "mean_abs_err_pct": (
                    round(mean_err_pct, 3)
                    if mean_err_pct is not None else None
                ),
                "max_abs_err_pct": (
                    round(max_err_pct, 3)
                    if max_err_pct is not None else None
                ),
                "tolerance_pct": RECON_TOL_PCT,
                "floor_ms": RECON_FLOOR_MS,
                "ok": ok,
            },
            "tail": {str(p): t for p, t in sorted(tails.items())},
            "device": device,
        }


__all__ = [
    "RECON_FLOOR_MS",
    "RECON_TOL_PCT",
    "STAGES",
    "RequestTrace",
    "RequestTracer",
    "pop_future_answered_by",
    "pop_future_timing",
    "set_future_answered_by",
    "set_future_timing",
]
