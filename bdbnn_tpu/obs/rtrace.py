"""Request-path tracing: per-request lifecycle spans with tail
attribution across the serving stack.

The serving verdicts (PRs 5-9) say *that* p99 regressed — per-priority
percentiles, fairness ratios, packed-vs-dense deltas — but never
*where a request spent its time*: queue wait, batch-formation wait,
pool-dispatch backpressure, device compute and response write all fold
into one aggregate latency. This module is the measurement substrate
that decomposes it, the serving analogue of the training side's
``jax.named_scope`` attribution (obs/trace.py): cheap span stamps at
the owning sites, rolled up into per-stage histograms, tail-exemplar
waterfalls and a reconciliation identity the SLO verdict (v4) carries
in its ``attribution`` block.

Stage taxonomy (one linear timeline per request; every duration is the
gap between consecutive stamps on ONE ``time.perf_counter`` clock —
never mixed-clock arithmetic):

==============  =========================================================
``read``        request line received -> body read + parsed
                (serve/http.py; slow-client body dribble lands here)
``admit``       parse -> admission decision (serve/admission.py quota)
``queue``       post-admission -> picked out of the batcher's
                per-priority queue (serve/batching.py ``_Request``
                enqueue; includes body decode + submit overhead and,
                on the pooled path, any ``max_pending_batches``
                backpressure hold — the front-queue half of
                "queue-bound")
``coalesce``    picked -> the coalesced batch dispatches to the runner
                (the micro-batcher's deadline window)
``dispatch``    runner dispatch -> a replica worker picks the batch up
                (serve/pool.py replica-queue wait; empty/null on the
                single-engine path — no pool, no dispatch hop)
``compute``     the engine call itself — blocked device compute as the
                host observes it (serve/engine.py; cross-checked by
                ``InferenceEngine.step_stats``/``time_step``)
``respond``     results delivered -> response written (serve/http.py;
                absent on the in-process serve-bench path)
==============  =========================================================

Recording is deliberately cheap (the <2%-overhead budget): one shared
``perf_counter`` base per process, append-only per-request stage
stamps (a dict write + one clock read per boundary), and bounded
rollups — rolling per-(priority, stage) sample windows, a slowest-K
min-heap per priority (tail exemplars are ALWAYS kept; you only know a
request was slow at the end), and deterministic seeded sampling
(splitmix64 over the request sequence number) deciding which full
waterfalls are emitted as ``rtrace`` events.

Percentiles reuse the hardened None-propagating ``percentile``/``_pct``
helpers from serve/loadgen.py (imported lazily — loadgen imports the
batcher, which imports this module for the future-timing handoff), so
an empty stage window lands as ``null`` in the verdict, never a
``TypeError``.

Two clocks meet in a serving verdict and they are NOT the same number:

- **server** spans (this module) start at request receipt on the
  server's ``perf_counter`` — they cannot see connect/accept backlog.
- **client** latency (serve/loadgen.py) is charged from the SCHEDULED
  arrival (no coordinated omission) — it includes network + backlog
  wait the server never observes.

The verdict's ``attribution.clocks`` block documents both; the
reconciliation identity (per-request stage sum == server-side
end-to-end latency, within tolerance) is checked against the SERVER
clock only.

Fleet tracing (PR 16) extends the same substrate across the host
boundary: the FleetRouter (serve/fleet.py) mints a trace id per
proxied request, stamps its OWN stages (``probe_wait`` -> ``pick`` ->
``connect`` -> per-attempt ``retry_hop``, with each backoff sleep
charged to the attempt that incurred it), and propagates a compact
context in the ``x-rtrace`` request header. The backend front end
adopts the context (its local waterfall carries the fleet trace id)
and returns its stage decomposition in the ``x-rtrace-stages``
response header, which the router stitches into one cross-host
waterfall. Two-clock discipline holds across hosts exactly as it does
between client and server: the router NEVER subtracts a backend
timestamp from its own clock — the ``network`` stage is the
router-measured exchange wall MINUS the backend's self-reported span,
a subtraction of two durations, never of two clocks.
"""

from __future__ import annotations

import heapq
import math
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

# the canonical stage order — every consumer (verdict, /statsz, watch,
# summarize, compare) renders stages in this order
STAGES = (
    "read", "admit", "queue", "coalesce", "dispatch", "compute",
    "respond",
)

# the router-side stage order of a cross-host (fleet) waterfall — the
# backend's own STAGES ride along as a nested block, never flattened
# into this namespace
FLEET_STAGES = ("probe_wait", "pick", "connect", "retry_hop", "network")

# trace-context wire format: one request header, one response header,
# both ``k=v`` pairs joined by ``;`` — parseable without a JSON
# dependency in the byte-level proxy path, and bounded so a hostile
# client cannot make the parser do unbounded work
TRACE_HEADER = "x-rtrace"
STAGE_HEADER = "x-rtrace-stages"
TRACE_CTX_MAX_LEN = 256
STAGE_HEADER_MAX_LEN = 1024

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{16}$")
_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# reconciliation tolerance: stage sum within this fraction of the
# measured end-to-end latency (the acceptance gate), with an absolute
# floor below which the residual is scheduler slop (settle-callback and
# future-wakeup gaps), not misattribution
RECON_TOL_PCT = 5.0
RECON_FLOOR_MS = 0.25

_M64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """Deterministic 64-bit mix (same construction as
    data/pipeline.py's per-sample keying): the sampling decision for
    request ``seq`` is a pure function of (seed, seq) — reproducible
    across runs, no RNG state to contend on in the request path."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def mint_trace_id(seed: int, seq: int) -> str:
    """A deterministic 16-hex trace id for proxied request ``seq`` —
    a pure function of (seed, seq), same splitmix64 construction as
    the sampling decision, so a fleet run's ids are reproducible."""
    return "%016x" % _splitmix64(_splitmix64(int(seed)) ^ int(seq))


def encode_trace_context(
    trace_id: str, seq: int, priority: int,
    tenant: Optional[str] = None,
) -> str:
    """The ``x-rtrace`` request-header value the router sends with a
    proxied request. Tenants that are not header-token-safe are
    simply omitted — the context is correlation metadata, never the
    routing source of truth (x-priority/x-tenant stay authoritative)."""
    out = f"v=1;id={trace_id};seq={int(seq)};p={int(priority)}"
    if tenant is not None and _TENANT_RE.match(str(tenant)):
        out += f";tn={tenant}"
    return out


def parse_trace_context(value: Any) -> Optional[Dict[str, Any]]:
    """Parse an inbound ``x-rtrace`` header; ``None`` on ANY
    malformation (wrong version, bad id, oversized, junk) — a garbage
    header from a non-fleet client must degrade to a fresh local
    trace, never to a 500."""
    if not isinstance(value, str) or not value:
        return None
    if len(value) > TRACE_CTX_MAX_LEN:
        return None
    fields: Dict[str, str] = {}
    for part in value.split(";"):
        key, sep, val = part.partition("=")
        if not sep or not key or key in fields:
            return None
        fields[key] = val
    if fields.get("v") != "1":
        return None
    trace_id = fields.get("id", "")
    if not _TRACE_ID_RE.match(trace_id):
        return None
    try:
        seq = int(fields.get("seq", ""))
        priority = int(fields.get("p", ""))
    except ValueError:
        return None
    if seq < 0 or not 0 <= priority < 64:
        return None
    tenant = fields.get("tn")
    if tenant is not None and not _TENANT_RE.match(tenant):
        return None
    return {
        "id": trace_id, "seq": seq, "priority": priority,
        "tenant": tenant,
    }


def encode_stage_header(
    trace_id: str, total_ms: float, stages: Dict[str, float]
) -> str:
    """The ``x-rtrace-stages`` response-header value a backend returns
    on a traced request: its self-reported span (``total``) and stage
    decomposition, all DURATIONS in ms — the only numbers that may
    legally cross the clock boundary back to the router."""
    parts = [f"v=1;id={trace_id};total={max(float(total_ms), 0.0):.3f}"]
    for stage in STAGES:
        ms = stages.get(stage)
        if ms is not None and math.isfinite(ms) and ms >= 0:
            parts.append(f"{stage}={float(ms):.3f}")
    return ";".join(parts)


def parse_stage_header(value: Any) -> Optional[Dict[str, Any]]:
    """Parse a backend's ``x-rtrace-stages`` header into
    ``{"id", "total_ms", "stages"}``; ``None`` on any malformation
    (the router then falls back to charging the whole exchange to
    ``network`` and counts the request unstitched)."""
    if not isinstance(value, str) or not value:
        return None
    if len(value) > STAGE_HEADER_MAX_LEN:
        return None
    fields: Dict[str, str] = {}
    for part in value.split(";"):
        key, sep, val = part.partition("=")
        if not sep or not key or key in fields:
            return None
        fields[key] = val
    if fields.get("v") != "1":
        return None
    trace_id = fields.get("id", "")
    if not _TRACE_ID_RE.match(trace_id):
        return None
    try:
        total_ms = float(fields.get("total", ""))
    except ValueError:
        return None
    if not math.isfinite(total_ms) or total_ms < 0:
        return None
    # the key set is CLOSED: v, id, total and the stage taxonomy —
    # an unknown key means a peer speaking some other dialect, and
    # half-understanding it is worse than the unstitched fallback
    if any(
        k not in ("v", "id", "total") and k not in STAGES
        for k in fields
    ):
        return None
    stages: Dict[str, float] = {}
    for stage in STAGES:
        raw = fields.get(stage)
        if raw is None:
            continue
        try:
            ms = float(raw)
        except ValueError:
            return None
        if not math.isfinite(ms) or ms < 0:
            return None
        stages[stage] = ms
    return {"id": trace_id, "total_ms": total_ms, "stages": stages}


# ---------------------------------------------------------------------------
# future-timing handoff: the replica pool measures the dispatch/compute
# split (replica-queue wait vs engine run) at the worker, one layer
# below the batcher that settles the per-request futures — the split
# rides the batch Future itself so no signature on the runner contract
# changes. concurrent.futures.Future is not slotted; a private
# attribute is the cheapest thread-safe channel (set before set_result,
# read in the settle callback).
# ---------------------------------------------------------------------------


def set_future_timing(
    fut: Any, dispatch_ms: float, compute_ms: float
) -> None:
    """Attach a (dispatch_ms, compute_ms) split to a batch Future —
    called by the replica worker BEFORE it resolves the future, so the
    batcher's settle callback always observes it."""
    fut._rtrace_timing = (float(dispatch_ms), float(compute_ms))


def pop_future_timing(fut: Any) -> Optional[tuple]:
    """The split attached by :func:`set_future_timing`, or None (the
    sync single-engine path, or a pool built before this module)."""
    timing = getattr(fut, "_rtrace_timing", None)
    if timing is not None:
        try:
            del fut._rtrace_timing
        except AttributeError:
            pass
    return timing


def set_future_answered_by(fut: Any, version: str) -> None:
    """Attach the artifact version that ANSWERED a future — the replica
    worker labels its batch Future before resolving it, the batcher
    relabels each per-request future at settle, and the HTTP front end
    reads it to feed the canary monitor's per-cohort latency windows
    (serve/canary.py). Same private-attribute channel as the timing
    split: thread-safe because it is written strictly before
    ``set_result`` and read strictly after the wait returns."""
    fut._rtrace_answered_by = str(version)


def pop_future_answered_by(fut: Any) -> Optional[str]:
    """The version label attached by :func:`set_future_answered_by`,
    or None (single-engine paths, pre-canary pools)."""
    version = getattr(fut, "_rtrace_answered_by", None)
    if version is not None:
        try:
            del fut._rtrace_answered_by
        except AttributeError:
            pass
    return version


class RequestTrace:
    """One request's append-only stage stamps.

    ``stamp(stage)`` charges the time since the previous stamp to
    ``stage`` and advances the cursor; ``add(stage, ms)`` records an
    externally measured duration (the pool's dispatch/compute split)
    WITHOUT advancing the cursor; ``sync()`` advances the cursor to
    now (after ``add``s, so the next ``stamp`` only charges its own
    gap). All stamps are on one ``perf_counter`` clock."""

    __slots__ = (
        "seq", "priority", "tenant", "t0", "_last", "stages", "ctx",
    )

    def __init__(
        self, seq: int, priority: int, tenant: Optional[str],
        t0: float,
    ):
        self.seq = seq
        self.priority = priority
        self.tenant = tenant
        self.t0 = t0
        self._last = t0
        self.stages: Dict[str, float] = {}
        # adopted fleet trace context (parse_trace_context result) —
        # set by the HTTP front end when a well-formed x-rtrace header
        # arrives; None for direct (non-fleet) clients
        self.ctx: Optional[Dict[str, Any]] = None

    def stamp(self, stage: str) -> None:
        now = time.perf_counter()
        self.stages[stage] = (
            self.stages.get(stage, 0.0) + (now - self._last) * 1000.0
        )
        self._last = now

    def add(self, stage: str, ms: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + float(ms)

    def sync(self, at: Optional[float] = None) -> None:
        """Advance the stamp cursor without charging a stage; ``at``
        pins the cursor to a wall already measured by the caller so
        span bookkeeping and the reconciliation total read the SAME
        instant (work done after ``at`` — response parsing, stitch
        arithmetic — is charged to nobody on purpose)."""
        self._last = time.perf_counter() if at is None else float(at)

    def waterfall(self) -> Dict[str, Any]:
        """The exemplar payload shape ``rtrace`` events and the
        verdict's tail table carry (strict-JSON-safe after jsonsafe)."""
        out = {
            "seq": self.seq,
            "priority": self.priority,
            "tenant": self.tenant,
            "total_ms": round((self._last - self.t0) * 1000.0, 3),
            "stages": {
                s: round(self.stages[s], 3)
                for s in STAGES if s in self.stages
            },
        }
        if self.ctx is not None:
            out["trace"] = self.ctx["id"]
        return out


class RequestTracer:
    """Per-process span recorder: hands out :class:`RequestTrace`
    objects, rolls finished ones into bounded live statistics, and
    assembles the verdict's ``attribution`` block.

    - ``sample_every`` — deterministic seeded sampling: request ``seq``
      is SAMPLED when ``splitmix64(seed ^ seq) % sample_every == 0``;
      sampled waterfalls fire ``on_sample`` (the orchestrations wire it
      to an ``rtrace`` event emit). 1 = every request.
    - ``tail_k`` — slowest-K exemplars per priority, kept ALWAYS
      (independent of sampling — the tail is the point).
    - ``window`` — rolling per-(priority, stage) sample windows the
      live histograms and verdict percentiles are computed over.

    Thread-safe: ``begin``/``finish``/``abort`` run on the event-loop
    thread, batcher worker and settle callbacks; ``stats`` and
    ``attribution`` snapshot under the same lock.
    """

    def __init__(
        self,
        *,
        seed: int = 0,
        sample_every: int = 16,
        tail_k: int = 5,
        window: int = 1024,
        on_sample: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        if tail_k < 0:
            raise ValueError("tail_k must be >= 0")
        if window < 1:
            raise ValueError("window must be >= 1")
        self.seed = int(seed)
        self.sample_every = int(sample_every)
        self.tail_k = int(tail_k)
        self.window = int(window)
        self.on_sample = on_sample
        # ONE shared clock base per process: every span in every layer
        # stamps perf_counter deltas against the same timeline
        self.t_base = time.perf_counter()
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        # guarded-by: _lock: finished, aborted, sampled
        self.finished = 0
        self.aborted = 0
        self.sampled = 0
        # rolling sample windows (bounded deques — C-implemented
        # eviction keeps the request-path cost flat):
        # {priority: {stage: deque[ms]}} plus the end-to-end window
        self._stage_win: Dict[int, Dict[str, Any]] = {}  # guarded-by: _lock
        self._e2e_win: Dict[int, Any] = {}  # guarded-by: _lock
        # slowest-K min-heap per priority: (total_ms, seq, trace) —
        # the trace object itself; waterfalls render at REPORT time,
        # never in the request path
        self._tail: Dict[int, List[tuple]] = {}  # guarded-by: _lock
        # reconciliation accumulators over EVERY finished request
        # guarded-by: _lock: _recon_n, _recon_sum_err_ms,
        # guarded-by: _lock: _recon_sum_err_pct, _recon_max_err_pct
        self._recon_n = 0
        self._recon_sum_err_ms = 0.0
        self._recon_sum_err_pct = 0.0
        self._recon_max_err_pct = 0.0

    # -- request path --------------------------------------------------

    def begin(
        self,
        priority: int = 0,
        tenant: Optional[str] = None,
        t_start: Optional[float] = None,
    ) -> RequestTrace:
        """A new trace; ``t_start`` (a perf_counter reading — e.g. the
        moment the request line arrived) backdates the clock so the
        first stamp charges the read that already happened."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        return RequestTrace(
            seq, int(priority), tenant,
            time.perf_counter() if t_start is None else float(t_start),
        )

    def _keep(self, seq: int) -> bool:
        if self.sample_every <= 1:
            return True
        return (
            _splitmix64(self.seed ^ seq) % self.sample_every == 0
        )

    def finish(self, trace: RequestTrace) -> None:
        """Roll one completed request into the live statistics. The
        trace's cursor must already cover its last stage (the caller
        stamps ``respond`` — or the bench done-callback lands right
        after settle). Kept lean on purpose (the <2% budget): deque
        appends, one heap push, no rendering — waterfalls materialize
        only for sampled exemplars and at report time."""
        now = trace._last
        total_ms = (now - trace.t0) * 1000.0
        stage_sum = sum(trace.stages.values())
        err_ms = abs(total_ms - stage_sum)
        err_pct = (
            err_ms / total_ms * 100.0 if total_ms > 0 else 0.0
        )
        sampled = self._keep(trace.seq)
        with self._lock:
            self.finished += 1
            p = trace.priority
            wins = self._stage_win.get(p)
            if wins is None:
                wins = self._stage_win[p] = {}
            for stage, ms in trace.stages.items():
                win = wins.get(stage)
                if win is None:
                    win = wins[stage] = deque(maxlen=self.window)
                win.append(ms)
            e2e = self._e2e_win.get(p)
            if e2e is None:
                e2e = self._e2e_win[p] = deque(maxlen=self.window)
            e2e.append(total_ms)
            self._recon_n += 1
            self._recon_sum_err_ms += err_ms
            self._recon_sum_err_pct += err_pct
            if err_pct > self._recon_max_err_pct:
                self._recon_max_err_pct = err_pct
            if self.tail_k > 0:
                tail = self._tail.get(p)
                if tail is None:
                    tail = self._tail[p] = []
                heapq.heappush(tail, (total_ms, trace.seq, trace))
                if len(tail) > self.tail_k:
                    heapq.heappop(tail)
            if sampled:
                self.sampled += 1
        if sampled and self.on_sample is not None:
            try:
                self.on_sample(trace.waterfall())
            except Exception:
                pass  # telemetry must never break the request path

    def abort(self, trace: Optional[RequestTrace]) -> None:
        """A request that ended without a served response (shed,
        rejected, failed): counted, never rolled into the stage
        statistics — a 503 written in 50us must not read as a fast
        serve."""
        if trace is None:
            return
        with self._lock:
            self.aborted += 1

    def bind(
        self,
        submit_fn: Callable[..., Any],
        *,
        priority: int = 0,
    ) -> Callable[[Any], Any]:
        """Wrap a ``submit(payload, trace=...) -> Future`` callable so
        every submission carries a trace finished on the future's
        resolution — the in-process serve-bench wiring (no socket, so
        no read/admit/respond stages; queue -> coalesce -> dispatch ->
        compute is the whole waterfall)."""

        def submit(payload):
            tr = self.begin(priority)
            try:
                fut = submit_fn(payload, trace=tr)
            except Exception:
                self.abort(tr)
                raise

            def _done(f, tr=tr):
                if not f.cancelled() and f.exception() is None:
                    self.finish(tr)
                else:
                    self.abort(tr)

            fut.add_done_callback(_done)
            return fut

        return submit

    # -- reporting -----------------------------------------------------

    @staticmethod
    def _pcts(win: List[float]) -> Optional[Dict[str, Any]]:
        # lazy: loadgen imports the batcher which imports this module —
        # by any call time the cycle is long resolved
        from bdbnn_tpu.serve.loadgen import _pct

        if not win:
            return None
        s = sorted(win)
        return {
            "p50_ms": _pct(s, 50.0),
            "p99_ms": _pct(s, 99.0),
            "mean_ms": round(sum(s) / len(s), 3),
            "n": len(s),
        }

    def _merged_stage_windows(self) -> Dict[str, List[float]]:  # requires-lock: _lock
        merged: Dict[str, List[float]] = {}
        for wins in self._stage_win.values():
            for stage, win in wins.items():
                merged.setdefault(stage, []).extend(win)
        return merged

    @staticmethod
    def _queue_share(
        stage_blocks: Dict[str, Optional[Dict[str, Any]]],
    ) -> Optional[float]:
        """Queue-boundedness: (queue + dispatch) mean over the summed
        stage means — the share `compare` judges so a p99 that moved
        from device-bound to queue-bound regresses even when the
        aggregate p99 is flat."""
        means = {
            s: b["mean_ms"] for s, b in stage_blocks.items()
            if b is not None
        }
        total = sum(means.values())
        if total <= 0:
            return None
        waiting = means.get("queue", 0.0) + means.get("dispatch", 0.0)
        return round(waiting / total, 4)

    def stats(self) -> Dict[str, Any]:
        """The live snapshot ``/statsz`` and the periodic ``rtrace``
        stats events carry: per-stage p50/p99 over the rolling windows
        (merged across priorities — compact on purpose), end-to-end
        p99 per priority, counts."""
        from bdbnn_tpu.serve.loadgen import _pct

        with self._lock:
            # _merged_stage_windows already builds fresh lists — no
            # second copy under the lock the request path contends on
            merged = self._merged_stage_windows()
            e2e = {p: list(w) for p, w in self._e2e_win.items()}
            finished, aborted, sampled = (
                self.finished, self.aborted, self.sampled
            )
        stage_blocks = {
            s: self._pcts(merged.get(s)) for s in STAGES
        }
        return {
            "requests": finished,
            "aborted": aborted,
            "sampled": sampled,
            "stage_p99_ms": {
                s: (b or {}).get("p99_ms") for s, b in stage_blocks.items()
            },
            "e2e_p99_ms_by_priority": {
                str(p): _pct(sorted(w), 99.0)
                for p, w in sorted(e2e.items())
            },
            "queue_share": self._queue_share(stage_blocks),
        }

    def attribution(
        self, *, device: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """The v4 verdict's ``attribution`` block: per-priority
        p50/p99 decomposed by stage, the reconciliation identity
        (stage sum vs server-side end-to-end, over every finished
        request), the slowest-K tail-exemplar table per priority, and
        the clock documentation. ``device`` (optional) attaches the
        engine's own blocked-step statistics as the compute-stage
        cross-check."""
        with self._lock:
            per_p_windows = {
                p: {s: list(w) for s, w in wins.items()}
                for p, wins in self._stage_win.items()
            }
            e2e = {p: list(w) for p, w in self._e2e_win.items()}
            tails = {
                p: [
                    tr.waterfall()
                    for _, _, tr in sorted(
                        t, key=lambda x: (x[0], x[1]), reverse=True
                    )
                ]
                for p, t in self._tail.items()
            }
            merged = self._merged_stage_windows()
            finished, aborted, sampled = (
                self.finished, self.aborted, self.sampled
            )
            recon_n = self._recon_n
            mean_err_ms = (
                self._recon_sum_err_ms / recon_n if recon_n else None
            )
            mean_err_pct = (
                self._recon_sum_err_pct / recon_n if recon_n else None
            )
            max_err_pct = (
                self._recon_max_err_pct if recon_n else None
            )
        stage_blocks = {s: self._pcts(merged.get(s)) for s in STAGES}
        per_priority: Dict[str, Any] = {}
        for p in sorted(set(per_p_windows) | set(e2e)):
            blocks = {
                s: self._pcts(per_p_windows.get(p, {}).get(s))
                for s in STAGES
            }
            per_priority[str(p)] = {
                "e2e": self._pcts(e2e.get(p, [])),
                "stages": blocks,
                "queue_share": self._queue_share(blocks),
            }
        ok = None
        if recon_n:
            ok = bool(
                mean_err_pct <= RECON_TOL_PCT
                or mean_err_ms <= RECON_FLOOR_MS
            )
        return {
            # both clocks a serving verdict mixes, named explicitly so
            # nobody subtracts a client latency from a server span:
            "clocks": {
                "server": (
                    "time.perf_counter, one shared base per process; "
                    "spans stamped from request receipt — cannot see "
                    "connect/accept backlog"
                ),
                "client": (
                    "time.perf_counter charged from the SCHEDULED "
                    "arrival (serve/loadgen.py, no coordinated "
                    "omission) — includes network + backlog wait the "
                    "server never observes"
                ),
            },
            "sample_every": self.sample_every,
            "tail_k": self.tail_k,
            "window": self.window,
            "requests": finished,
            "aborted": aborted,
            "sampled": sampled,
            "stages": stage_blocks,
            "queue_share": self._queue_share(stage_blocks),
            "per_priority": per_priority,
            "reconciliation": {
                "requests": recon_n,
                "mean_abs_err_ms": (
                    round(mean_err_ms, 4)
                    if mean_err_ms is not None else None
                ),
                "mean_abs_err_pct": (
                    round(mean_err_pct, 3)
                    if mean_err_pct is not None else None
                ),
                "max_abs_err_pct": (
                    round(max_err_pct, 3)
                    if max_err_pct is not None else None
                ),
                "tolerance_pct": RECON_TOL_PCT,
                "floor_ms": RECON_FLOOR_MS,
                "ok": ok,
            },
            "tail": {str(p): t for p, t in sorted(tails.items())},
            "device": device,
        }


class FleetTrace(RequestTrace):
    """One proxied request's cross-host waterfall: the router's own
    stages (FLEET_STAGES order) plus the backend's stitched stage
    block. Same stamp/add/sync arithmetic as :class:`RequestTrace` —
    every router-side duration is on the router's ``perf_counter``;
    the backend block arrives as durations over the wire and is never
    mixed into router-clock arithmetic."""

    __slots__ = (
        "trace_id", "host", "attempts", "backend", "backend_total_ms",
    )

    def __init__(
        self, seq: int, priority: int, tenant: Optional[str],
        t0: float, trace_id: str,
    ):
        super().__init__(seq, priority, tenant, t0)
        self.trace_id = trace_id
        self.host: Optional[str] = None  # label of the answering host
        self.attempts = 0
        self.backend: Optional[Dict[str, float]] = None
        self.backend_total_ms: Optional[float] = None

    def slowest_stage(self) -> Optional[str]:
        """The single most expensive span of this request, across both
        sides of the hop — ``retry_hop`` / ``network`` name the router
        side, ``backend.compute`` etc. name the host side — so a tail
        exemplar always names host AND stage."""
        spans = {s: ms for s, ms in self.stages.items()}
        for s, ms in (self.backend or {}).items():
            spans[f"backend.{s}"] = ms
        if not spans:
            return None
        return max(spans.items(), key=lambda kv: kv[1])[0]

    def waterfall(self) -> Dict[str, Any]:
        out = {
            "trace": self.trace_id,
            "seq": self.seq,
            "priority": self.priority,
            "tenant": self.tenant,
            "host": self.host,
            "attempts": self.attempts,
            "total_ms": round((self._last - self.t0) * 1000.0, 3),
            "stages": {
                s: round(self.stages[s], 3)
                for s in FLEET_STAGES if s in self.stages
            },
            "backend_total_ms": (
                round(self.backend_total_ms, 3)
                if self.backend_total_ms is not None else None
            ),
            "backend": (
                {
                    s: round(self.backend[s], 3)
                    for s in STAGES if s in self.backend
                }
                if self.backend is not None else None
            ),
            "slowest_stage": self.slowest_stage(),
        }
        return out


class FleetTracer(RequestTracer):
    """The router-side span recorder: mints trace ids, stitches the
    backend's self-reported stage block into the router waterfall, and
    assembles the v7 verdict's ``fleet_attribution`` block.

    Stitching contract (the §13 two-clock discipline, one hop up): the
    router measures ``connect`` and the exchange wall on its OWN
    clock; the backend reports its span and stage decomposition as
    DURATIONS in the ``x-rtrace-stages`` header; ``network`` is the
    exchange wall minus the backend span — a difference of two
    durations. A missing/malformed header charges the whole exchange
    to ``network`` and counts the request ``unstitched`` (it still
    reconciles — the identity checks bookkeeping, not the backend)."""

    def __init__(
        self,
        *,
        seed: int = 0,
        sample_every: int = 16,
        tail_k: int = 5,
        window: int = 1024,
        on_sample: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        super().__init__(
            seed=seed, sample_every=sample_every, tail_k=tail_k,
            window=window, on_sample=on_sample,
        )
        # backend stage windows: {priority: {stage: deque[ms]}} and
        # {host: {stage: deque[ms]}} — the per-host view feeds the
        # host-stage-spread gate
        self._backend_win: Dict[int, Dict[str, Any]] = {}  # guarded-by: _lock
        self._host_win: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._host_n: Dict[str, int] = {}  # guarded-by: _lock
        # guarded-by: _lock: _stitched, _unstitched, _recon_violations
        self._stitched = 0
        self._unstitched = 0
        self._recon_violations = 0
        # cumulative retry-hop / e2e ms per priority (shares survive
        # window eviction) — guarded-by: _lock: _retry_ms, _e2e_ms
        self._retry_ms: Dict[int, float] = {}
        self._e2e_ms: Dict[int, float] = {}

    # -- request path --------------------------------------------------

    def begin(
        self,
        priority: int = 0,
        tenant: Optional[str] = None,
        t_start: Optional[float] = None,
    ) -> FleetTrace:
        with self._lock:
            seq = self._seq
            self._seq += 1
        return FleetTrace(
            seq, int(priority), tenant,
            time.perf_counter() if t_start is None else float(t_start),
            mint_trace_id(self.seed, seq),
        )

    def stitch(
        self,
        trace: FleetTrace,
        exchange_ms: float,
        stage_header: Any,
        host: Optional[str],
    ) -> None:
        """Fold the answering host's response into the waterfall:
        parse its ``x-rtrace-stages`` header, derive ``network`` as
        exchange wall minus the backend's span (clamped at 0 — the
        backend span can only legally be SHORTER than the exchange
        that contains it), or charge the whole exchange to ``network``
        when the header is absent/malformed (unstitched)."""
        trace.host = host
        parsed = parse_stage_header(stage_header)
        if parsed is not None and parsed["id"] == trace.trace_id:
            trace.backend = parsed["stages"]
            trace.backend_total_ms = parsed["total_ms"]
            trace.add(
                "network",
                max(float(exchange_ms) - parsed["total_ms"], 0.0),
            )
        else:
            trace.backend = None
            trace.backend_total_ms = None
            trace.add("network", float(exchange_ms))

    def finish(self, trace: FleetTrace) -> None:  # type: ignore[override]
        """Roll one relayed-200 request into the fleet statistics.
        The reconciliation identity here is cross-hop: router stages
        (network included) + backend stage sum == router-observed
        end-to-end, within the same tolerance as the single-host
        identity."""
        now = trace._last
        total_ms = (now - trace.t0) * 1000.0
        backend_ms = sum((trace.backend or {}).values())
        stage_sum = sum(trace.stages.values()) + backend_ms
        err_ms = abs(total_ms - stage_sum)
        err_pct = (
            err_ms / total_ms * 100.0 if total_ms > 0 else 0.0
        )
        sampled = self._keep(trace.seq)
        with self._lock:
            self.finished += 1
            p = trace.priority
            wins = self._stage_win.get(p)
            if wins is None:
                wins = self._stage_win[p] = {}
            for stage, ms in trace.stages.items():
                win = wins.get(stage)
                if win is None:
                    win = wins[stage] = deque(maxlen=self.window)
                win.append(ms)
            e2e = self._e2e_win.get(p)
            if e2e is None:
                e2e = self._e2e_win[p] = deque(maxlen=self.window)
            e2e.append(total_ms)
            if trace.backend is not None:
                self._stitched += 1
                bwins = self._backend_win.get(p)
                if bwins is None:
                    bwins = self._backend_win[p] = {}
                hwins = None
                if trace.host is not None:
                    hwins = self._host_win.get(trace.host)
                    if hwins is None:
                        hwins = self._host_win[trace.host] = {}
                for stage, ms in trace.backend.items():
                    win = bwins.get(stage)
                    if win is None:
                        win = bwins[stage] = deque(maxlen=self.window)
                    win.append(ms)
                    if hwins is not None:
                        win = hwins.get(stage)
                        if win is None:
                            win = hwins[stage] = deque(
                                maxlen=self.window
                            )
                        win.append(ms)
            else:
                self._unstitched += 1
            if trace.host is not None:
                self._host_n[trace.host] = (
                    self._host_n.get(trace.host, 0) + 1
                )
            self._retry_ms[p] = (
                self._retry_ms.get(p, 0.0)
                + trace.stages.get("retry_hop", 0.0)
            )
            self._e2e_ms[p] = self._e2e_ms.get(p, 0.0) + total_ms
            self._recon_n += 1
            self._recon_sum_err_ms += err_ms
            self._recon_sum_err_pct += err_pct
            if err_pct > self._recon_max_err_pct:
                self._recon_max_err_pct = err_pct
            if err_pct > RECON_TOL_PCT and err_ms > RECON_FLOOR_MS:
                self._recon_violations += 1
            if self.tail_k > 0:
                tail = self._tail.get(p)
                if tail is None:
                    tail = self._tail[p] = []
                heapq.heappush(tail, (total_ms, trace.seq, trace))
                if len(tail) > self.tail_k:
                    heapq.heappop(tail)
            if sampled:
                self.sampled += 1
        if sampled and self.on_sample is not None:
            try:
                self.on_sample(trace.waterfall())
            except Exception:
                pass  # telemetry must never break the proxy path

    # -- reporting -----------------------------------------------------

    @staticmethod
    def _share(retry: float, e2e: float, n: int) -> Optional[float]:
        if n <= 0:
            return None
        if e2e <= 0:
            return 0.0
        return round(retry / e2e, 4)

    def _merged_backend_windows(self) -> Dict[str, List[float]]:  # requires-lock: _lock
        merged: Dict[str, List[float]] = {}
        for wins in self._backend_win.values():
            for stage, win in wins.items():
                merged.setdefault(stage, []).extend(win)
        return merged

    def stats(self) -> Dict[str, Any]:
        """The live router snapshot (``/statsz`` ``rtrace`` block and
        the ``fleet`` stats heartbeat): router-stage and backend-stage
        p99 over the rolling windows, e2e p99 per priority, cumulative
        retry-hop share, stitch counters."""
        from bdbnn_tpu.serve.loadgen import _pct

        with self._lock:
            merged = self._merged_stage_windows()
            bmerged = self._merged_backend_windows()
            e2e = {p: list(w) for p, w in self._e2e_win.items()}
            finished, aborted, sampled = (
                self.finished, self.aborted, self.sampled
            )
            stitched, unstitched = self._stitched, self._unstitched
            retry = sum(self._retry_ms.values())
            e2e_sum = sum(self._e2e_ms.values())
        stage_blocks = {
            s: self._pcts(merged.get(s)) for s in FLEET_STAGES
        }
        backend_blocks = {
            s: self._pcts(bmerged.get(s)) for s in STAGES
        }
        return {
            "requests": finished,
            "aborted": aborted,
            "sampled": sampled,
            "stitched": stitched,
            "unstitched": unstitched,
            "stage_p99_ms": {
                s: (b or {}).get("p99_ms")
                for s, b in stage_blocks.items()
            },
            "backend_stage_p99_ms": {
                s: (b or {}).get("p99_ms")
                for s, b in backend_blocks.items()
            },
            "e2e_p99_ms_by_priority": {
                str(p): _pct(sorted(w), 99.0)
                for p, w in sorted(e2e.items())
            },
            "retry_hop_share": self._share(retry, e2e_sum, finished),
        }

    def attribution(
        self, *, device: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """The v7 verdict's ``fleet_attribution`` block: per-priority
        e2e p50/p99 decomposed into router stages + network + the
        backend stage block, retry-hop share, per-host backend-stage
        spread, slowest-K cross-host exemplars (each naming host AND
        stage), and the cross-hop reconciliation identity."""
        with self._lock:
            per_p = {
                p: {s: list(w) for s, w in wins.items()}
                for p, wins in self._stage_win.items()
            }
            per_p_backend = {
                p: {s: list(w) for s, w in wins.items()}
                for p, wins in self._backend_win.items()
            }
            per_host = {
                h: {s: list(w) for s, w in wins.items()}
                for h, wins in self._host_win.items()
            }
            host_n = dict(self._host_n)
            e2e = {p: list(w) for p, w in self._e2e_win.items()}
            tails = {
                p: [
                    tr.waterfall()
                    for _, _, tr in sorted(
                        t, key=lambda x: (x[0], x[1]), reverse=True
                    )
                ]
                for p, t in self._tail.items()
            }
            merged = self._merged_stage_windows()
            bmerged = self._merged_backend_windows()
            finished, aborted, sampled = (
                self.finished, self.aborted, self.sampled
            )
            stitched, unstitched = self._stitched, self._unstitched
            retry_by_p = dict(self._retry_ms)
            e2e_by_p = dict(self._e2e_ms)
            recon_n = self._recon_n
            violations = self._recon_violations
            mean_err_ms = (
                self._recon_sum_err_ms / recon_n if recon_n else None
            )
            mean_err_pct = (
                self._recon_sum_err_pct / recon_n if recon_n else None
            )
            max_err_pct = (
                self._recon_max_err_pct if recon_n else None
            )
        stage_blocks = {s: self._pcts(merged.get(s)) for s in FLEET_STAGES}
        backend_blocks = {s: self._pcts(bmerged.get(s)) for s in STAGES}
        per_priority: Dict[str, Any] = {}
        for p in sorted(set(per_p) | set(e2e)):
            n_p = len(e2e.get(p, []))
            per_priority[str(p)] = {
                "e2e": self._pcts(e2e.get(p, [])),
                "stages": {
                    s: self._pcts(per_p.get(p, {}).get(s))
                    for s in FLEET_STAGES
                },
                "backend_stages": {
                    s: self._pcts(per_p_backend.get(p, {}).get(s))
                    for s in STAGES
                },
                "retry_hop_share": self._share(
                    retry_by_p.get(p, 0.0), e2e_by_p.get(p, 0.0), n_p,
                ),
            }
        per_host_blocks = {
            h: {
                "requests": host_n.get(h, 0),
                "stages": {
                    s: self._pcts(per_host.get(h, {}).get(s))
                    for s in STAGES
                },
            }
            for h in sorted(set(per_host) | set(host_n))
        }
        # per-host stage spread: for each backend stage, the ratio of
        # the slowest host's p99 to the fastest host's — 1.0 means a
        # perfectly even fleet, and the MAX over stages is the compare
        # gate (a single host slow in a single stage must move it)
        spread: Dict[str, Optional[float]] = {}
        for s in STAGES:
            p99s = []
            for h, wins in per_host.items():
                blk = self._pcts(wins.get(s))
                if blk is not None and blk["p99_ms"] is not None:
                    p99s.append(blk["p99_ms"])
            if len(p99s) >= 2 and min(p99s) > 0:
                spread[s] = round(max(p99s) / min(p99s), 4)
            else:
                spread[s] = None
        spreads = [v for v in spread.values() if v is not None]
        spread_max = max(spreads) if spreads else None
        retry_sum = sum(retry_by_p.values())
        e2e_sum = sum(e2e_by_p.values())
        ok = None
        if recon_n:
            ok = bool(
                (
                    mean_err_pct <= RECON_TOL_PCT
                    or mean_err_ms <= RECON_FLOOR_MS
                )
                and violations == 0
            )
        return {
            "clocks": {
                "router": (
                    "time.perf_counter on the router process; spans "
                    "stamped from request parse — cannot see the "
                    "client's connect/backlog wait"
                ),
                "backend": (
                    "each host's own perf_counter base; its span "
                    "crosses the wire as DURATIONS in "
                    "x-rtrace-stages, never as timestamps"
                ),
                "contract": (
                    "no cross-clock subtraction: network = router "
                    "exchange wall minus the backend's self-reported "
                    "span (two durations)"
                ),
            },
            "sample_every": self.sample_every,
            "tail_k": self.tail_k,
            "window": self.window,
            "requests": finished,
            "aborted": aborted,
            "sampled": sampled,
            "stitched": stitched,
            "unstitched": unstitched,
            "stages": stage_blocks,
            "backend_stages": backend_blocks,
            "retry_hop_share": self._share(
                retry_sum, e2e_sum, finished,
            ),
            "per_priority": per_priority,
            "per_host": per_host_blocks,
            "host_stage_spread": spread,
            "host_stage_spread_max": spread_max,
            "reconciliation": {
                "requests": recon_n,
                "stitched": stitched,
                "unstitched": unstitched,
                "violations": violations,
                "mean_abs_err_ms": (
                    round(mean_err_ms, 4)
                    if mean_err_ms is not None else None
                ),
                "mean_abs_err_pct": (
                    round(mean_err_pct, 3)
                    if mean_err_pct is not None else None
                ),
                "max_abs_err_pct": (
                    round(max_err_pct, 3)
                    if max_err_pct is not None else None
                ),
                "tolerance_pct": RECON_TOL_PCT,
                "floor_ms": RECON_FLOOR_MS,
                "ok": ok,
            },
            "tail": {str(p): t for p, t in sorted(tails.items())},
            "device": device,
        }


class HostStatsWindows:
    """The fleet metrics plane's storage: per-(host, priority, stage)
    rolling windows merged from each host's scraped ``/statsz`` rtrace
    block, with per-host failure counters and staleness.

    The scrape loop (FleetRouter's stats pump) calls ``record`` after
    a successful bounded-timeout scrape and ``record_failure`` when
    one times out or errors; ``stale_after`` consecutive failures mark
    that host's window stale — the merged view then EXCLUDES it (an
    autoscaler must never act on a wedged host's frozen numbers) and
    ``watch`` renders the host as stale. A single wedged host can
    never stall the pump: every scrape carries its own timeout and a
    failure only moves counters."""

    def __init__(self, *, window: int = 64, stale_after: int = 3):
        if window < 1:
            raise ValueError("window must be >= 1")
        if stale_after < 1:
            raise ValueError("stale_after must be >= 1")
        self.window = int(window)
        self.stale_after = int(stale_after)
        self._lock = threading.Lock()
        # per-host scrape state:
        # {host: {"stage": {stage: deque[p99_ms]},
        #         "e2e": {priority: deque[p99_ms]},
        #         "last": <latest rtrace block>,
        #         "t_ok": perf_counter of the last good scrape}}
        self._hosts: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        # guarded-by: _lock: _scrapes, _failures, _fail_streak
        self._scrapes: Dict[str, int] = {}
        self._failures: Dict[str, int] = {}
        self._fail_streak: Dict[str, int] = {}

    def record(self, host: str, rtrace_block: Dict[str, Any]) -> None:
        """One good scrape: roll the host's reported per-stage and
        per-priority p99s into its windows and clear its fail streak."""
        if not isinstance(rtrace_block, dict):
            return self.record_failure(host)
        stage_p99 = rtrace_block.get("stage_p99_ms") or {}
        e2e_p99 = rtrace_block.get("e2e_p99_ms_by_priority") or {}
        now = time.perf_counter()
        with self._lock:
            state = self._hosts.get(host)
            if state is None:
                state = self._hosts[host] = {
                    "stage": {}, "e2e": {}, "last": None, "t_ok": None,
                }
            for stage, p99 in stage_p99.items():
                if not isinstance(p99, (int, float)):
                    continue
                if not math.isfinite(p99):
                    continue
                win = state["stage"].get(stage)
                if win is None:
                    win = state["stage"][stage] = deque(
                        maxlen=self.window
                    )
                win.append(float(p99))
            for prio, p99 in e2e_p99.items():
                if not isinstance(p99, (int, float)):
                    continue
                if not math.isfinite(p99):
                    continue
                win = state["e2e"].get(str(prio))
                if win is None:
                    win = state["e2e"][str(prio)] = deque(
                        maxlen=self.window
                    )
                win.append(float(p99))
            state["last"] = rtrace_block
            state["t_ok"] = now
            self._scrapes[host] = self._scrapes.get(host, 0) + 1
            self._fail_streak[host] = 0

    def record_failure(self, host: str) -> None:
        """A scrape that timed out or errored: counters only — the
        host's windows keep their last-known numbers but go stale
        after ``stale_after`` consecutive failures."""
        with self._lock:
            self._failures[host] = self._failures.get(host, 0) + 1
            self._fail_streak[host] = self._fail_streak.get(host, 0) + 1

    def _stale(self, host: str) -> bool:  # requires-lock: _lock
        return self._fail_streak.get(host, 0) >= self.stale_after

    def snapshot(self) -> Dict[str, Any]:
        """The live plane: per-host windowed stage/e2e percentiles
        with staleness, plus a merged view over FRESH hosts only (per
        stage and per priority, the worst fresh host's windowed p99 —
        the number the future autoscaler keys on)."""
        with self._lock:
            hosts = {
                h: {
                    "stage": {s: list(w) for s, w in st["stage"].items()},
                    "e2e": {p: list(w) for p, w in st["e2e"].items()},
                    "last": st["last"],
                    "t_ok": st["t_ok"],
                }
                for h, st in self._hosts.items()
            }
            scrapes = dict(self._scrapes)
            failures = dict(self._failures)
            streaks = dict(self._fail_streak)
        # a host we have only ever failed to scrape still shows up
        for h in set(failures) - set(hosts):
            hosts[h] = {"stage": {}, "e2e": {}, "last": None, "t_ok": None}
        now = time.perf_counter()
        out_hosts: Dict[str, Any] = {}
        merged_stage: Dict[str, List[float]] = {}
        merged_e2e: Dict[str, List[float]] = {}
        fresh = stale = 0
        for h in sorted(hosts):
            st = hosts[h]
            is_stale = streaks.get(h, 0) >= self.stale_after
            if is_stale:
                stale += 1
            else:
                fresh += 1
            stage_blocks = {
                s: RequestTracer._pcts(st["stage"].get(s))
                for s in STAGES
            }
            e2e_blocks = {
                p: RequestTracer._pcts(w)
                for p, w in sorted(st["e2e"].items())
            }
            out_hosts[h] = {
                "stale": is_stale,
                "scrapes": scrapes.get(h, 0),
                "failures": failures.get(h, 0),
                "fail_streak": streaks.get(h, 0),
                "age_s": (
                    round(now - st["t_ok"], 3)
                    if st["t_ok"] is not None else None
                ),
                "stage_p99_ms": {
                    s: (b or {}).get("p99_ms")
                    for s, b in stage_blocks.items()
                },
                "e2e_p99_ms_by_priority": {
                    p: (b or {}).get("p99_ms")
                    for p, b in e2e_blocks.items()
                },
                "queue_share": (st["last"] or {}).get("queue_share"),
            }
            if not is_stale:
                for s, win in st["stage"].items():
                    merged_stage.setdefault(s, []).extend(win)
                for p, win in st["e2e"].items():
                    merged_e2e.setdefault(p, []).extend(win)
        merged = {
            "stage_p99_ms": {
                s: (RequestTracer._pcts(merged_stage.get(s)) or {}).get(
                    "p99_ms"
                )
                for s in STAGES
            },
            "e2e_p99_ms_by_priority": {
                p: (RequestTracer._pcts(w) or {}).get("p99_ms")
                for p, w in sorted(merged_e2e.items())
            },
        }
        return {
            "window": self.window,
            "stale_after": self.stale_after,
            "hosts_fresh": fresh,
            "hosts_stale": stale,
            "hosts": out_hosts,
            "merged": merged,
        }


__all__ = [
    "FLEET_STAGES",
    "RECON_FLOOR_MS",
    "RECON_TOL_PCT",
    "STAGES",
    "STAGE_HEADER",
    "TRACE_CTX_MAX_LEN",
    "TRACE_HEADER",
    "FleetTrace",
    "FleetTracer",
    "HostStatsWindows",
    "RequestTrace",
    "RequestTracer",
    "encode_stage_header",
    "encode_trace_context",
    "mint_trace_id",
    "parse_stage_header",
    "parse_trace_context",
    "pop_future_answered_by",
    "pop_future_timing",
    "set_future_answered_by",
    "set_future_timing",
]
