"""Unified run telemetry (observability subsystem).

One structured, machine-readable layer behind the three historical
channels (logger text, ``ScalarWriter`` JSONL, ``--profile-dir``
traces):

- :mod:`bdbnn_tpu.obs.manifest` — ``manifest.json`` provenance
- :mod:`bdbnn_tpu.obs.events`   — ``events.jsonl`` structured timeline
- :mod:`bdbnn_tpu.obs.timing`   — host step-phase accounting
- :mod:`bdbnn_tpu.obs.trace`    — semantic span taxonomy, the trace
  parser (per-category device ms/step + MFU), and exception-safe
  capture windows (``--profile-at``)
- :mod:`bdbnn_tpu.obs.memory`   — HBM watermark polling (``memory``
  events)
- :mod:`bdbnn_tpu.obs.probes`   — on-device binarization health probes
  (imported lazily by the train step; it needs jax)
- :mod:`bdbnn_tpu.obs.summarize` — the ``summarize`` CLI's report engine
- :mod:`bdbnn_tpu.obs.watch`    — the ``watch`` CLI's live status tail

This package root stays stdlib-importable: ``summarize``/``watch`` must
read run directories without initializing a JAX backend, so anything
needing jax lives in :mod:`~bdbnn_tpu.obs.probes` (or behind the lazy
imports inside :class:`~bdbnn_tpu.obs.trace.TraceCapture`) and is NOT
imported here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from bdbnn_tpu.obs.compare import compare_runs, extract_run, render_comparison
from bdbnn_tpu.obs.events import (
    EVENTS_NAME,
    KNOWN_KINDS,
    EventWriter,
    load_events,
    read_events,
)
from bdbnn_tpu.obs.health import (
    HealthConfig,
    HealthMonitor,
    apply_overrides as apply_health_overrides,
)
from bdbnn_tpu.obs.manifest import (
    MANIFEST_NAME,
    RunManifest,
    config_hash,
    read_manifest,
    write_manifest,
)
from bdbnn_tpu.obs.memory import emit_memory_event, hbm_watermark
from bdbnn_tpu.obs.summarize import resolve_run_dir, summarize_run
from bdbnn_tpu.obs.timing import StepPhaseTimer
from bdbnn_tpu.obs.trace import (
    BF16_PEAK_TFLOPS,
    DEVICE_SPANS,
    HOST_PHASES,
    TraceCapture,
    attribute_trace,
    find_trace_file,
    hlo_breakdown,
    jit_step_ms,
    parse_profile_at,
)


@dataclasses.dataclass
class ObsHooks:
    """The telemetry bundle fit() threads through its epoch loop."""

    events: EventWriter
    timer: StepPhaseTimer
    # layer name -> weight count, for normalizing drained flip sums
    probe_sizes: Dict[str, int]
    nonfinite_policy: str = "raise"
    # --profile-at capture windows (None = no windows requested)
    tracer: Optional[TraceCapture] = None
    # online health monitor (obs/health.py; None = --no-health)
    health: Optional[HealthMonitor] = None
    # fit()-scoped auto-forensics callback:
    # forensics(state, epoch, step_cursor, alerts) — snapshots a
    # checkpoint + schedules a trace window when an alert fires
    forensics: Optional[Any] = None


__all__ = [
    "BF16_PEAK_TFLOPS",
    "DEVICE_SPANS",
    "EVENTS_NAME",
    "HOST_PHASES",
    "KNOWN_KINDS",
    "MANIFEST_NAME",
    "EventWriter",
    "HealthConfig",
    "HealthMonitor",
    "ObsHooks",
    "RunManifest",
    "StepPhaseTimer",
    "TraceCapture",
    "apply_health_overrides",
    "attribute_trace",
    "compare_runs",
    "config_hash",
    "emit_memory_event",
    "extract_run",
    "find_trace_file",
    "hbm_watermark",
    "hlo_breakdown",
    "jit_step_ms",
    "load_events",
    "parse_profile_at",
    "read_events",
    "read_manifest",
    "render_comparison",
    "resolve_run_dir",
    "summarize_run",
    "write_manifest",
]
