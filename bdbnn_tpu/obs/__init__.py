"""Unified run telemetry (observability subsystem).

One structured, machine-readable layer behind the three historical
channels (logger text, ``ScalarWriter`` JSONL, ``--profile-dir``
traces):

- :mod:`bdbnn_tpu.obs.manifest` — ``manifest.json`` provenance
- :mod:`bdbnn_tpu.obs.events`   — ``events.jsonl`` structured timeline
- :mod:`bdbnn_tpu.obs.timing`   — host step-phase accounting
- :mod:`bdbnn_tpu.obs.probes`   — on-device binarization health probes
  (imported lazily by the train step; it needs jax)
- :mod:`bdbnn_tpu.obs.summarize` — the ``summarize`` CLI's report engine

This package root stays stdlib-importable: ``summarize`` must read run
directories without initializing a JAX backend, so anything needing jax
lives in :mod:`~bdbnn_tpu.obs.probes` and is NOT imported here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from bdbnn_tpu.obs.events import EVENTS_NAME, EventWriter, read_events
from bdbnn_tpu.obs.manifest import (
    MANIFEST_NAME,
    RunManifest,
    config_hash,
    read_manifest,
    write_manifest,
)
from bdbnn_tpu.obs.summarize import resolve_run_dir, summarize_run
from bdbnn_tpu.obs.timing import StepPhaseTimer


@dataclasses.dataclass
class ObsHooks:
    """The telemetry bundle fit() threads through its epoch loop."""

    events: EventWriter
    timer: StepPhaseTimer
    # layer name -> weight count, for normalizing drained flip sums
    probe_sizes: Dict[str, int]
    nonfinite_policy: str = "raise"


__all__ = [
    "EVENTS_NAME",
    "MANIFEST_NAME",
    "EventWriter",
    "ObsHooks",
    "RunManifest",
    "StepPhaseTimer",
    "config_hash",
    "read_events",
    "read_manifest",
    "resolve_run_dir",
    "summarize_run",
    "write_manifest",
]
