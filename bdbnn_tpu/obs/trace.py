"""Semantic trace attribution — spans, capture windows, and the parser.

The paper's cost story (XNOR-Net lineage) is that binary convs should
dominate neither time nor memory; checking that used to mean regexing
raw HLO op names out of a ``jax.profiler`` trace with a one-off script
(``profile_r05.py``) that only understood the flagship bench config.
This module makes the attribution first-class, in three parts:

1. **Span taxonomy.** The jitted step's meaningful segments are wrapped
   in ``jax.named_scope`` at their definition sites (``nn/layers.py``,
   ``nn/binarize.py``, ``models/resnet.py``, ``losses/``,
   ``train/step.py``), so XLA op metadata — and therefore device trace
   events — carry stable category names (:data:`DEVICE_SPANS`) instead
   of fusion-renamed HLO suffixes. Host phases (:data:`HOST_PHASES`)
   are annotated by the train loop with
   ``jax.profiler.TraceAnnotation`` while a capture window is open.

2. **Parser** (:func:`attribute_trace`, :func:`hlo_breakdown`,
   :func:`jit_step_ms`) — stdlib-only aggregation of a
   ``trace.json.gz`` into per-category device ms/step + an MFU
   estimate, for ANY config. ``summarize`` (which must never
   initialize a JAX backend) and the bench/profile harnesses share it.

3. **Capture windows** (:class:`TraceCapture`) — start/stop the
   profiler at arbitrary ``EPOCH:STEP[:NSTEPS]`` points
   (``--profile-at``), exception-safe: a step that raises between
   start and stop can neither leave the profiler running nor stop it
   twice. ``jax`` is imported lazily inside the capture methods only.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

# Device-side span taxonomy: the categories a BD-BNN train step's
# device time decomposes into. Each name is a jax.named_scope at the
# site that owns the math; trace events are attributed to the INNERMOST
# matching span on their metadata path.
DEVICE_SPANS: Tuple[str, ...] = (
    "binarize",       # sign/STE of weights + activations (nn/layers.py)
    "binary_conv",    # the ±alpha conv itself (nn/kernels)
    "bn_act",         # BatchNorm + residual add + activation (models/resnet.py)
    "kurtosis_loss",  # the bimodal regularizer (losses/kurtosis.py)
    "kd_logit_loss",  # KD distribution loss over logits (losses/kd.py)
    "kd_weight_loss", # KD layer weight KL (losses/kd.py)
    "ede_grad",       # EDE estimator backward transform (nn/binarize.py)
    "optimizer",      # optax update + apply (train/step.py)
    "probes",         # binarization health probes (obs/probes.py)
)

# Host-side phases, annotated by the train loop while a window is open.
HOST_PHASES: Tuple[str, ...] = ("data_wait", "dispatch")

# Published per-chip dense bf16 peaks (TFLOP/s), keyed on
# jax.devices()[0].device_kind. Sources: Google Cloud TPU system
# architecture docs (v2-v6e product pages). Shared by bench.py,
# profile_r05.py and `summarize`'s MFU estimate.
BF16_PEAK_TFLOPS: Dict[str, float] = {
    "TPU v2": 22.5,
    "TPU v3": 61.5,
    "TPU v4": 275.0,  # one megacore device per chip
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,       # v5p reports device_kind "TPU v5"
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,  # v6e (Trillium)
    "TPU v6e": 918.0,
}

TraceSource = Union[str, Sequence[Dict[str, Any]]]


# ---------------------------------------------------------------------------
# capture-window spec
# ---------------------------------------------------------------------------


def parse_profile_at(spec: str, default_steps: int = 5) -> Tuple[int, int, int]:
    """``"EPOCH:STEP[:NSTEPS]"`` → ``(epoch, start_step, n_steps)``.

    Generalizes the legacy epoch-0-only ``--profile-dir`` window to an
    arbitrary point in training (e.g. ``12:40:8`` = 8 steps starting at
    epoch 12 step 40 — after the kurtosis gate opens, say)."""
    parts = spec.split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"bad --profile-at spec {spec!r}: want EPOCH:STEP[:NSTEPS]"
        )
    try:
        nums = [int(p) for p in parts]
    except ValueError as e:
        raise ValueError(f"bad --profile-at spec {spec!r}: {e}") from None
    epoch, step = nums[0], nums[1]
    n_steps = nums[2] if len(nums) == 3 else default_steps
    if epoch < 0 or step < 0 or n_steps < 1:
        raise ValueError(
            f"bad --profile-at spec {spec!r}: epoch/step must be >= 0 "
            "and NSTEPS >= 1"
        )
    return epoch, step, n_steps


# ---------------------------------------------------------------------------
# trace parsing (stdlib-only; shared by summarize / bench / profile_r05)
# ---------------------------------------------------------------------------


def find_trace_file(root: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``root`` (the profiler writes
    ``<dir>/plugins/profile/<ts>/<host>.trace.json.gz``)."""
    hits = sorted(
        glob.glob(os.path.join(root, "**", "*.trace.json.gz"), recursive=True)
    )
    return hits[-1] if hits else None


def load_trace_events(source: TraceSource) -> List[Dict[str, Any]]:
    """Trace events from a path (``.json.gz`` or plain ``.json``) or an
    already-loaded event list (passthrough)."""
    if not isinstance(source, str):
        return list(source)
    opener = gzip.open if source.endswith(".gz") else open
    with opener(source, "rt") as f:
        tr = json.load(f)
    return tr.get("traceEvents", [])


def _pid_names(events) -> Dict[Any, str]:
    return {
        e["pid"]: str(e.get("args", {}).get("name", ""))
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }


def _real_device_pids(events) -> set:
    """Pids of true device tracks (TPU/GPU processes)."""
    names = _pid_names(events)
    return {
        p
        for p, n in names.items()
        if "TPU" in n or "GPU" in n or "device" in n.lower()
    }


def _thread_names(events) -> Dict[Tuple[Any, Any], str]:
    return {
        (e["pid"], e.get("tid")): str(e.get("args", {}).get("name", ""))
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }


# device-process threads that hold actual executed work: the XLA op and
# module lines (TPU) / streams (GPU). Everything ELSE under a device
# pid is an umbrella view of the same time — "TensorFlow Name Scope"
# spans named after the scopes themselves, "TensorFlow Ops", the
# "Steps" line, TraceMe — and counting it would double-attribute every
# category (or inflate "unattributed" by a full step per aux line).
_OP_THREAD = re.compile(r"xla ops|xla modules|stream", re.I)


def _split_events(
    events, step_prefix: str
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Partition complete (``ph == "X"``) events into
    ``(device_ops, module_events, host_events)``.

    On TPU/GPU the device track is a distinct process; its executed-op
    threads (see :data:`_OP_THREAD`; when the trace names threads at
    all, only those count — unknown thread names are dropped rather
    than risked as double counts) carry device time, with
    ``step_prefix``-named events (e.g. ``jit_train_step``) as the
    module level and the rest as ops. The CPU backend has no device
    track — XLA op events land on the host process, identifiable by
    their ``hlo_op`` metadata arg; runtime noise on the same pid
    (executor bookkeeping, the PjitFunction span that would
    double-count every op under it) stays host-side."""
    real_dev = _real_device_pids(events)
    tnames = _thread_names(events)
    dev_threads_named = any(p in real_dev for p, _ in tnames)
    device_ops: List[Dict[str, Any]] = []
    module_evs: List[Dict[str, Any]] = []
    host_evs: List[Dict[str, Any]] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        on_dev = e.get("pid") in real_dev
        if on_dev and dev_threads_named and not _OP_THREAD.search(
            tnames.get((e.get("pid"), e.get("tid")), "")
        ):
            continue  # aux umbrella line on the device process
        if name.startswith(step_prefix) and (on_dev or not real_dev):
            module_evs.append(e)
        elif on_dev or "hlo_op" in (e.get("args") or {}):
            device_ops.append(e)
        else:
            host_evs.append(e)
    return device_ops, module_evs, host_evs


_TRAILING_IDX = re.compile(r"[.\d]+$")


def _span_of(event: Dict[str, Any], spans: Sequence[str]) -> Optional[str]:
    """Innermost span on the event's metadata path, or None.

    XLA op events carry the framework scope path (named_scope segments)
    in metadata args — ``tf_op`` / ``long_name`` / ``scope`` depending
    on backend and profiler version — and sometimes in the event name
    itself. Segments are matched exactly after stripping trailing
    ``.N`` disambiguators, scanning innermost-first."""
    candidates = [str(event.get("name", ""))]
    for v in (event.get("args") or {}).values():
        if isinstance(v, str):
            candidates.append(v)
    for cand in candidates:
        if "/" not in cand and cand not in spans:
            # cheap pre-filter: a bare HLO name can still BE a span
            # (host TraceAnnotations are bare), otherwise skip
            base = _TRAILING_IDX.sub("", cand)
            if base in spans:
                return base
            continue
        segs = [s for s in cand.split("/") if s]
        for seg in reversed(segs):  # innermost scope wins
            base = _TRAILING_IDX.sub("", seg)
            if base in spans:
                return base
    return None


def attribute_trace(
    source: TraceSource,
    n_steps: int,
    *,
    flops_per_step: Optional[float] = None,
    peak_tflops: Optional[float] = None,
    step_prefix: str = "jit_",
) -> Dict[str, Any]:
    """Aggregate a trace into semantic per-category device ms/step.

    - device-track op events are attributed to the innermost
      :data:`DEVICE_SPANS` scope on their metadata path; the rest pools
      under ``"unattributed"`` (raw HLO ops whose metadata names no
      span — e.g. input transfers, or scopes added after this parser);
    - module-level events (name starting with ``step_prefix``, e.g.
      ``jit_train_step``) give ``step_total_ms``; where a backend
      emits none (CPU), the op-duration sum stands in;
    - host-track events named exactly a :data:`HOST_PHASES` phase
      (the loop's TraceAnnotations) land in ``host_phases_ms_per_step``;
    - MFU = flops_per_step / device-second / peak. ``flops_per_step``
      falls back to per-op ``flops`` metadata summed from the trace
      when the backend recorded it.
    """
    events = load_trace_events(source)
    steps = max(int(n_steps or 0), 1)
    device_ops, module_evs, host_evs = _split_events(events, step_prefix)

    categories = {s: 0.0 for s in DEVICE_SPANS}
    unattributed = 0.0
    host = {p: 0.0 for p in HOST_PHASES}
    op_total = 0.0
    trace_flops = 0.0

    for e in device_ops:
        dur_ms = float(e.get("dur", 0)) / 1e3
        f = (e.get("args") or {}).get("flops")
        if isinstance(f, (int, float)):
            trace_flops += float(f)
        span = _span_of(e, DEVICE_SPANS)
        if span is not None:
            categories[span] += dur_ms
        else:
            unattributed += dur_ms
        op_total += dur_ms
    for e in host_evs:
        phase = _span_of(e, HOST_PHASES)
        if phase is not None:
            host[phase] += float(e.get("dur", 0)) / 1e3

    module_ms = sum(float(e.get("dur", 0)) / 1e3 for e in module_evs)
    step_total = (
        module_ms / steps if module_evs else (op_total / steps or None)
    )
    if flops_per_step is None and trace_flops > 0:
        flops_per_step = trace_flops / steps
    mfu = None
    if step_total and flops_per_step and peak_tflops:
        mfu = round(
            flops_per_step / (step_total / 1e3) / (peak_tflops * 1e12), 4
        )

    out_cats = {
        k: round(v / steps, 3) for k, v in categories.items() if v > 0.0
    }
    if unattributed > 0.0:
        out_cats["unattributed"] = round(unattributed / steps, 3)
    return {
        "n_steps": steps,
        "categories_ms_per_step": dict(
            sorted(out_cats.items(), key=lambda kv: -kv[1])
        ),
        "step_total_ms": round(step_total, 3) if step_total else None,
        "host_phases_ms_per_step": {
            k: round(v / steps, 3) for k, v in host.items() if v > 0.0
        },
        "flops_per_step": flops_per_step,
        "peak_tflops": peak_tflops,
        "mfu": mfu,
    }


def hlo_breakdown(
    source: TraceSource, n_steps: int, top: int = 10
) -> Tuple[Dict[str, float], Optional[float]]:
    """Legacy raw-HLO view (the shape of ``PROFILE_r04.json``):
    device-track op durations (ms/step) grouped by normalized HLO op
    name (trailing ``.N`` / digit suffixes stripped), top ``top``
    groups + ``"other"``; plus the ms/step of the ``jit_train_step``
    module events. Kept comparable with committed round-4/5 profiles;
    new tooling should prefer :func:`attribute_trace`."""
    events = load_trace_events(source)
    steps = max(int(n_steps or 0), 1)
    device_ops, module_evs, _ = _split_events(events, "jit_train_step")
    groups: Dict[str, float] = {}
    step_total = sum(float(e.get("dur", 0)) / 1e3 for e in module_evs)
    for e in device_ops:
        name = str(e.get("name", ""))
        dur_ms = float(e.get("dur", 0)) / 1e3
        base = _TRAILING_IDX.sub("", name)
        groups[base] = groups.get(base, 0.0) + dur_ms
    per_step = {
        k: round(v / steps, 3)
        for k, v in sorted(groups.items(), key=lambda kv: -kv[1])
    }
    out = dict(list(per_step.items())[:top])
    rest = sum(list(per_step.values())[top:])
    if rest:
        out["other"] = round(rest, 3)
    return out, (step_total / steps if step_total else None)


# ---------------------------------------------------------------------------
# per-layer attribution (obs/roofline.py's measured side)
# ---------------------------------------------------------------------------

# an HLO instruction line in `compiled.as_text()` with framework scope
# metadata: `  %convolution.119 = f32[...] convolution(...),
# metadata={op_name="jit(_apply)/jit(main)/BiResNet/layer1_0/conv1/..."
# ...}`. The instruction name (sans %) is exactly what CPU-backend
# profiler op events carry as args["hlo_op"].
_HLO_INSTR_SCOPE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s[^\n]*?"
    r"metadata=\{[^}\n]*?op_name=\"([^\"]+)\"",
    re.M,
)
_HLO_MODULE = re.compile(r"^HloModule\s+([\w.\-]+)", re.M)


def hlo_op_scopes(hlo_text: str) -> Dict[str, str]:
    """``{instruction_name: framework_scope_path}`` from optimized HLO
    text (``compiled.as_text()``).

    Why this exists: TPU traces carry the named-scope path on each op
    event (``tf_op`` — what :func:`_span_of` consumes), but the CPU
    backend emits op events whose ``tf_op`` is EMPTY; only ``hlo_op``
    (the instruction name, e.g. ``convolution.119``) survives. The
    compiled executable's own HLO text still records the full
    ``op_name`` scope per instruction — parsing it restores the join
    on any backend, including for fusion instructions (their metadata
    names the representative op's scope)."""
    return {
        m.group(1): m.group(2)
        for m in _HLO_INSTR_SCOPE.finditer(hlo_text or "")
    }


def hlo_module_name(hlo_text: str) -> Optional[str]:
    """The ``HloModule`` header name (e.g. ``jit__apply``), for
    filtering trace op events down to one executable via their
    ``hlo_module`` metadata arg."""
    m = _HLO_MODULE.search(hlo_text or "")
    return m.group(1) if m else None


def _match_needle(segs: List[str], needle_segs: List[str]) -> bool:
    """True if ``needle_segs`` occurs as a consecutive run in ``segs``,
    comparing each segment exactly or after stripping a trailing
    ``.N``/digit disambiguator (scope paths repeat a module name as
    ``conv1_1`` only via flax, which is part of the needle itself —
    the stripping only drops XLA's appended indices)."""
    n = len(needle_segs)
    for i in range(len(segs) - n + 1):
        ok = True
        for j in range(n):
            s = segs[i + j]
            if s != needle_segs[j] and _TRAILING_IDX.sub("", s) != (
                needle_segs[j]
            ):
                ok = False
                break
        if ok:
            return True
    return False


def attribute_trace_layers(
    source: TraceSource,
    n_steps: int,
    *,
    layers: Dict[str, str],
    op_scopes: Optional[Dict[str, str]] = None,
    module: Optional[str] = None,
    step_prefix: str = "jit_",
) -> Dict[str, Any]:
    """Per-LAYER device ms/step — the measured half of the roofline.

    ``layers`` maps display names to module scope paths (e.g.
    ``{"layer1_0.conv1": "layer1_0/conv1"}``, from
    :func:`bdbnn_tpu.obs.roofline.model_layer_table`). Each device op
    event resolves its scope path via ``op_scopes[hlo_op]`` (the
    compiled-HLO join above) when given, falling back to the event's
    own string metadata (``tf_op`` — the TPU path); the op is charged
    to the layer whose scope segments occur consecutively in that path,
    LONGEST needle first — so the stem ``conv1`` can never swallow
    ``layer1_0/conv1``'s ops. Ops matching no layer (BN/residual/pad,
    input transfers) pool under ``"unattributed"``; ``total_ms`` is the
    full device-op time per step, the number reconciled against the
    engine's ``time_step`` wall. ``module`` (see
    :func:`hlo_module_name`) drops op events from other executables
    that share the capture window."""
    events = load_trace_events(source)
    steps = max(int(n_steps or 0), 1)
    device_ops, _, _ = _split_events(events, step_prefix)

    ordered = sorted(
        layers.items(),
        key=lambda kv: (-len([s for s in kv[1].split("/") if s]), kv[0]),
    )
    needles = [
        (name, [s for s in scope.split("/") if s])
        for name, scope in ordered
    ]

    per_layer = {name: 0.0 for name in layers}
    unattributed = 0.0
    total = 0.0
    for e in device_ops:
        args = e.get("args") or {}
        if module and str(args.get("hlo_module", module)) != module:
            continue
        dur_ms = float(e.get("dur", 0)) / 1e3
        hlo_op = str(args.get("hlo_op") or e.get("name", ""))
        scope = (op_scopes or {}).get(hlo_op)
        candidates = [scope] if scope else [
            v for v in args.values() if isinstance(v, str) and "/" in v
        ]
        hit = None
        for cand in candidates:
            segs = [s for s in cand.split("/") if s]
            for name, nsegs in needles:
                if _match_needle(segs, nsegs):
                    hit = name
                    break
            if hit:
                break
        if hit is not None:
            per_layer[hit] += dur_ms
        else:
            unattributed += dur_ms
        total += dur_ms
    return {
        "n_steps": steps,
        "layers": {
            k: round(v / steps, 4) for k, v in per_layer.items() if v > 0.0
        },
        "unattributed": round(unattributed / steps, 4),
        "total_ms": round(total / steps, 4),
    }


def jit_step_ms(
    source: TraceSource, prefix: str = "jit_train_step"
) -> Optional[float]:
    """Median on-device duration (ms) of module-level events named
    ``prefix*`` — the tunnel-latency-free per-step number bench.py
    reports as ``device_ms_per_step``."""
    events = load_trace_events(source)
    _, module_evs, _ = _split_events(events, prefix)
    durs = sorted(float(e.get("dur", 0)) / 1e3 for e in module_evs)
    return durs[len(durs) // 2] if durs else None


# ---------------------------------------------------------------------------
# capture windows (needs jax — imported lazily so obs stays stdlib)
# ---------------------------------------------------------------------------


class TraceCapture:
    """Profiler windows at arbitrary ``(epoch, step)`` points.

    Exception-safe by construction: ``_stop`` clears :attr:`active`
    BEFORE calling ``jax.profiler.stop_trace()``, so a raise anywhere
    between start and stop leads to exactly one stop — the loop's
    ``finally`` calls :meth:`stop_if_active`, which is a no-op once a
    normal-path :meth:`maybe_stop` has run, and a second failure inside
    ``stop_trace`` itself cannot re-enter it.
    """

    def __init__(
        self, trace_dir: str, windows: Sequence[Tuple[int, int, int]]
    ) -> None:
        self.trace_dir = trace_dir
        self._pending = sorted(windows)
        # user-requested (--profile-at) specs; unfired() reports only
        # these — a dynamically schedule()d forensics window left
        # pending (the run ended before its target point) is not a
        # user error worth a warning
        self._static = set(self._pending)
        self.active: Optional[Dict[str, int]] = None

    def schedule(self, epoch: int, start_step: int, n_steps: int) -> None:
        """Dynamically add a capture window mid-run — the auto-forensics
        path (obs/health.py): an alert schedules the next ``n_steps``
        steps so the trace holds the pathological steps themselves.
        Callers must target a step the loop will actually run
        (``start_step < steps_per_epoch``): a window opening on the
        loop's final ``maybe_start`` before StopIteration would capture
        an empty trace and emit a misleading ``profile`` event."""
        self._pending.append(
            (int(epoch), int(start_step), max(int(n_steps), 1))
        )
        self._pending.sort()

    def maybe_start(self, epoch: int, step: int) -> bool:
        """Open the window scheduled at this epoch with start step
        ``<= step``, if any. ``<=`` tolerates a caller that skips step
        indices (the loop calls per step, so normally it hits the start
        step exactly). A window whose epoch is never visited (resume
        past it) or whose start step exceeds the epoch's length cannot
        fire — :meth:`unfired` reports those so the run can warn
        instead of silently writing no trace."""
        if self.active is not None:
            return False
        for i, (e, s, n) in enumerate(self._pending):
            if e == epoch and step >= s:
                import jax

                del self._pending[i]
                os.makedirs(self.trace_dir, exist_ok=True)
                jax.profiler.start_trace(self.trace_dir)
                self.active = {"epoch": epoch, "start_step": step, "steps": n}
                return True
        return False

    def unfired(self) -> List[Tuple[int, int, int]]:
        """User-requested windows still pending — unreachable specs
        (epoch resumed past, start step beyond the epoch's step count)
        end up here. Dynamic forensics windows are excluded."""
        return [w for w in self._pending if w in self._static]

    def maybe_stop(self, epoch: int, step: int, fence=None):
        """Close the window once its step budget is traced. Returns the
        window info dict when a stop happened, else None."""
        if self.active is None:
            return None
        if step >= self.active["start_step"] + self.active["steps"] - 1:
            return self._stop(fence)
        return None

    def stop_if_active(self, fence=None, last_step: Optional[int] = None):
        """Failure/epoch-end path: flush an open window exactly once
        (the profiler otherwise records forever and writes nothing).
        ``last_step`` trims the window's reported step count when the
        epoch ended short of the budget — the ms/step math downstream
        must divide by steps actually traced."""
        if self.active is None:
            return None
        if last_step is not None:
            traced = max(last_step - self.active["start_step"] + 1, 1)
            self.active["steps"] = min(self.active["steps"], traced)
        return self._stop(fence)

    def annotate(self, name: str):
        """A ``TraceAnnotation(name)`` while a window is open (host
        phase attribution), else a free nullcontext — the hot loop
        stays unperturbed outside windows."""
        if self.active is None:
            return nullcontext()
        import jax

        return jax.profiler.TraceAnnotation(name)

    def _stop(self, fence):
        import jax

        info = dict(self.active)
        # clear FIRST: if fence() or stop_trace() raises, no later
        # finally-path call may stop a second time
        self.active = None
        try:
            if fence is not None:
                fence()  # drain queued steps so the trace holds them
        finally:
            jax.profiler.stop_trace()
        info["trace_dir"] = self.trace_dir
        return info
