"""Run provenance: one ``manifest.json`` per run directory.

Every artifact JSON this repo emits (accuracy curves, bench lines,
scalars) used to carry its own ad-hoc provenance blob — or none. The
manifest centralizes it: config hash, JAX/jaxlib versions, device
topology, process layout and backend are captured ONCE at ``fit()``
start, so any consumer holding a run directory can answer "what code
ran, on what hardware, with what config" without re-deriving it.

Stdlib-only at import time; ``jax`` is imported inside
:meth:`RunManifest.capture` so ``summarize`` (a pure file reader) never
pays backend-init cost for it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import sys
import time
from typing import Any, Dict, List, Optional

MANIFEST_SCHEMA_VERSION = 1
MANIFEST_NAME = "manifest.json"


def config_hash(cfg: Any) -> str:
    """Stable short hash of a run configuration.

    Accepts the RunConfig dataclass, any dataclass, or a plain dict;
    hashes the sorted-key JSON form so field order / tuple-vs-list
    differences never change the hash.
    """
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        payload = dataclasses.asdict(cfg)
    elif isinstance(cfg, dict):
        payload = cfg
    else:
        payload = dict(vars(cfg))
    blob = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class RunManifest:
    """Reproducible-provenance record for one run directory."""

    schema: int
    created: str
    created_unix: float
    config_hash: str
    config: Dict[str, Any]
    jax_version: str
    jaxlib_version: str
    backend: str
    device_kind: str
    device_count: int
    local_device_count: int
    process_index: int
    process_count: int
    python: str
    hostname: str
    argv: List[str]

    @classmethod
    def capture(cls, cfg: Any) -> "RunManifest":
        """Snapshot the live process + backend + ``cfg``."""
        import jax

        try:
            import jaxlib

            jaxlib_version = getattr(jaxlib, "__version__", "unknown")
        except Exception:
            jaxlib_version = "unknown"
        dev = jax.devices()[0]
        if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
            cfg_dict = dataclasses.asdict(cfg)
        else:
            cfg_dict = dict(cfg) if isinstance(cfg, dict) else dict(vars(cfg))
        now = time.time()
        return cls(
            schema=MANIFEST_SCHEMA_VERSION,
            created=time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(now)),
            created_unix=round(now, 3),
            config_hash=config_hash(cfg_dict),
            config=cfg_dict,
            jax_version=jax.__version__,
            jaxlib_version=jaxlib_version,
            backend=dev.platform,
            device_kind=dev.device_kind,
            device_count=jax.device_count(),
            local_device_count=jax.local_device_count(),
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            python=sys.version.split()[0],
            hostname=socket.gethostname(),
            argv=list(sys.argv),
        )

    def to_dict(self) -> Dict[str, Any]:
        # round-trip through JSON so tuples in the config become lists —
        # to_dict(capture(cfg)) == read_manifest(dir) byte-for-byte
        return json.loads(json.dumps(dataclasses.asdict(self), default=repr))

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RunManifest":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


def write_manifest(
    log_path: str,
    cfg: Any,
    extra: Optional[Dict[str, Any]] = None,
    write: bool = True,
) -> Dict[str, Any]:
    """Capture + atomically write ``<log_path>/manifest.json``; returns
    the written dict.

    ``write=False`` captures without touching the filesystem — on a
    multi-process (pod) run every host shares ONE run dir, so only
    process 0 writes the manifest (the captured topology fields are
    identical on every host; ``process_index`` is the one per-host
    field and the canonical manifest records process 0's). ``extra``
    carries restart ancestry: ``resumed_from`` / ``restart_lineage``
    plus, for an elastic resume, ``topology_from`` / ``topology_to``
    (the writer's vs this run's process/device layout)."""
    man = RunManifest.capture(cfg).to_dict()
    if extra:
        man.update(extra)
    if not write:
        return man
    os.makedirs(log_path, exist_ok=True)
    path = os.path.join(log_path, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=2, default=repr)
    os.replace(tmp, path)
    return man


def read_manifest(run_dir: str) -> Optional[Dict[str, Any]]:
    """Load ``manifest.json`` from a run dir; None when absent."""
    path = os.path.join(run_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
