"""HBM watermark telemetry.

``device.memory_stats()`` (PJRT allocator counters: ``bytes_in_use``,
``peak_bytes_in_use``, ``bytes_limit``, ...) polled at cheap moments —
after the first-step compile and at epoch boundaries — and emitted as
``memory`` events into the run's ``events.jsonl``. That turns "did this
config fit, and how close to the HBM ceiling did it sail?" into a
post-hoc file question (`summarize` renders peak/limit), instead of a
rerun-under-a-profiler question.

Stdlib-only by the obs-package rule: devices are PASSED IN (the train
loop hands over ``jax.local_devices()``); nothing here imports jax.
Backends without allocator stats (CPU returns ``None``) emit the event
with ``available: false`` so the schema — and the tooling reading it —
is identical everywhere.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

# normalized per-device fields, in emit order
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats(device) -> Optional[Dict[str, int]]:
    """One device's allocator counters, normalized to the three fields
    every consumer needs — or None when the backend has none."""
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out: Dict[str, int] = {}
    for key in _STAT_KEYS:
        v = stats.get(key)
        if v is not None:
            out[key] = int(v)
    # a backend reporting usage but no high-water mark still yields a
    # usable watermark: the poll-time usage is a lower bound
    if "peak_bytes_in_use" not in out and "bytes_in_use" in out:
        out["peak_bytes_in_use"] = out["bytes_in_use"]
    return out or None


def hbm_snapshot(devices: Sequence[Any]) -> List[Dict[str, Any]]:
    """Per-device stat rows for every local device that reports them."""
    rows = []
    for d in devices:
        stats = device_memory_stats(d)
        if stats is None:
            continue
        rows.append({"device": str(getattr(d, "id", d)), **stats})
    return rows


def emit_memory_event(events, phase: str, devices: Sequence[Any], **fields):
    """Poll ``devices`` and append one ``memory`` event.

    Schema: ``{kind: "memory", phase: "post_compile"|"epoch",
    available: bool, devices: [...], peak_bytes, limit_bytes, ...}``.
    ``peak_bytes``/``limit_bytes`` are the max over local devices (the
    binding constraint under data parallelism — every chip holds the
    same replicated state). Never raises past telemetry: a failing
    allocator query must not kill a training run."""
    try:
        rows = hbm_snapshot(devices)
    except Exception:
        rows = []
    peaks = [r["peak_bytes_in_use"] for r in rows if "peak_bytes_in_use" in r]
    limits = [r["bytes_limit"] for r in rows if "bytes_limit" in r]
    return events.emit(
        "memory",
        phase=phase,
        available=bool(rows),
        devices=rows,
        peak_bytes=max(peaks) if peaks else None,
        limit_bytes=max(limits) if limits else None,
        **fields,
    )


def hbm_watermark(memory_events: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Fold a run's ``memory`` events into the summary's HBM section:
    the run-wide peak, the device limit, and their ratio."""
    peaks = [
        e["peak_bytes"] for e in memory_events if e.get("peak_bytes")
    ]
    limits = [
        e["limit_bytes"] for e in memory_events if e.get("limit_bytes")
    ]
    if not peaks:
        return None
    peak = max(peaks)
    limit = max(limits) if limits else None
    out: Dict[str, Any] = {
        "peak_bytes": peak,
        "peak_gib": round(peak / 2**30, 3),
        "limit_bytes": limit,
    }
    if limit:
        out["limit_gib"] = round(limit / 2**30, 3)
        out["utilization"] = round(peak / limit, 4)
    return out
