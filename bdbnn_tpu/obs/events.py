"""``events.jsonl`` — the structured event channel.

One append-only JSONL stream per run directory, shared by the train
loop, validation and ``bench.py``. Where ``scalars.jsonl`` holds
per-epoch (tag, value, step) points for curve plotting, events carry
*structured records at arbitrary granularity* — per-interval step-phase
timing, per-layer probe snapshots, compile time, non-finite incidents —
each stamped with wall-clock time so post-hoc tools (the ``summarize``
subcommand) can reconstruct a run's timeline without having watched it.

Event kinds emitted by ``fit()``:

- ``run_start``   — config hash, epochs, steps_per_epoch
- ``compile``     — first-step trace+compile seconds (epoch 0 step 0)
- ``train_interval`` — per print-interval: loss/top1/img_per_s,
  data_wait/dispatch/drain seconds + shares, per-layer ``flip_rate``
  and ``kurtosis`` dicts, ``grad_norm``
- ``epoch``       — epoch train means + wall seconds
- ``eval``        — per-validation acc1/acc5/loss
- ``nonfinite``   — a drained interval contained non-finite losses
- ``profile``     — a trace capture window closed (epoch, start_step,
  steps, trace_dir) — `summarize` keys its attribution section on it
- ``memory``      — HBM watermark poll (obs/memory.py)
- ``checkpoint``  — a checkpoint committed (epoch-end, step/wallclock
  interval, or preemption), with the schedule state it froze (LR step,
  EDE t/k, kurtosis gate) — the fault-injection tests compare these
  against the resumed run's ``restore`` event bitwise
- ``restore``     — a resume restored state: source dir, integrity
  verdict, ``fallback`` (checkpoint.old used), what was and wasn't
  restored, and the resume-point schedule state
- ``preempt``     — SIGTERM/SIGINT latched and the mid-epoch
  checkpoint landed; the process exits with the preempt code next
- ``data_error``  — a corrupt/undecodable sample was substituted
  (graceful input degradation, data/pipeline.py) instead of killing
  the run
- ``run_end``     — best acc/epoch, total wall seconds

``bench.py`` adds ``bench_result`` records with the same envelope.

New kinds must be registered in :data:`KNOWN_KINDS` —
``tests/test_events_schema.py`` AST-scans every ``.emit(`` call site in
the package against it, and round-trips each kind's payload through a
strict RFC-8259 parser, so an unregistered kind (or one smuggling NaN)
fails CI instead of silently corrupting the channel.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional

EVENTS_NAME = "events.jsonl"

# every event kind any EventWriter.emit call site may use
KNOWN_KINDS = frozenset(
    {
        "run_start",
        "compile",
        "train_interval",
        "epoch",
        "eval",
        "nonfinite",
        "profile",
        "memory",
        "checkpoint",
        "restore",
        "preempt",
        "data_error",
        "run_end",
        "bench_result",
    }
)


def jsonsafe(obj: Any) -> Any:
    """Recursively coerce a payload to strict RFC-8259 values.

    Non-finite floats become None: bare ``NaN`` tokens are invalid JSON
    (jq and most non-Python consumers reject the whole line), and the
    ``nonfinite`` event kind already carries the incident explicitly.
    Non-builtin numeric scalars (``np.float32``/``np.int64``/0-d arrays
    — anything with ``.item()``) are unwrapped to Python numbers:
    ``json.dumps`` would otherwise bounce them to ``default=repr``
    strings. No numpy import — obs stays stdlib."""
    if isinstance(obj, bool):
        return obj
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, (int, str, type(None))):
        return obj
    if isinstance(obj, dict):
        return {k: jsonsafe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonsafe(v) for v in obj]
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            unwrapped = item()
        except Exception:
            return obj
        if type(unwrapped) is not type(obj):  # guard: item() must unwrap
            return jsonsafe(unwrapped)
    return obj


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Tolerant JSONL reader shared by the events and scalars channels:
    blank and malformed lines (a crashed writer's torn tail) are
    skipped, not fatal."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


class EventWriter:
    """Append-only writer for ``<log_path>/events.jsonl``.

    ``emit`` is cheap host work (one json.dumps + buffered write +
    flush) — safe inside the hot loop's drain points, never between
    async dispatches.
    """

    def __init__(self, log_path: str, name: str = EVENTS_NAME) -> None:
        os.makedirs(log_path, exist_ok=True)
        self.path = os.path.join(log_path, name)
        self._f = open(self.path, "a")

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        rec = jsonsafe({"t": round(time.time(), 3), "kind": kind, **fields})
        self._f.write(json.dumps(rec, default=repr) + "\n")
        self._f.flush()
        return rec

    def close(self) -> None:
        """Idempotent: fit() closes on every exit path."""
        if not self._f.closed:
            self._f.close()


def read_events(
    run_dir: str, kind: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Load a run dir's events, optionally filtered by kind."""
    recs = read_jsonl(os.path.join(run_dir, EVENTS_NAME))
    if kind is None:
        return recs
    return [r for r in recs if r.get("kind") == kind]


__all__ = [
    "EVENTS_NAME",
    "KNOWN_KINDS",
    "EventWriter",
    "jsonsafe",
    "read_events",
    "read_jsonl",
]
