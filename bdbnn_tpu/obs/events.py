"""``events.jsonl`` — the structured event channel.

One append-only JSONL stream per run directory, shared by the train
loop, validation and ``bench.py``. Where ``scalars.jsonl`` holds
per-epoch (tag, value, step) points for curve plotting, events carry
*structured records at arbitrary granularity* — per-interval step-phase
timing, per-layer probe snapshots, compile time, non-finite incidents —
each stamped with wall-clock time so post-hoc tools (the ``summarize``
subcommand) can reconstruct a run's timeline without having watched it.

Event kinds emitted by ``fit()``:

- ``run_start``   — config hash, epochs, steps_per_epoch
- ``compile``     — first-step trace+compile seconds (epoch 0 step 0)
- ``train_interval`` — per print-interval: loss/top1/img_per_s,
  data_wait/dispatch/drain seconds + shares, per-layer ``flip_rate``
  and ``kurtosis`` dicts, ``grad_norm``
- ``epoch``       — epoch train means + wall seconds
- ``eval``        — per-validation acc1/acc5/loss + ``count`` (the
  GLOBAL example total after the cross-host psum — on a pod it must
  equal the full val-split size, proving eval is sharded over hosts
  rather than replicated per host)
- ``nonfinite``   — a drained interval contained non-finite losses
- ``profile``     — a trace capture window closed (epoch, start_step,
  steps, trace_dir) — `summarize` keys its attribution section on it
- ``memory``      — HBM watermark poll (obs/memory.py)
- ``checkpoint``  — a checkpoint committed (epoch-end, step/wallclock
  interval, preemption, or forensics), with the schedule state it
  froze (LR step, EDE t/k, kurtosis gate) — the fault-injection tests
  compare these against the resumed run's ``restore`` event bitwise.
  ``coordinated`` records whether the save ran as an aligned
  collective decided by the multi-process step-boundary agreement
  (train/resilience.py); the checkpoint's ``resume.json`` sidecar
  additionally carries the writer's ``topology``
- ``restore``     — a resume restored state: source dir, integrity
  verdict, ``fallback`` (checkpoint.old used), what was and wasn't
  restored, and the resume-point schedule state. Elastic resumes add
  the topology lineage: ``topology_from`` (the checkpoint writer's
  process/device/mesh layout, from its sidecar), ``topology_to`` (the
  restoring run's layout) and ``resharded`` (the reshard disposition:
  True when the layouts differ and the global arrays were re-placed
  onto the current mesh, False for a same-topology resume, null for
  pre-elastic checkpoints that recorded no topology)
- ``preempt``     — a preemption signal was agreed on and the
  mid-epoch checkpoint landed; the process exits with the preempt
  code next. ``coordinated`` is True on multi-process runs (the
  signal landed on ONE host; the step-boundary all-reduce spread it
  so every host saved the same step — ``coordination_step`` — and
  exits 75 together); ``signum`` is the agreed signal number
- ``data_error``  — a corrupt/undecodable sample was substituted
  (graceful input degradation, data/pipeline.py) instead of killing
  the run
- ``alert``       — a health detector fired (obs/health.py): detector
  name, severity (``critical`` = run-ending for ``summarize --strict``
  gating), epoch/step, the observed value vs its threshold, and a
  human message; may be followed by auto-forensics (a ``checkpoint``
  event with reason ``forensics`` + a ``profile`` window)
- ``health``      — run-end health summary: intervals observed, alert
  totals (overall/critical) and per-detector counts, so consumers can
  gate without re-scanning every alert
- ``run_end``     — best acc/epoch, total wall seconds

``bench.py`` adds ``bench_result`` records with the same envelope. The
serving subsystem (``bdbnn_tpu/serve/``) adds four more:

- ``export``      — a training checkpoint was frozen into a serving
  artifact (serve/export.py): artifact path, arch, source checkpoint +
  integrity verdict, binarized-conv count, compression ratio, and the
  checkpoint's recorded eval top-1 the artifact claims to reproduce.
  Appended to the SOURCE run's timeline, so the training→serving
  hand-off is auditable from the run dir alone
- ``serve``       — serving telemetry from ``serve-bench``
  (serve/loadgen.py) and ``serve-http`` (serve/http.py),
  disambiguated by ``phase``: ``start`` (buckets, per-bucket AOT
  warmup seconds, load model), ``stats`` (live queue depth — plus
  ``queue_depth_by_priority`` on serve-http runs — batch occupancy,
  rolling p99, shed/completed counts — what ``watch`` renders for a
  serving run), ``verdict`` (the final SLO verdict: p50/p95/p99 ms,
  throughput, shed rate, drain disposition; v2 verdicts add
  per-priority latency blocks, per-tenant shed rates and the
  max/min fairness ratio — what ``compare`` judges across builds)
- ``http``        — the network front end's lifecycle (serve/http.py),
  disambiguated by ``phase``: ``start`` (bind host/port, priority
  classes, per-class queue bound, scenario), ``ready`` (AOT warmup
  finished — /readyz flipped 200; per-bucket compile seconds),
  ``stats`` (periodic live state: readiness, in-flight count,
  per-priority queue depths / completed / shed counts, per-tenant
  admission counters — the serving heartbeat ``watch`` renders),
  ``drain`` (the SIGTERM latch fired: signum, preempted flag —
  /readyz went 503 while accepted requests finish), ``stop`` (the
  listener closed after the verdict)
- ``admission``   — per-tenant admission control (serve/admission.py):
  ``config`` (the default token-bucket quota and every per-tenant
  override, recorded at startup so a verdict's shed rates can be read
  against the quotas that produced them), ``summary`` (final
  per-tenant admitted / over-quota / queue-shed / completed counters
  at drain — the per-tenant half of the SLO verdict)
- ``replica``     — replica-pool lifecycle + heartbeat (serve/pool.py),
  disambiguated by ``phase``: ``start`` (one per replica at pool
  bring-up: replica id, device, version), ``unhealthy`` (the health
  monitor declared a replica wedged or its worker dead: reason,
  seconds stuck), ``restart`` (the replica was routed around, its
  unstarted work re-dispatched — ``requeued``/``shed`` counts — and a
  fresh worker spawned), ``monitor_error`` (the health loop survived
  an internal error — recorded, never fatal), ``stats`` (periodic live
  table: one row per replica with device / version / state / queue
  depth / completed,
  plus the completed-by-version ledger and the swap state — what
  ``watch`` renders as the per-replica table)
- ``swap``        — blue/green artifact rollout (serve/pool.py),
  disambiguated by ``phase``: ``trigger`` (the swap-under-load
  orchestration fired at a schedule position), ``start``
  (version_from/version_to, replica count; ``canary`` true when the
  rollout runs the canary stage), ``warm`` (one standby runner built +
  AOT-warmed, per replica), ``shift`` (one replica drained its vN work
  and now serves vN+1; ``canary`` true for the canary subset's
  shifts), ``done`` (rollout complete: seconds, replicas shifted),
  ``failed`` (the standby build aborted — vN kept serving; error
  recorded), ``rolled_back`` (the canary stage auto-rolled the rollout
  back: trigger detector, seconds — vN kept serving BY DESIGN, not a
  failure)
- ``canary``      — one canary episode's lifecycle (serve/canary.py
  via serve/pool.py), disambiguated by ``phase``: ``start`` (fraction,
  versions, the canary replica subset, shadow sampling), ``observing``
  (the subset shifted; the observation loop begins: eval interval +
  budget), ``evaluate`` (one monitor tick: the per-detector evidence
  table — value/threshold/breach/fired/eligible per detector — plus
  cohort served counts and the running decision), ``decision`` (the
  episode resolved outside a normal evaluate — budget timeout:
  decision, trigger, reason), ``rollback`` (one canary replica drained
  its vN+1 work and restored vN: which runner — rebuilt via the
  factory or the retained original), ``promote`` (the canary passed;
  the full replica-by-replica shift completed: seconds, evaluations).
  The whole episode also lands as the v5 SLO verdict's nullable
  ``canary`` block, which ``compare`` judges
- ``shadow``      — the shadow-mirroring logit-drift probe
  (serve/pool.py comparator thread), disambiguated by ``phase``:
  ``mirror`` (one sampled incumbent batch was ALSO executed on the
  canary and the logits diffed off the hot path: batch seq, versions,
  ``drift`` = max abs element-wise difference — EXACTLY 0.0 between
  identical artifacts because packed inference is deterministic and
  bitwise-exact; any nonzero drift is a real defect)
- ``fleet``       — the cross-host fleet router's lifecycle
  (serve/fleet.py), disambiguated by ``phase``: ``start`` (router
  bind host/port, the backend host set, scenario), ``ready`` (at
  least one backend host probed ready — dispatch is possible),
  ``probe`` (a host's health state TRANSITIONED:
  state_from/state_to over the warming/ready/draining/dead machine —
  steady-state probes emit nothing), ``proxy`` (one proxy attempt
  against a host failed at the transport layer and the request is
  being retried on a peer: host, cause connect/timeout/reset,
  attempt index), ``pull`` (the fleet swap replicated a version into
  one host registry by digest-verified pull), ``swap`` (the
  host-by-host fleet rollout's trail: ``trigger`` at a schedule
  position, one ``shifted`` per host as its own swap machine lands
  terminal, then ``done``/``failed`` — the serialization is the
  zero-overlap rollout contract), ``stats`` (the periodic per-host
  table: state, occupancy, proxied/completed/relayed counters,
  retries by cause — what ``watch`` renders as the fleet banner —
  plus, when the router traces, the fleet metrics plane: ``rtrace``
  = the router's OWN cross-host trace windows {requests, stitched,
  unstitched, stage_p99_ms over probe_wait/pick/connect/retry_hop/
  network, backend_stage_p99_ms, e2e_p99_ms_by_priority,
  retry_hop_share} and ``host_windows`` = the scraped per-host
  /statsz windows {hosts: {host: {stale, scrapes, failures,
  fail_streak, age_s, stage_p99_ms, e2e_p99_ms_by_priority}},
  merged over FRESH hosts only} — what ``watch`` renders as the
  live fleet waterfall and per-host stage table),
  ``drain`` (the router's SIGTERM latch fired) and ``stop`` (the
  listener closed after the verdict). The final per-host ledgers
  land in the v6 SLO verdict's ``fleet`` block, which ``compare``
  judges
- ``rtrace``      — request-path lifecycle tracing (obs/rtrace.py),
  disambiguated by ``phase``: ``request`` (one SAMPLED request's full
  waterfall — seq, priority, tenant, total_ms, per-stage ms over the
  read/admit/queue/coalesce/dispatch/compute/respond taxonomy;
  deterministic seeded sampling, so the same seed emits the same
  exemplars; a FLEET router's sampled waterfall carries the stitched
  cross-host trace context instead: ``trace`` (the minted 16-hex
  id), ``host``, ``attempts``, router stages over probe_wait/pick/
  connect/retry_hop/network, ``backend_total_ms`` + ``backend``
  (the backend's self-reported stage dict, or null when unstitched)
  and ``slowest_stage``) and ``stats`` (the periodic heartbeat:
  per-stage p99 over the rolling windows, end-to-end p99 per
  priority, the queue-share figure — what ``watch`` renders as the
  live waterfall and ``/statsz`` mirrors). The final per-priority
  decomposition, reconciliation identity and tail-exemplar table
  land in the v4 SLO verdict's ``attribution`` block — or, for the
  fleet router, the v7 ``fleet_attribution`` block — not in events

The recipe-search harness (``bdbnn_tpu/search/``) adds two:

- ``search``      — one sweep's lifecycle (search/harness.py),
  disambiguated by ``phase``: ``start``/``resume`` (trial count,
  families, worker fan-out, the sweep config hash the ledger pins),
  ``preempted`` (a SIGTERM/SIGINT was forwarded to every in-flight
  trial worker, each checkpointed + exited 75, the ledger recorded
  their cursors — the harness exits 75 next; ``completed`` counts the
  trials already done, which ``--resume`` will never re-run) and
  ``verdict`` (the final leaderboard: deterministic ranking by
  best/final top-1, winner, time-to-common-accuracy, per-trial
  status/attempts table — what ``compare`` judges as
  ``search_best_top1``/``search_time_to_common_acc_s`` and
  ``summarize`` renders as the leaderboard section)
- ``trial``       — one trial's transitions (search/harness.py),
  disambiguated by ``phase``: ``start`` (family spec, lr, attempt),
  ``resumed`` (a preempted trial relaunched with ``--resume`` against
  its recorded run dir), ``done`` (best/final top-1 + wall seconds +
  the resolved run dir), ``preempted`` (the forwarded signal landed;
  a mid-epoch checkpoint exists), ``interrupted`` (the signal caught
  the worker before its first checkpoint — the attempt is lost, the
  trial returns to pending, NOT a failure) and ``failed`` (nonzero
  exit that was not a preemption; the worker log has the autopsy)

The static analyzer adds one more:

- ``analysis``    — one ``check`` CLI run's verdict (bdbnn_tpu/
  analysis/ via ``check --events-into RUN_DIR``): checkers run, files
  scanned, open/suppressed finding counts, per-checker counts and the
  open finding records — so ``summarize`` can render the last static-
  analysis verdict alongside a run's telemetry

The performance observatory (obs/roofline.py) adds one:

- ``perf``        — one ``perf`` CLI run's lifecycle, disambiguated
  by ``phase``: ``start`` (artifact, arch, buckets, impls, iters,
  device kind), ``bucket`` (one (impl, bucket) traced timing window:
  wall ms, attributed ms, whether the trace reconciled against the
  wall) and ``verdict`` (the full strict-JSON ``perf_verdict`` —
  per-layer roofline efficiency, bound classes, summary aggregates —
  the same dict the run dir's ``perf_verdict.json`` and the
  append-only ``PERF_LEDGER.jsonl`` persist; what ``compare`` judges
  per-(layer, bucket, impl) and ``watch``/``summarize`` render)

The capacity observatory (obs/capacity.py) adds one:

- ``capacity``    — the capacity & demand plane's lifecycle
  (serve/http.py stats pump), disambiguated by ``phase``: ``stats``
  (one periodic tick: windowed offered rps, in-flight decisions, the
  max per-key shed ratio, the saturation-headroom estimate, the last
  utilization gauges — busy fraction / occupancy / queue share /
  admission headroom — and the per-detector burn-rate table),
  ``breach`` (a per-(priority, objective) error-budget detector fired
  after warmup→debounce with BOTH burn windows over threshold: the
  detector name, fast/slow burn rates, threshold — the breach episode
  opens here) and ``recovered`` (the latched detector's fast window
  dropped back under budget; the episode closes and lands in the
  verdict's ``capacity`` block with its peak burn rate)

New kinds must be registered in :data:`KNOWN_KINDS` — the
``event-schema`` checker (bdbnn_tpu/analysis/eventschema.py, wrapped
as a tier-1 test by ``tests/test_events_schema.py``) AST-scans every
``.emit(`` call site in the package against it, requires every
registered kind to be documented here and to keep a live call site,
and the test round-trips each kind's payload through a strict RFC-8259
parser — so an unregistered kind (or one smuggling NaN) fails CI
instead of silently corrupting the channel.

**Rotation.** ``events.jsonl`` is append-only and a multi-day run's
interval events would otherwise grow it without bound. The writer takes
a size cap (``max_bytes``; fit() wires ``--events-max-mb``): when the
live file crosses it, it is renamed to the next ``events.<N>.jsonl``
segment (``events.1.jsonl`` is the OLDEST) and a fresh ``events.jsonl``
is opened. :func:`load_events` / :func:`read_events` transparently read
the rotated segments in order, so every consumer (``summarize``,
``watch``, ``compare``) sees one continuous timeline.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

EVENTS_NAME = "events.jsonl"

# every event kind any EventWriter.emit call site may use
KNOWN_KINDS = frozenset(
    {
        "run_start",
        "compile",
        "train_interval",
        "epoch",
        "eval",
        "nonfinite",
        "profile",
        "memory",
        "checkpoint",
        "restore",
        "preempt",
        "data_error",
        "alert",
        "health",
        "run_end",
        "bench_result",
        "export",
        "serve",
        "http",
        "admission",
        "replica",
        "swap",
        "fleet",
        "rtrace",
        "canary",
        "shadow",
        "search",
        "trial",
        "analysis",
        "perf",
        "capacity",
    }
)


def jsonsafe(obj: Any) -> Any:
    """Recursively coerce a payload to strict RFC-8259 values.

    Non-finite floats become None: bare ``NaN`` tokens are invalid JSON
    (jq and most non-Python consumers reject the whole line), and the
    ``nonfinite`` event kind already carries the incident explicitly.
    Non-builtin numeric scalars (``np.float32``/``np.int64``/0-d arrays
    — anything with ``.item()``) are unwrapped to Python numbers:
    ``json.dumps`` would otherwise bounce them to ``default=repr``
    strings. No numpy import — obs stays stdlib."""
    if isinstance(obj, bool):
        return obj
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, (int, str, type(None))):
        return obj
    if isinstance(obj, dict):
        return {k: jsonsafe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonsafe(v) for v in obj]
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            unwrapped = item()
        except Exception:
            return obj
        if type(unwrapped) is not type(obj):  # guard: item() must unwrap
            return jsonsafe(unwrapped)
    return obj


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Tolerant JSONL reader shared by the events and scalars channels:
    blank and malformed lines (a crashed writer's torn tail) are
    skipped, not fatal."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def _rotated_segments(path: str) -> List[str]:
    """Existing rotated segments for ``path``, oldest first
    (``events.1.jsonl`` before ``events.2.jsonl`` — numeric order, not
    lexicographic)."""
    base, ext = os.path.splitext(path)
    hits = []
    d = os.path.dirname(path) or "."
    if not os.path.isdir(d):
        return []
    prefix = os.path.basename(base) + "."
    suffix = ext
    for name in os.listdir(d):
        if not (name.startswith(prefix) and name.endswith(suffix)):
            continue
        mid = name[len(prefix):len(name) - len(suffix)] if suffix else (
            name[len(prefix):]
        )
        if mid.isdigit():
            hits.append((int(mid), os.path.join(d, name)))
    return [p for _, p in sorted(hits)]


class EventWriter:
    """Append-only writer for ``<log_path>/events.jsonl``.

    ``emit`` is cheap host work (one json.dumps + buffered write +
    flush) — safe inside the hot loop's drain points, never between
    async dispatches. It is also thread-safe: the serving stack emits
    concurrently from the micro-batcher worker (``on_batch``), the
    serve-http stats pump and the main thread, and interleaved writes
    would tear JSONL lines (silently dropped by the tolerant reader —
    lost telemetry) or let two threads race ``_rotate`` into a closed
    file. One lock around write+flush+rotate closes both.

    ``max_bytes`` > 0 enables size-aware rotation: when the live file
    crosses the cap after a write, it becomes the next ``events.<N>``
    segment and a fresh file is opened — a multi-day run cannot fill
    the disk with one unbounded JSONL. Records are never split across
    segments (rotation happens between emits).
    """

    def __init__(
        self, log_path: str, name: str = EVENTS_NAME,
        max_bytes: int = 0,
    ) -> None:
        os.makedirs(log_path, exist_ok=True)
        self.path = os.path.join(log_path, name)
        self.max_bytes = max(int(max_bytes), 0)
        self._lock = threading.Lock()
        self._f = open(self.path, "a")

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        rec = jsonsafe({"t": round(time.time(), 3), "kind": kind, **fields})
        line = json.dumps(rec, default=repr) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()
            if self.max_bytes and self._f.tell() >= self.max_bytes:
                self._rotate()
        return rec

    def _rotate(self) -> None:
        segments = _rotated_segments(self.path)
        base, ext = os.path.splitext(self.path)
        if segments:
            last = os.path.basename(segments[-1])
            lastbase = os.path.basename(base) + "."
            idx = int(last[len(lastbase):len(last) - len(ext)]) + 1
        else:
            idx = 1
        self._f.close()
        os.replace(self.path, f"{base}.{idx}{ext}")
        self._f = open(self.path, "a")

    def close(self) -> None:
        """Idempotent: fit() closes on every exit path."""
        with self._lock:
            if not self._f.closed:
                self._f.close()


def read_events(
    run_dir: str, kind: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Load a run dir's events — rotated segments (oldest first) plus
    the live file, one continuous timeline — optionally filtered by
    kind."""
    path = os.path.join(run_dir, EVENTS_NAME)
    recs: List[Dict[str, Any]] = []
    for seg in _rotated_segments(path):
        recs += read_jsonl(seg)
    recs += read_jsonl(path)
    if kind is None:
        return recs
    return [r for r in recs if r.get("kind") == kind]


# the rotation-transparent loader under its contract name
load_events = read_events


def serve_digest(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One shared digest of a timeline's serving telemetry — the
    ``export`` events, the ``serve`` phases (``start`` marker, the
    ``stats`` trail, the LAST ``verdict``) and the network front end's
    ``http``/``admission`` trail (serve-http runs). ``summarize``,
    ``watch`` and ``compare`` all consume serving runs through this,
    so a verdict-field change lands in one place instead of three."""
    exports = [e for e in events if e.get("kind") == "export"]
    serves = [e for e in events if e.get("kind") == "serve"]
    https = [e for e in events if e.get("kind") == "http"]
    admissions = [e for e in events if e.get("kind") == "admission"]
    replicas = [e for e in events if e.get("kind") == "replica"]
    swaps = [e for e in events if e.get("kind") == "swap"]
    rtraces = [e for e in events if e.get("kind") == "rtrace"]
    canaries = [e for e in events if e.get("kind") == "canary"]
    shadows = [e for e in events if e.get("kind") == "shadow"]
    fleets = [e for e in events if e.get("kind") == "fleet"]
    capacities = [e for e in events if e.get("kind") == "capacity"]
    return {
        "fleet_start": next(
            (e for e in fleets if e.get("phase") == "start"), None
        ),
        "fleet_stats": next(
            (
                e for e in reversed(fleets)
                if e.get("phase") == "stats"
            ),
            None,
        ),
        "fleet_probes": [
            e for e in fleets if e.get("phase") == "probe"
        ],
        "fleet_drain": next(
            (e for e in reversed(fleets) if e.get("phase") == "drain"),
            None,
        ),
        "canary_events": canaries,
        "canary_last": canaries[-1] if canaries else None,
        "canary_last_evaluate": next(
            (
                e for e in reversed(canaries)
                if e.get("phase") == "evaluate"
            ),
            None,
        ),
        "shadow_mirrors": [
            e for e in shadows if e.get("phase") == "mirror"
        ],
        "rtrace_stats": next(
            (
                e for e in reversed(rtraces)
                if e.get("phase") == "stats"
            ),
            None,
        ),
        "replica_stats": next(
            (
                e for e in reversed(replicas)
                if e.get("phase") == "stats"
            ),
            None,
        ),
        "replica_restarts": [
            e for e in replicas if e.get("phase") == "restart"
        ],
        "swap_events": swaps,
        "swap_last": swaps[-1] if swaps else None,
        "exports": exports,
        "start": next(
            (e for e in serves if e.get("phase") == "start"), None
        ),
        "stats": [e for e in serves if e.get("phase") == "stats"],
        "verdict": next(
            (e for e in reversed(serves) if e.get("phase") == "verdict"),
            None,
        ),
        "http_start": next(
            (e for e in https if e.get("phase") == "start"), None
        ),
        "http_stats": [e for e in https if e.get("phase") == "stats"],
        "http_drain": next(
            (e for e in reversed(https) if e.get("phase") == "drain"),
            None,
        ),
        "admission_config": next(
            (e for e in admissions if e.get("phase") == "config"), None
        ),
        "admission_summary": next(
            (
                e for e in reversed(admissions)
                if e.get("phase") == "summary"
            ),
            None,
        ),
        # the capacity plane (obs/capacity.py): the LAST periodic tick
        # (live headroom/burn gauges), every breach/recovery
        # transition, and the full tick trail (the headroom-over-time
        # timeline the flash-crowd acceptance reads)
        "capacity_stats": next(
            (
                e for e in reversed(capacities)
                if e.get("phase") == "stats"
            ),
            None,
        ),
        "capacity_stats_trail": [
            e for e in capacities if e.get("phase") == "stats"
        ],
        "capacity_breaches": [
            e for e in capacities if e.get("phase") == "breach"
        ],
        "capacity_recoveries": [
            e for e in capacities if e.get("phase") == "recovered"
        ],
    }


__all__ = [
    "EVENTS_NAME",
    "KNOWN_KINDS",
    "EventWriter",
    "jsonsafe",
    "load_events",
    "read_events",
    "read_jsonl",
    "serve_digest",
]
