"""Online training-health monitor — per-drain pathology detectors.

BNN training fails in ways float training doesn't, and the failure
signatures are visible in signals the run already collects for free
(Courbariaux et al., arXiv:1602.02830 document the oscillation/freeze
modes; XNOR-Net, arXiv:1603.05279 the sensitivity to scale/schedule
drift):

- **flip_collapse** (critical) — per-layer sign-flip rate falls to ~0
  long before the schedule ends: the binarized weights froze and the
  remaining epochs are wasted TPU time.
- **flip_explosion** (critical) — a large fraction of binarized weights
  changes sign EVERY step: oscillation under a too-hot LR; the run is
  churning, not converging.
- **kurt_divergence** (warning) — latent-weight kurtosis runs away from
  the configured bimodal target the paper's L_K loss is supposed to
  enforce (only armed when the kurtosis loss is on).
- **loss_spike** (critical) — interval loss jumps a factor over its own
  trailing median (divergence, bad batch, LR cliff).
- **loss_plateau** (warning) — loss flat (relative range below epsilon)
  at a HIGH value in the first half of training. A plateau at ~0 loss
  is convergence, not pathology — ``plateau_min_loss`` gates that out.
- **throughput_regression** (warning) — img/s falls well below the
  run's own trailing baseline (input pipeline degraded, a straggler
  host, thermal throttling).
- **hbm_creep** (warning) — the HBM high-water mark grows past the
  post-compile baseline (fragmentation, eval-shape growth) toward an
  OOM that would otherwise arrive unannounced hours later.

Every detector runs the same state machine: **warmup** (first N
observations are never judged — early training is legitimately noisy),
**debounce** (the breach must persist K consecutive drains before an
alert fires — one weird interval is not a pathology), and
**hysteresis** (after firing, the detector latches until the signal
recovers past a re-arm threshold, so a signal hovering at the limit
emits one alert, not one per drain).

Alerts are ``alert`` events in the run's ``events.jsonl`` and can
trigger **auto-forensics** (wired by the train loop): a checkpoint
snapshot under ``<run_dir>/forensics/`` plus a bounded ``TraceCapture``
window, so the step-level evidence for a pathology is captured at the
moment it happens instead of being unreproducible later. A ``health``
summary event lands at run end; ``summarize --strict`` turns run-ending
(critical) alerts into a nonzero exit for CI.

Stdlib-only (obs-package rule): the monitor consumes already-drained
host floats; it must be importable by ``summarize``/``watch`` without
a JAX backend.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence

# detector name -> severity. "critical" alerts are RUN-ENDING for
# gating purposes: `summarize --strict` exits nonzero on them.
SEVERITIES: Dict[str, str] = {
    "flip_collapse": "critical",
    "flip_explosion": "critical",
    "kurt_divergence": "warning",
    "loss_spike": "critical",
    "loss_plateau": "warning",
    "throughput_regression": "warning",
    "hbm_creep": "warning",
}
DETECTORS = tuple(SEVERITIES)
RUN_ENDING_SEVERITY = "critical"


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Detector thresholds + shared warmup/debounce. Every field can be
    overridden from the CLI via ``--health-threshold NAME=VALUE``."""

    # shared state-machine knobs (flip/kurt detectors; the windowed
    # detectors gate on their own history length instead of warmup).
    # Warmup 10 drains: the first moments of a binary net are
    # legitimately weird (zero flips right after init on small layers,
    # kurtosis still near gaussian), and smoke-scale runs should end
    # before eligibility rather than alert on being small.
    warmup_intervals: int = 10
    debounce: int = 2
    # flip_collapse: mean per-step flip fraction below this while less
    # than flip_collapse_progress of the epoch budget has run
    flip_collapse_rate: float = 1e-5
    flip_collapse_progress: float = 0.9
    # flip_explosion: mean per-step flip fraction above this
    flip_explosion_rate: float = 0.25
    # kurt_divergence: |mean kurtosis - target| above this (armed only
    # when the kurtosis loss is configured)
    kurt_divergence_abs: float = 6.0
    # loss_spike: interval loss > factor x trailing median of the last
    # loss_window interval losses (needs >= 4 history)
    loss_spike_factor: float = 3.0
    loss_window: int = 8
    # loss_plateau: relative range of the last plateau_window interval
    # losses below this, before plateau_progress of training, at a mean
    # loss above plateau_min_loss (a plateau at ~0 is convergence)
    plateau_rel_range: float = 1e-3
    plateau_window: int = 6
    plateau_progress: float = 0.5
    plateau_min_loss: float = 0.05
    # throughput_regression: img/s below (1 - drop) x the trailing
    # median of the last throughput_window intervals
    throughput_drop: float = 0.3
    throughput_window: int = 8
    # hbm_creep: peak_bytes above (1 + frac) x the first watermark
    hbm_creep_frac: float = 0.08


def apply_overrides(
    cfg: HealthConfig, specs: Sequence[str]
) -> HealthConfig:
    """``("loss_spike_factor=5", ...)`` -> a new HealthConfig. Unknown
    names and unparseable values raise ValueError at config time, not
    at the first drain hours into a run."""
    if not specs:
        return cfg
    fields = {f.name: f for f in dataclasses.fields(HealthConfig)}
    updates: Dict[str, Any] = {}
    for spec in specs:
        name, sep, raw = spec.partition("=")
        name = name.strip()
        if not sep or name not in fields:
            raise ValueError(
                f"bad --health-threshold {spec!r}: want NAME=VALUE with "
                f"NAME one of {sorted(fields)}"
            )
        typ = fields[name].type
        try:
            updates[name] = (
                int(raw) if typ in (int, "int") else float(raw)
            )
        except ValueError as e:
            raise ValueError(
                f"bad --health-threshold {spec!r}: {e}"
            ) from None
    return dataclasses.replace(cfg, **updates)


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _finite_mean(d: Optional[Dict[str, Any]]) -> Optional[float]:
    """Mean over a per-layer dict's finite values; None when empty."""
    vals = [
        float(v)
        for v in (d or {}).values()
        if isinstance(v, (int, float)) and math.isfinite(float(v))
    ]
    return sum(vals) / len(vals) if vals else None


class _DetectorState:
    """The warmup + debounce + hysteresis state machine one detector
    runs per drain. ``update`` returns True exactly when an alert
    should fire.

    Exported as :data:`DetectorState`: the serving-side canary monitor
    (serve/canary.py) runs the SAME discipline over live request
    windows — one state machine, two consumers, so the semantics of
    "a breach must persist, then latch" can never drift between the
    training and serving health stacks."""

    __slots__ = ("warmup", "debounce", "seen", "streak", "latched", "fired")

    def __init__(self, warmup: int, debounce: int) -> None:
        self.warmup = max(warmup, 0)
        self.debounce = max(debounce, 1)
        self.seen = 0
        self.streak = 0
        self.latched = False  # hysteresis: fired, waiting for recovery
        self.fired = 0

    def update(self, breach: bool, recovered: bool = False) -> bool:
        self.seen += 1
        if self.seen <= self.warmup:
            return False
        if self.latched:
            if recovered:
                self.latched = False
                self.streak = 0
            return False
        if not breach:
            self.streak = 0
            return False
        self.streak += 1
        if self.streak < self.debounce:
            return False
        self.fired += 1
        self.latched = True
        self.streak = 0
        return True


# the public name serving (serve/canary.py) builds its detectors on
DetectorState = _DetectorState


class HealthMonitor:
    """Evaluates every detector at the drain points the loop already
    has. ``observe_interval`` consumes the same host floats the
    ``train_interval`` event carries; ``observe_memory`` consumes the
    emitted ``memory`` records. Both emit ``alert`` events and return
    the alerts fired, so the caller can trigger auto-forensics with the
    live train state in hand."""

    def __init__(
        self,
        cfg: HealthConfig,
        events,
        *,
        epochs: int,
        kurt_target: Optional[float] = None,
    ) -> None:
        self.cfg = cfg
        self.events = events
        self.epochs = max(epochs, 1)
        self.kurt_target = kurt_target  # None = kurtosis loss off
        self.intervals = 0
        self.alerts: List[Dict[str, Any]] = []
        w, d = cfg.warmup_intervals, cfg.debounce
        self._states = {
            "flip_collapse": _DetectorState(w, d),
            "flip_explosion": _DetectorState(w, d),
            "kurt_divergence": _DetectorState(w, d),
            # a spike is instantaneous — debounce 1; history gates warmup
            "loss_spike": _DetectorState(0, 1),
            "loss_plateau": _DetectorState(0, 1),
            "throughput_regression": _DetectorState(0, d),
            "hbm_creep": _DetectorState(0, 1),
        }
        self._loss_hist: List[float] = []
        self._rate_hist: List[float] = []
        self._hbm_baseline: Optional[int] = None

    # ------------------------------------------------------------------
    def _fire(
        self, detector: str, *, epoch: int, step: int, value: float,
        threshold: float, message: str,
    ) -> Dict[str, Any]:
        rec = self.events.emit(
            "alert",
            detector=detector,
            severity=SEVERITIES[detector],
            epoch=epoch,
            step=step,
            value=value,
            threshold=threshold,
            message=message,
        )
        self.alerts.append(rec)
        return rec

    def observe_interval(
        self,
        *,
        epoch: int,
        step: int,
        loss: Optional[float],
        img_per_s: Optional[float],
        flip_rate: Optional[Dict[str, float]] = None,
        kurtosis: Optional[Dict[str, float]] = None,
    ) -> List[Dict[str, Any]]:
        """One drained print interval. Returns the alerts fired."""
        cfg = self.cfg
        self.intervals += 1
        fired: List[Dict[str, Any]] = []
        progress = epoch / self.epochs

        mean_flip = _finite_mean(flip_rate)
        if mean_flip is not None:
            st = self._states["flip_collapse"]
            breach = (
                mean_flip < cfg.flip_collapse_rate
                and progress < cfg.flip_collapse_progress
            )
            if st.update(breach, mean_flip > 2 * cfg.flip_collapse_rate):
                fired.append(self._fire(
                    "flip_collapse", epoch=epoch, step=step,
                    value=mean_flip, threshold=cfg.flip_collapse_rate,
                    message=(
                        f"mean sign-flip rate {mean_flip:.3g}/step < "
                        f"{cfg.flip_collapse_rate:.3g} at {progress:.0%} "
                        "of the epoch budget — binarized weights look "
                        "frozen"
                    ),
                ))
            st = self._states["flip_explosion"]
            if st.update(
                mean_flip > cfg.flip_explosion_rate,
                mean_flip < 0.5 * cfg.flip_explosion_rate,
            ):
                fired.append(self._fire(
                    "flip_explosion", epoch=epoch, step=step,
                    value=mean_flip, threshold=cfg.flip_explosion_rate,
                    message=(
                        f"mean sign-flip rate {mean_flip:.3g}/step > "
                        f"{cfg.flip_explosion_rate:.3g} — binarized "
                        "weights oscillating (LR too hot?)"
                    ),
                ))

        mean_kurt = _finite_mean(kurtosis)
        if self.kurt_target is not None and mean_kurt is not None:
            dist = abs(mean_kurt - self.kurt_target)
            st = self._states["kurt_divergence"]
            if st.update(
                dist > cfg.kurt_divergence_abs,
                dist < 0.8 * cfg.kurt_divergence_abs,
            ):
                fired.append(self._fire(
                    "kurt_divergence", epoch=epoch, step=step,
                    value=mean_kurt, threshold=cfg.kurt_divergence_abs,
                    message=(
                        f"mean latent kurtosis {mean_kurt:.3g} is "
                        f"{dist:.3g} from the target "
                        f"{self.kurt_target:g} (tolerance "
                        f"{cfg.kurt_divergence_abs:g}) — the bimodal "
                        "shape L_K enforces is not holding"
                    ),
                ))

        if loss is not None and math.isfinite(loss):
            hist = self._loss_hist
            if len(hist) >= 4:  # trailing median EXCLUDES this interval
                med = _median(hist[-cfg.loss_window:])
                st = self._states["loss_spike"]
                if med > 0 and st.update(
                    loss > cfg.loss_spike_factor * med,
                    loss < 1.5 * med,
                ):
                    fired.append(self._fire(
                        "loss_spike", epoch=epoch, step=step,
                        value=loss, threshold=cfg.loss_spike_factor * med,
                        message=(
                            f"interval loss {loss:.4g} > "
                            f"{cfg.loss_spike_factor:g}x the trailing "
                            f"median {med:.4g}"
                        ),
                    ))
            hist.append(loss)
            if len(hist) >= cfg.plateau_window:
                win = hist[-cfg.plateau_window:]
                mean = sum(win) / len(win)
                rel = (max(win) - min(win)) / max(abs(mean), 1e-9)
                st = self._states["loss_plateau"]
                if st.update(
                    rel < cfg.plateau_rel_range
                    and progress < cfg.plateau_progress
                    and mean > cfg.plateau_min_loss,
                    rel > 2 * cfg.plateau_rel_range,
                ):
                    fired.append(self._fire(
                        "loss_plateau", epoch=epoch, step=step,
                        value=mean, threshold=cfg.plateau_rel_range,
                        message=(
                            f"loss flat (relative range {rel:.2e} over "
                            f"{cfg.plateau_window} intervals) at "
                            f"{mean:.4g}, before "
                            f"{cfg.plateau_progress:.0%} of training"
                        ),
                    ))

        if img_per_s is not None and img_per_s > 0:
            rates = self._rate_hist
            if len(rates) >= cfg.throughput_window:
                med = _median(rates[-cfg.throughput_window:])
                st = self._states["throughput_regression"]
                floor = (1.0 - cfg.throughput_drop) * med
                if st.update(
                    img_per_s < floor,
                    img_per_s > (1.0 - 0.5 * cfg.throughput_drop) * med,
                ):
                    fired.append(self._fire(
                        "throughput_regression", epoch=epoch, step=step,
                        value=img_per_s, threshold=floor,
                        message=(
                            f"{img_per_s:.1f} img/s < "
                            f"{1 - cfg.throughput_drop:.0%} of this "
                            f"run's trailing median {med:.1f} img/s"
                        ),
                    ))
            rates.append(img_per_s)

        return fired

    def observe_memory(self, record: Dict[str, Any]) -> List[Dict[str, Any]]:
        """One emitted ``memory`` event record. The first watermark
        (post-compile) is the baseline; growth past it alerts once."""
        peak = record.get("peak_bytes")
        if not peak:
            return []
        if self._hbm_baseline is None:
            self._hbm_baseline = int(peak)
            return []
        cfg = self.cfg
        ceiling = (1.0 + cfg.hbm_creep_frac) * self._hbm_baseline
        st = self._states["hbm_creep"]
        if st.update(peak > ceiling):  # latched for the rest of the run
            return [self._fire(
                "hbm_creep",
                epoch=int(record.get("epoch") or 0),
                step=0,
                value=float(peak),
                threshold=ceiling,
                message=(
                    f"HBM peak {peak / 2**30:.2f} GiB > "
                    f"{1 + cfg.hbm_creep_frac:.2f}x the post-compile "
                    f"baseline {self._hbm_baseline / 2**30:.2f} GiB"
                ),
            )]
        return []

    # ------------------------------------------------------------------
    def counts(self) -> Dict[str, int]:
        return {
            name: st.fired
            for name, st in self._states.items()
            if st.fired
        }

    def emit_summary(self) -> Dict[str, Any]:
        """The run-end ``health`` event: totals by detector + severity,
        so `summarize`/CI can gate without re-scanning every alert."""
        critical = sum(
            1 for a in self.alerts
            if a.get("severity") == RUN_ENDING_SEVERITY
        )
        return self.events.emit(
            "health",
            intervals=self.intervals,
            alerts_total=len(self.alerts),
            alerts_critical=critical,
            by_detector=self.counts(),
        )


__all__ = [
    "DETECTORS",
    "RUN_ENDING_SEVERITY",
    "SEVERITIES",
    "DetectorState",
    "HealthConfig",
    "HealthMonitor",
    "apply_overrides",
]
