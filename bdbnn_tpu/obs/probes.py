"""On-device binarization health probes.

Binary-network training fails in ways epoch-mean loss curves can't
show: latent weight distributions that never go bimodal despite the
kurtosis regularizer, and sign-flip churn — binarized weights
oscillating across zero step after step — that stalls convergence (the
instability XNOR-Net and the original BNN paper mitigate with scale
factors and STE clipping; PAPERS.md arXiv:1603.05279, 1602.02830).

The probes here are pure ``jnp`` expressions evaluated INSIDE the
already-jitted train step and accumulated by the existing
``DeviceMetrics`` sums, so they cost zero extra host syncs:

- ``flips/<layer>``  — count of latent weights whose sign changed in
  this optimizer update. Summed over a print interval and divided by
  (layer size × interval steps) on the host, it is the per-step
  fraction of binarized weights that flipped ("flip rate").
- ``kurt/<layer>``   — Bessel-corrected kurtosis of the layer's latent
  weights after the update (same estimator as the training loss,
  ``losses/kurtosis.py``). Interval mean ≈ how bimodal the layer
  actually is vs its target.
- ``nonfinite``      — 1 when the step's total loss is not finite.
  Drained at interval granularity and fed to the configurable
  fail-fast policy (a NaN epoch previously poisoned best-acc tracking
  silently).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from bdbnn_tpu.losses.kurtosis import kurtosis
from bdbnn_tpu.models.resnet import get_by_path

FLIP_PREFIX = "flips/"
KURT_PREFIX = "kurt/"


class NonFiniteLossError(RuntimeError):
    """Raised (policy 'raise') when a drained interval contained
    non-finite train losses."""


def probe_metrics(
    old_params,
    new_params,
    probe_paths: Sequence[Tuple[str, ...]],
    probe_names: Sequence[str],
) -> Dict[str, jax.Array]:
    """Per-hooked-layer sign-flip counts + kurtosis, as DeviceMetrics-
    summable scalars. Traced into the jitted step; adds no host work."""
    out: Dict[str, jax.Array] = {}
    for path, name in zip(probe_paths, probe_names):
        w_old = get_by_path(old_params, path)
        w_new = get_by_path(new_params, path)
        out[FLIP_PREFIX + name] = jnp.sum(
            (jnp.sign(w_old) != jnp.sign(w_new)).astype(jnp.float32)
        )
        out[KURT_PREFIX + name] = kurtosis(w_new)
    return out


def nonfinite_flag(loss: jax.Array) -> jax.Array:
    """1 iff the step's loss is NaN/Inf (int32, DeviceMetrics-summable)."""
    return jnp.logical_not(jnp.isfinite(loss)).astype(jnp.int32)


def drain_probe_report(
    sums: Dict[str, float],
    probe_sizes: Dict[str, int],
    interval_steps: int,
) -> Tuple[Dict[str, float], Dict[str, float]]:
    """Host-side: turn drained probe SUMS into per-layer per-step flip
    rates and interval-mean kurtosis."""
    steps = max(interval_steps, 1)
    flip_rate = {}
    kurt = {}
    for name, size in probe_sizes.items():
        f = sums.get(FLIP_PREFIX + name)
        if f is not None:
            flip_rate[name] = f / (max(size, 1) * steps)
        k = sums.get(KURT_PREFIX + name)
        if k is not None:
            kurt[name] = k / steps
    return flip_rate, kurt
