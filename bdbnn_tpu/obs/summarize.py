"""Post-hoc run reports: ``python -m bdbnn_tpu.cli summarize <run_dir>``.

Consumes the three files a run directory accumulates —
``manifest.json`` (provenance), ``scalars.jsonl`` (per-epoch curves),
``events.jsonl`` (structured timeline) — and renders what a human
debugging a finished BNN run actually asks:

- was the run input-starved or compute-bound? (host step-phase shares)
- did the gradient signal survive the EDE anneal, or starve?
  (grad-norm trajectory — schedule-budget vs starvation, VERDICT r5)
- did the latent weights actually go bimodal? (per-layer kurtosis)
- did binarized weights churn? (per-layer sign-flip rates)
- how long to each accuracy level, and what did each loss term do?
- where did device time actually go, and how close to the HBM ceiling?
  (the "attribution" section — rendered whenever the run captured a
  ``--profile-at`` trace window and/or ``memory`` events: per-semantic-
  category device ms/step from the span-annotated trace, an MFU
  estimate, and the run-wide HBM peak against the device limit)
- is the run preemption-safe, and what is its restart history? (the
  "resilience" section: checkpoint cadence + age of the last
  checkpoint, restart lineage from the manifest, restore provenance
  incl. ``checkpoint.old`` fallbacks, preemptions, substituted
  corrupt samples)
- did the online health monitor fire? (the "health" section: alert
  counts by detector, the run-ending/critical alerts ``--strict``
  turns into a nonzero exit for CI, the run-end ``health`` roll-up)

Stdlib-only: summarizing a run must never initialize a JAX backend.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional, Tuple

from bdbnn_tpu.obs.events import jsonsafe, read_events, read_jsonl
from bdbnn_tpu.obs.manifest import read_manifest
from bdbnn_tpu.obs.memory import hbm_watermark
from bdbnn_tpu.obs.trace import (
    BF16_PEAK_TFLOPS,
    attribute_trace,
    find_trace_file,
)

# data-wait share of interval wall time above which a run is called
# input-bound: at 35% the host spends over a third of each interval
# blocked on the pipeline — batch-size/worker tuning, not compute, is
# the lever
INPUT_BOUND_SHARE = 0.35
# grad-norm collapse factor for the starvation flag: final epoch below
# 5% of the run's peak means the estimator passes almost no gradient
GRAD_COLLAPSE_RATIO = 0.05


def resolve_run_dir(path: str) -> str:
    """Accept either a run dir itself or a log root above it; pick the
    LATEST dir holding run files (timestamp-named dirs sort
    lexicographically, matching run_kd.py's convention)."""
    for name in ("events.jsonl", "scalars.jsonl", "manifest.json"):
        if os.path.exists(os.path.join(path, name)):
            return path
    hits: List[str] = []
    for name in ("events.jsonl", "scalars.jsonl", "manifest.json"):
        hits += glob.glob(os.path.join(path, "**", name), recursive=True)
    if not hits:
        raise FileNotFoundError(
            f"no run files (manifest.json / scalars.jsonl / events.jsonl) "
            f"under {path!r}"
        )
    return os.path.dirname(sorted(hits)[-1])


def read_scalars(run_dir: str) -> List[Dict[str, Any]]:
    return read_jsonl(os.path.join(run_dir, "scalars.jsonl"))


def _curve(scalars, tag) -> List[Tuple[int, float]]:
    pts = [(s["step"], s["value"]) for s in scalars if s.get("tag") == tag]
    return sorted(pts)


def _phase_totals(intervals) -> Dict[str, float]:
    tot = {"data_wait_s": 0.0, "dispatch_s": 0.0, "drain_s": 0.0,
           "interval_s": 0.0}
    for ev in intervals:
        for k in tot:
            tot[k] += float(ev.get(k, 0.0))
    tot = {k: round(v, 3) for k, v in tot.items()}
    wall = max(tot["interval_s"], 1e-9)
    tot["data_wait_share"] = round(tot["data_wait_s"] / wall, 4)
    tot["drain_share"] = round(tot["drain_s"] / wall, 4)
    return tot


def _starvation(phases, grad_curve) -> Dict[str, Any]:
    """The two starvations a BNN run stalls on, separated.

    Input starvation is a host-time fact (data-wait share); gradient
    starvation is a grad-norm-trajectory fact (the annealed EDE
    backward → 0 a.e.). Each gets its own flag plus one combined
    human-readable verdict line.
    """
    share = phases.get("data_wait_share", 0.0) if phases else 0.0
    input_bound = bool(phases) and share > INPUT_BOUND_SHARE
    grad_first = grad_curve[0][1] if grad_curve else None
    grad_last = grad_curve[-1][1] if grad_curve else None
    grad_peak = max(v for _, v in grad_curve) if grad_curve else None
    grad_starved = bool(
        grad_curve
        and len(grad_curve) >= 2
        and grad_peak > 0
        and grad_last < GRAD_COLLAPSE_RATIO * grad_peak
    )
    if input_bound:
        verdict = (
            f"INPUT-BOUND: {share:.0%} of hot-loop wall time waiting on "
            "the input pipeline — tune workers/backend before blaming "
            "compute"
        )
    elif grad_starved:
        verdict = (
            f"GRADIENT STARVATION suspected: epoch-mean grad norm fell "
            f"to {grad_last:.3g} from a peak of {grad_peak:.3g} "
            f"(<{GRAD_COLLAPSE_RATIO:.0%}) — the estimator anneal, not "
            "the schedule budget, is the limiter"
        )
    elif not phases and not grad_curve:
        verdict = "no verdict: run recorded neither phase timing nor grad norms"
    else:
        verdict = (
            f"not starved: data-wait share {share:.0%}"
            + (
                f", grad norm {grad_first:.3g} -> {grad_last:.3g}"
                if grad_curve
                else ", grad norm not recorded"
            )
        )
    return {
        "input_bound": input_bound,
        "data_wait_share": share,
        "grad_norm_first": grad_first,
        "grad_norm_last": grad_last,
        "grad_norm_peak": grad_peak,
        "gradient_starvation_suspected": grad_starved,
        "verdict": verdict,
    }


def _probe_trajectories(scalars, events) -> Dict[str, Dict[str, Any]]:
    """Per-layer first->last flip-rate / kurtosis. Prefers the per-epoch
    scalars (written by the train loop); falls back to the per-interval
    events of runs that died before epoch end."""
    out: Dict[str, Dict[str, Any]] = {}
    for s in scalars:
        tag = s.get("tag", "")
        for prefix, key in (("Probe flip ", "flip_rate"),
                            ("Probe kurt ", "kurtosis")):
            if tag.startswith(prefix):
                layer = tag[len(prefix):]
                d = out.setdefault(layer, {})
                d.setdefault(f"{key}_curve", []).append(
                    (s["step"], s["value"])
                )
    if not out:
        intervals = [e for e in events if e.get("kind") == "train_interval"]
        for ev in intervals:
            for field, key in (("flip_rate", "flip_rate"),
                               ("kurtosis", "kurtosis")):
                for layer, v in (ev.get(field) or {}).items():
                    # a NaN probe value lands as null in the event
                    # (jsonsafe); skip it rather than crash the report
                    # of exactly the broken run being post-mortemed
                    if v is None:
                        continue
                    d = out.setdefault(layer, {})
                    # step resets every epoch — key on (epoch, step) so
                    # first/last stay chronological across epochs
                    d.setdefault(f"{key}_curve", []).append(
                        ((ev.get("epoch", 0), ev.get("step", 0)), v)
                    )
    for layer, d in out.items():
        for key in ("flip_rate", "kurtosis"):
            curve = sorted(d.pop(f"{key}_curve", []))
            if curve:
                d[f"{key}_first"] = round(curve[0][1], 6)
                d[f"{key}_last"] = round(curve[-1][1], 6)
    return out


def _attribution(run_dir, manifest, events) -> Optional[Dict[str, Any]]:
    """The device-time + HBM section, present whenever the run captured
    a trace window (``profile`` event) or memory watermarks (``memory``
    events).

    Per-category ms/step comes from parsing the newest trace file under
    the run dir with the semantic-span parser; MFU pairs the trace's
    step total with the profile event's FLOPs (when recorded) or the
    trace's own per-op flops metadata, against the manifest device
    kind's published bf16 peak."""
    profile_evs = [e for e in events if e.get("kind") == "profile"]
    memory_evs = [e for e in events if e.get("kind") == "memory"]
    if not profile_evs and not memory_evs:
        return None
    out: Dict[str, Any] = {}
    if profile_evs:
        pe = profile_evs[-1]
        out["captured"] = {
            k: pe.get(k) for k in ("epoch", "start_step", "steps")
        }
        peak = None
        if manifest:
            peak = BF16_PEAK_TFLOPS.get(manifest.get("device_kind", ""))
        trace_path = None
        # the trace lives under the run dir (--profile-at default) or
        # wherever the profile event says the window was written
        for root in (run_dir, pe.get("trace_dir") or ""):
            if root and os.path.isdir(root):
                trace_path = find_trace_file(root)
                if trace_path:
                    break
        if trace_path:
            att = attribute_trace(
                trace_path,
                pe.get("steps") or 1,
                flops_per_step=pe.get("flops_per_step"),
                peak_tflops=peak,
            )
            out.update(att)
            out["trace_file"] = trace_path
        else:
            out["trace_file"] = None
    if memory_evs:
        out["hbm"] = hbm_watermark(memory_evs)
    return out


def _health(events) -> Optional[Dict[str, Any]]:
    """The health-monitor section: alert counts by detector/severity,
    the run-ending (critical) alerts `summarize --strict` gates on,
    and the run-end ``health`` roll-up when one landed. None when the
    run recorded no health telemetry at all."""
    alerts = [e for e in events if e.get("kind") == "alert"]
    roll = next(
        (e for e in reversed(events) if e.get("kind") == "health"), None
    )
    if not alerts and roll is None:
        return None
    by_detector: Dict[str, int] = {}
    for a in alerts:
        det = str(a.get("detector", "?"))
        by_detector[det] = by_detector.get(det, 0) + 1
    critical = [
        {
            k: a.get(k)
            for k in ("detector", "epoch", "step", "value", "threshold",
                      "message")
        }
        for a in alerts
        if a.get("severity") == "critical"
    ]
    return {
        "alerts_total": len(alerts),
        "alerts_critical": len(critical),
        "by_detector": dict(sorted(by_detector.items())),
        "critical": critical,
        "summary_event": (
            {
                k: roll.get(k)
                for k in ("intervals", "alerts_total", "alerts_critical",
                          "by_detector")
            }
            if roll
            else None
        ),
    }


def _serving(events) -> Optional[Dict[str, Any]]:
    """The serving section: ``export`` hand-offs recorded on a training
    run's timeline, and/or a ``serve-bench`` run's own start/stats/
    verdict trail. None when the run has no serving telemetry."""
    from bdbnn_tpu.obs.events import serve_digest

    digest = serve_digest(events)
    exports = digest["exports"]
    start = digest["start"]
    stats = digest["stats"]
    verdict = digest["verdict"]
    http_start = digest["http_start"]
    fleet_start = digest["fleet_start"]
    if (
        not exports and start is None and not stats and verdict is None
        and http_start is None and fleet_start is None
    ):
        return None
    return {
        "exports": [
            {
                k: e.get(k)
                for k in ("artifact", "arch", "checkpoint", "integrity",
                          "binarized_convs", "compression_ratio",
                          "checkpoint_acc1")
            }
            for e in exports
        ],
        "bench": (
            {
                k: start.get(k)
                for k in ("artifact", "arch", "mode", "rate_rps",
                          "requests", "buckets", "queue_depth",
                          "max_delay_ms", "warmup_compile_s")
            }
            if start
            else None
        ),
        "http": (
            {
                k: http_start.get(k)
                for k in ("host", "port", "arch", "priorities",
                          "queue_depth", "buckets", "scenario",
                          "rate_rps", "requests")
            }
            if http_start
            else None
        ),
        "admission": (
            {
                "tenants": (
                    digest["admission_summary"].get("tenants") or {}
                ),
            }
            if digest["admission_summary"]
            else None
        ),
        "stats_events": len(stats) + len(digest["http_stats"]),
        "verdict": (
            {
                k: verdict.get(k)
                for k in ("p50_ms", "p95_ms", "p99_ms", "throughput_rps",
                          "mean_batch_occupancy", "shed_rate",
                          "requests_submitted", "requests_completed",
                          "requests_shed", "max_queue_depth_seen",
                          "max_queue", "preempted", "drained_clean",
                          "wall_s", "scenario", "per_priority",
                          "per_tenant", "fairness_ratio", "slo",
                          "replicas", "scaling", "swap", "attribution",
                          "canary", "fleet", "fleet_attribution",
                          "capacity")
            }
            if verdict
            else None
        ),
        "fleet": (
            {
                "hosts": fleet_start.get("hosts"),
                "router": (
                    f"{fleet_start.get('host')}:"
                    f"{fleet_start.get('port')}"
                ),
                "probe_transitions": len(digest["fleet_probes"]),
            }
            if fleet_start
            else None
        ),
        "replica_restarts": len(digest["replica_restarts"]),
        "canary_events": len(digest["canary_events"]),
        "shadow_mirrors": len(digest["shadow_mirrors"]),
        "capacity_breaches": len(digest["capacity_breaches"]),
        "capacity_recoveries": len(digest["capacity_recoveries"]),
    }


def _search(events) -> Optional[Dict[str, Any]]:
    """The recipe-search section (bdbnn_tpu/search/): the sweep's
    leaderboard verdict when one landed, otherwise the live trial
    states, plus the resumed-trial lineage (which trials survived a
    preemption and how many attempts they took). None when the
    timeline carries no search telemetry."""
    from bdbnn_tpu.search.harness import search_digest

    digest = search_digest(events)
    if digest["start"] is None and digest["verdict"] is None:
        return None
    verdict = digest["verdict"]
    start = digest["start"] or {}
    out: Dict[str, Any] = {
        "trials_total": (verdict or start).get("trials_total"),
        "families": start.get("families"),
        "workers": start.get("workers"),
        "preempted": digest["preempted"] is not None,
    }
    if verdict is not None:
        trials = verdict.get("trials") or {}
        out.update({
            "completed": verdict.get("completed"),
            "failed": verdict.get("failed"),
            "common_acc_level": verdict.get("common_acc_level"),
            "ranking": verdict.get("ranking"),
            "winner": verdict.get("winner"),
            "trials": trials,
            # resumed-trial lineage: the trials that crossed a
            # preemption (attempts > 1) — the evidence `--resume`
            # continued rather than restarted the sweep
            "resumed_trials": {
                tid: {
                    "attempts": t.get("attempts"),
                    "status": t.get("status"),
                }
                for tid, t in sorted(trials.items())
                if t.get("resumed")
            },
        })
    else:
        states: Dict[str, str] = {}
        for tid, ev in sorted(digest["trial_latest"].items()):
            states[tid] = ev.get("phase")
        out["trial_states"] = states
        best = digest["best_done"]
        out["best_so_far"] = (
            {
                "trial": best.get("trial"),
                "family": best.get("family"),
                "lr": best.get("lr"),
                "best_top1": best.get("best_top1"),
            }
            if best
            else None
        )
    return out


def _perf(events) -> Optional[Dict[str, Any]]:
    """The performance-observatory section (obs/roofline.py): the
    sweep header from the ``perf`` start event, the measured
    (impl, bucket) cells that landed, and the roofline verdict's
    summary/ceilings/skips when one landed. None when the timeline
    carries no perf telemetry."""
    perf = [e for e in events if e.get("kind") == "perf"]
    if not perf:
        return None
    start = next((e for e in perf if e.get("phase") == "start"), None)
    verdict_ev = next(
        (e for e in reversed(perf) if e.get("phase") == "verdict"), None
    )
    cells = [
        {
            k: e.get(k)
            for k in ("impl", "bucket", "wall_ms", "attributed_ms",
                      "reconciled")
        }
        for e in perf
        if e.get("phase") == "bucket"
    ]
    out: Dict[str, Any] = {
        "start": (
            {
                k: start.get(k)
                for k in ("artifact", "arch", "dataset", "device_kind",
                          "buckets", "impls", "iters")
            }
            if start
            else None
        ),
        "cells": cells,
        "verdict": None,
    }
    if verdict_ev is not None:
        v = verdict_ev.get("verdict") or {}
        out["verdict"] = {
            "summary": v.get("summary"),
            "ceilings": v.get("ceilings"),
            "skipped": v.get("skipped"),
            "perf_layer_keys": len(v.get("perf_layers") or {}),
            "run_dir": verdict_ev.get("run_dir"),
        }
    return out


def _resilience(manifest, events) -> Dict[str, Any]:
    """Checkpoint/restart posture: how much work a preemption would
    cost right now, and how this run relates to its ancestors."""
    ckpts = [e for e in events if e.get("kind") == "checkpoint"]
    restores = [e for e in events if e.get("kind") == "restore"]
    preempts = [e for e in events if e.get("kind") == "preempt"]
    data_errors = [e for e in events if e.get("kind") == "data_error"]
    lineage = list((manifest or {}).get("restart_lineage") or [])
    last_age = None
    if ckpts and events:
        # age of the newest checkpoint at the run's last sign of life —
        # the work a preemption at that moment would have thrown away
        last_age = round(float(events[-1]["t"]) - float(ckpts[-1]["t"]), 1)
    return {
        "checkpoints": len(ckpts),
        "mid_epoch_checkpoints": sum(
            1 for e in ckpts if e.get("step_in_epoch")
        ),
        "last_checkpoint_age_s": last_age,
        "restart_count": len(lineage),
        "resumed_from": (manifest or {}).get("resumed_from"),
        "restart_lineage": lineage,
        "restores": [
            {
                k: r.get(k)
                for k in ("source", "fallback", "integrity", "epoch",
                          "step_in_epoch", "topology_from", "topology_to",
                          "resharded")
            }
            for r in restores
        ],
        "preempts": [
            {
                k: p.get(k)
                for k in ("signum", "epoch", "step_in_epoch", "coordinated")
            }
            for p in preempts
        ],
        "data_errors": len(data_errors),
    }


def summarize_run(path: str) -> Tuple[str, Dict[str, Any]]:
    """Returns ``(report_text, summary_dict)`` for a run directory."""
    run_dir = resolve_run_dir(path)
    manifest = read_manifest(run_dir)
    scalars = read_scalars(run_dir)
    events = read_events(run_dir)

    intervals = [e for e in events if e.get("kind") == "train_interval"]
    compile_ev = next((e for e in events if e.get("kind") == "compile"), None)
    evals = [e for e in events if e.get("kind") == "eval"]
    nonfinite = [e for e in events if e.get("kind") == "nonfinite"]
    t0 = events[0]["t"] if events else None

    phases = _phase_totals(intervals) if intervals else {}
    grad_curve = _curve(scalars, "Train grad_norm")
    starvation = _starvation(phases, grad_curve)

    # time-to-accuracy from eval events (wall clock vs run start);
    # scalar-only runs still get the accuracy trajectory, just untimed
    val_curve = _curve(scalars, "Val Acc1")
    tta = [
        {
            "epoch": e.get("epoch"),
            "acc1": round(float(e.get("acc1", 0.0)), 3),
            "elapsed_s": round(e["t"] - t0, 1) if t0 is not None else None,
        }
        for e in evals
    ]
    if not tta and val_curve:
        tta = [
            {"epoch": ep, "acc1": round(v, 3), "elapsed_s": None}
            for ep, v in val_curve
        ]
    best = max(tta, key=lambda r: r["acc1"]) if tta else None

    components = {}
    for s in scalars:
        tag = s.get("tag", "")
        if tag.startswith("Train loss_"):
            components.setdefault(tag[len("Train "):], []).append(
                (s["step"], s["value"])
            )
    components = {
        k: [round(v, 5) for _, v in sorted(pts)]
        for k, pts in sorted(components.items())
    }

    probes = _probe_trajectories(scalars, events)
    attribution = _attribution(run_dir, manifest, events)
    resilience = _resilience(manifest, events)
    health = _health(events)
    serving = _serving(events)
    search = _search(events)
    perf = _perf(events)
    # the LAST static-analysis verdict recorded on this timeline
    # (`check --events-into RUN_DIR`, bdbnn_tpu/analysis/)
    analysis_ev = next(
        (e for e in reversed(events) if e.get("kind") == "analysis"),
        None,
    )
    analysis = (
        {
            k: analysis_ev.get(k)
            for k in ("verdict", "checkers", "files_scanned",
                      "findings", "suppressed", "by_checker")
        }
        if analysis_ev is not None else None
    )

    summary: Dict[str, Any] = {
        "run_dir": run_dir,
        "provenance": (
            {
                k: manifest.get(k)
                for k in (
                    "config_hash", "jax_version", "jaxlib_version",
                    "backend", "device_kind", "device_count",
                    "process_count", "created",
                )
            }
            if manifest
            else None
        ),
        "compile_s": (
            round(float(compile_ev["seconds"]), 3) if compile_ev else None
        ),
        "phases": phases or None,
        "starvation": starvation,
        "time_to_accuracy": tta,
        "best": best,
        "loss_components": components,
        "probes": probes,
        "attribution": attribution,
        "resilience": resilience,
        "health": health,
        "serving": serving,
        "search": search,
        "perf": perf,
        "analysis": analysis,
        "nonfinite_intervals": len(nonfinite),
    }
    # strict JSON out the other end too: a warn-policy run's NaN
    # scalars must not make `summarize --json` unparseable
    summary = jsonsafe(summary)

    lines: List[str] = [f"== Run summary: {run_dir}"]
    if manifest:
        lines.append(
            "provenance: config {config_hash}  jax {jax_version} "
            "(jaxlib {jaxlib_version})  backend {backend} "
            "[{device_kind} x{device_count}, {process_count} proc]".format(
                **{k: manifest.get(k) for k in (
                    "config_hash", "jax_version", "jaxlib_version",
                    "backend", "device_kind", "device_count",
                    "process_count",
                )}
            )
        )
    else:
        lines.append("provenance: no manifest.json (pre-telemetry run?)")
    if summary["compile_s"] is not None:
        lines.append(f"compile: first-step trace+compile {summary['compile_s']:.2f}s")
    if phases:
        lines.append(
            "host phases: data-wait {data_wait_s:.2f}s "
            "({data_wait_share:.0%})  dispatch {dispatch_s:.2f}s  "
            "drain {drain_s:.2f}s ({drain_share:.0%}) of "
            "{interval_s:.2f}s hot-loop wall".format(**phases)
        )
    lines.append(f"starvation verdict: {starvation['verdict']}")
    if nonfinite:
        lines.append(
            f"!! non-finite loss intervals: {len(nonfinite)} "
            f"(policy {nonfinite[0].get('policy', '?')})"
        )
    if health:
        if health["alerts_total"]:
            lines.append(
                f"health: {health['alerts_total']} alert(s) — "
                + ", ".join(
                    f"{k} x{v}"
                    for k, v in health["by_detector"].items()
                )
                + (
                    f"; {health['alerts_critical']} run-ending"
                    if health["alerts_critical"]
                    else ""
                )
            )
            for a in health["critical"]:
                lines.append(
                    f"  !! {a['detector']} at epoch {a.get('epoch')} "
                    f"step {a.get('step')}: {a.get('message')}"
                )
        else:
            lines.append("health: monitored, no alerts")
    if analysis:
        lines.append(
            f"static analysis: {str(analysis.get('verdict')).upper()} "
            f"({analysis.get('findings')} open, "
            f"{analysis.get('suppressed')} suppressed over "
            f"{analysis.get('files_scanned')} files; "
            + ", ".join(analysis.get("checkers") or []) + ")"
        )
    if search:
        lines.append(
            f"recipe search: {search.get('trials_total')} trial(s)"
            + (
                f" over families {', '.join(search.get('families') or [])}"
                if search.get("families")
                else ""
            )
            + (
                f" | {search.get('workers')} worker(s)"
                if search.get("workers")
                else ""
            )
            + (" | PREEMPTED mid-sweep" if search.get("preempted") else "")
        )
        ranking = search.get("ranking")
        if ranking is not None:
            lines.append(
                f"  leaderboard ({search.get('completed')} completed, "
                f"{search.get('failed')} failed; common acc level "
                f"{search.get('common_acc_level')}):"
            )
            lines.append(
                f"  {'rank':<5} {'trial':<26} {'family':<22} "
                f"{'lr':>8} {'best':>7} {'final':>7} {'t->common':>10}"
            )
            trials_meta = search.get("trials") or {}
            for row in ranking:
                meta = trials_meta.get(row.get("trial")) or {}
                ttca = meta.get("time_to_common_acc_s")
                lines.append(
                    f"  {row.get('rank'):<5} {row.get('trial'):<26} "
                    f"{row.get('family'):<22} {row.get('lr'):>8g} "
                    f"{row.get('best_top1'):>7} {row.get('final_top1'):>7} "
                    f"{(str(ttca) + 's') if ttca is not None else '-':>10}"
                )
            winner = search.get("winner")
            if winner:
                lines.append(
                    f"  winner: {winner.get('trial')} "
                    f"({winner.get('family')} @ lr {winner.get('lr')}) "
                    f"best {winner.get('best_top1')} -> "
                    f"{winner.get('run_dir')}"
                )
            resumed = search.get("resumed_trials") or {}
            for tid, info in resumed.items():
                lines.append(
                    f"  resumed lineage: {tid} took "
                    f"{info.get('attempts')} attempt(s) "
                    f"({info.get('status')}) — completed trials were "
                    "never re-run"
                )
        else:
            states = search.get("trial_states") or {}
            if states:
                lines.append(
                    "  live trial states: "
                    + ", ".join(f"{t}={s}" for t, s in states.items())
                )
            best = search.get("best_so_far")
            if best:
                lines.append(
                    f"  best so far: {best.get('trial')} "
                    f"({best.get('family')} @ lr {best.get('lr')}) "
                    f"best_top1 {best.get('best_top1')}"
                )
    if perf:
        ps = perf.get("start") or {}
        lines.append(
            f"perf observatory: roofline sweep on {ps.get('arch')} "
            f"({ps.get('artifact')}) | buckets {ps.get('buckets')} x "
            f"impls {ps.get('impls')} | {ps.get('iters')} iters on "
            f"{ps.get('device_kind')}"
        )
        pv = perf.get("verdict")
        if pv:
            s = pv.get("summary") or {}
            ceil = pv.get("ceilings") or {}
            lines.append(
                f"  ceilings: {ceil.get('matched')} — "
                f"{ceil.get('peak_flops')} FLOP/s peak, "
                f"{ceil.get('hbm_gbs')} GB/s HBM (ridge "
                f"{ceil.get('ridge_intensity')} flop/byte)"
            )
            lines.append(
                f"  best {s.get('step_ms_best')} ms/step @ b"
                f"{s.get('bucket')} | dense {s.get('step_ms_dense')} / "
                f"packed {s.get('step_ms_packed')} ms | roof "
                f"efficiency {s.get('efficiency_mean')} | attributed "
                f"{s.get('attributed_share')} | mfu {s.get('mfu_best')}"
                f" | {pv.get('perf_layer_keys')} per-layer key(s)"
            )
            for skip in pv.get("skipped") or []:
                lines.append(
                    f"  skipped {skip.get('impl')}: {skip.get('reason')}"
                )
        for c in perf.get("cells") or []:
            recon = c.get("reconciled")
            lines.append(
                f"  {c.get('impl')} b{c.get('bucket')}: "
                f"{c.get('wall_ms')} ms/step, attributed "
                f"{c.get('attributed_ms')} ms "
                + (
                    "(reconciled)" if recon
                    else "(RECONCILIATION BROKEN)" if recon is False
                    else "(unreconciled)"
                )
            )
    if serving:
        for ex in serving["exports"]:
            lines.append(
                f"export: {ex.get('artifact')} (arch {ex.get('arch')}, "
                f"{ex.get('binarized_convs')} binary convs, "
                f"{ex.get('compression_ratio')}x smaller, integrity "
                f"{ex.get('integrity')}, recorded acc1 "
                f"{ex.get('checkpoint_acc1')})"
            )
        bench = serving.get("bench")
        if bench:
            lines.append(
                f"serving: {bench.get('mode')} load on {bench.get('arch')} "
                f"| buckets {bench.get('buckets')} | queue bound "
                f"{bench.get('queue_depth')} | coalesce "
                f"{bench.get('max_delay_ms')}ms"
            )
        http = serving.get("http")
        if http:
            lines.append(
                f"serving: http front end {http.get('host')}:"
                f"{http.get('port')} on {http.get('arch')} | "
                f"{http.get('priorities')} priority classes x queue "
                f"{http.get('queue_depth')} | buckets "
                f"{http.get('buckets')}"
                + (
                    f" | scenario {http.get('scenario')} @ "
                    f"{http.get('rate_rps')} req/s"
                    if http.get("scenario")
                    else ""
                )
            )
        fleet_info = serving.get("fleet")
        if fleet_info:
            lines.append(
                f"serving: fleet router {fleet_info.get('router')} "
                f"over {len(fleet_info.get('hosts') or [])} host(s) | "
                f"{fleet_info.get('probe_transitions')} health "
                "transition(s)"
            )
        sv = serving.get("verdict")
        if sv:
            lines.append(
                f"  SLO: p50 {sv.get('p50_ms')} / p95 {sv.get('p95_ms')} "
                f"/ p99 {sv.get('p99_ms')} ms | "
                f"{sv.get('throughput_rps')} req/s | occupancy "
                f"{sv.get('mean_batch_occupancy')} | shed "
                f"{sv.get('requests_shed')}/{sv.get('requests_submitted')}"
                + (
                    " | PREEMPTED, drained cleanly"
                    if sv.get("preempted") and sv.get("drained_clean")
                    else ""
                )
            )
            if sv.get("max_queue_depth_seen") is not None:
                lines.append(
                    f"  queue: peak depth {sv.get('max_queue_depth_seen')}"
                    f" of bound {sv.get('max_queue')}"
                )
            # the per-priority latency table (v2 / serve-http verdicts)
            per_priority = sv.get("per_priority") or {}
            if per_priority:
                lines.append(
                    f"  {'class':<8} {'p50':>8} {'p95':>8} {'p99':>8} "
                    f"{'ok':>7} {'shed':>6} {'of':>7}"
                )
                for p in sorted(per_priority, key=int):
                    v = per_priority[p]

                    def _ms(x):
                        return "-" if x is None else f"{x:.1f}"

                    lines.append(
                        f"  p{p:<7} {_ms(v.get('p50_ms')):>8} "
                        f"{_ms(v.get('p95_ms')):>8} "
                        f"{_ms(v.get('p99_ms')):>8} "
                        f"{v.get('completed'):>7} {v.get('shed'):>6} "
                        f"{v.get('submitted'):>7}"
                    )
            per_tenant = sv.get("per_tenant") or {}
            for t in sorted(per_tenant):
                v = per_tenant[t]
                lines.append(
                    f"  tenant {t}: {v.get('completed')}/"
                    f"{v.get('submitted')} ok | "
                    f"{v.get('over_quota')} over-quota | "
                    f"{v.get('shed_queue')} queue-shed "
                    f"(shed rate {v.get('shed_rate')})"
                )
            if sv.get("fairness_ratio") is not None:
                lines.append(
                    "  fairness: max/min tenant service ratio "
                    f"{sv.get('fairness_ratio')}"
                )
            slo = sv.get("slo")
            if slo is not None:
                lines.append(
                    "  slo: priority-0 p99 "
                    f"{slo.get('p99_ms_priority0')} ms vs target "
                    f"{slo.get('p99_ms_target_priority0')} ms — "
                    + ("MET" if slo.get("met") else "MISSED")
                )
            # the v3 replica-pool blocks: per-replica occupancy table,
            # the --replicas scaling sweep, and the swap disposition
            reps = sv.get("replicas")
            if reps:
                lines.append(
                    f"  replicas: {reps.get('n')} on "
                    f"{reps.get('version')} | "
                    f"{reps.get('dispatched_batches')} batches "
                    f"dispatched | {reps.get('restarts')} restart(s)"
                )
                for r in reps.get("per_replica") or []:
                    lines.append(
                        f"    r{r.get('replica')} "
                        f"[{r.get('device')}] {r.get('version')}: "
                        f"{r.get('completed')} done "
                        f"({r.get('share'):.0%} share)"
                        + (
                            f", {r.get('restarts')} restart(s)"
                            if r.get("restarts") else ""
                        )
                    )
            scaling = sv.get("scaling")
            if scaling:
                lines.append(
                    "  scaling: "
                    + "  ".join(
                        f"{n}x -> "
                        f"{scaling['throughput_rps'].get(str(n))} rps"
                        for n in scaling.get("replicas") or []
                    )
                    + f" | efficiency {scaling.get('efficiency')} at "
                    f"{max(scaling.get('replicas') or [0])} replicas"
                    + (
                        "" if scaling.get("monotone")
                        else " | NOT MONOTONE"
                    )
                )
            swap = sv.get("swap")
            if swap:
                lines.append(
                    f"  swap: {swap.get('version_from')} -> "
                    f"{swap.get('version_to')} "
                    + (
                        f"DONE in {swap.get('seconds')}s"
                        if swap.get("performed")
                        else f"{swap.get('state')} "
                        f"({swap.get('error')})"
                    )
                    + f" | {swap.get('replicas_shifted')} shifted | "
                    f"shed during swap {swap.get('shed')}"
                )
                by = swap.get("answered_by") or {}
                if by:
                    lines.append(
                        "    answered by: "
                        + "  ".join(
                            f"{v}: {n}" for v, n in sorted(by.items())
                        )
                    )
            # the v5 canary episode: decision + trigger, the
            # observation windows, the per-detector evidence table and
            # the shadow-probe accounting — the rollout's whole story
            # reconstructable from the run dir alone
            can = sv.get("canary")
            if can:
                decision = can.get("decision")
                lines.append(
                    f"  canary: {can.get('version_from')} -> "
                    f"{can.get('version_to')} | fraction "
                    f"{can.get('fraction')} on replicas "
                    f"{can.get('replicas_canary')} | "
                    + (
                        f"ROLLED BACK (trigger {can.get('trigger')})"
                        if decision == "rollback"
                        else f"PROMOTED in {can.get('promote_s')}s"
                        if decision == "promote"
                        else str(decision)
                    )
                    + f" after {can.get('evaluations')} evaluation(s)"
                    f" over {can.get('observe_s')}s"
                )
                served = can.get("served") or {}
                lines.append(
                    "    served: canary "
                    f"{served.get('canary')} / incumbent "
                    f"{served.get('incumbent')}"
                )
                dets = can.get("detectors") or {}
                if dets:
                    lines.append(
                        f"    {'detector':<14} {'value':>10} "
                        f"{'threshold':>10} {'status':>10}"
                    )
                    for name in sorted(dets):
                        d = dets[name] or {}
                        status = (
                            "FIRED" if d.get("fired")
                            else "breach" if d.get("breach")
                            else "ok" if d.get("eligible")
                            else "no data"
                        )
                        val = d.get("value")
                        thr = d.get("threshold")
                        lines.append(
                            f"    {name:<14} "
                            f"{'-' if val is None else format(val, '.4g'):>10} "
                            f"{'-' if thr is None else format(thr, '.4g'):>10} "
                            f"{status:>10}"
                        )
                shadow = can.get("shadow") or {}
                lines.append(
                    f"    shadow: {shadow.get('mirrored')} mirrored, "
                    f"{shadow.get('compared')} compared, max drift "
                    f"{shadow.get('max_abs_drift')}"
                    + (
                        " (bitwise-exact — any nonzero drift is a "
                        "real defect)"
                        if (shadow.get('compared') or 0) > 0 else ""
                    )
                )
            # the v6 fleet block: per-host ledgers, the cross-host
            # retry accounting, the per-host p99 spread and the
            # summed-across-hosts drop count — the whole fleet episode
            # reconstructable from the run dir alone
            flt = sv.get("fleet")
            if flt:
                cons = flt.get("ledger_consistent")
                lines.append(
                    f"  fleet: {flt.get('n_hosts')} host(s) | "
                    f"{flt.get('completed_total')} completed | "
                    f"{flt.get('retries_total')} retries (rate "
                    f"{flt.get('retry_rate')}) | p99 spread "
                    f"{flt.get('host_p99_spread')} | dropped "
                    f"{flt.get('dropped')} | ledger "
                    + (
                        "CONSISTENT" if cons
                        else "TORN" if cons is False else "unchecked"
                    )
                )
                for label in sorted(flt.get("hosts") or {}):
                    h = (flt.get("hosts") or {})[label]
                    retries = sum((h.get("retries") or {}).values())
                    lines.append(
                        f"    {label} [{h.get('state')}] "
                        f"{h.get('host')}:{h.get('port')}: "
                        f"{h.get('completed')} done / "
                        f"{h.get('proxied')} proxied | p99 "
                        f"{h.get('p99_ms')} ms | {retries} retry(s) | "
                        f"{h.get('probe_transitions')} transition(s)"
                    )
                fswap = flt.get("swap")
                if fswap:
                    unshifted = fswap.get("hosts_unshifted") or []
                    lines.append(
                        f"    fleet swap: {fswap.get('state')} "
                        f"({len(fswap.get('hosts_shifted') or [])}/"
                        f"{fswap.get('hosts_total')} hosts shifted, "
                        f"{fswap.get('seconds')}s)"
                        + (
                            f" — {fswap.get('error')}"
                            if fswap.get("error") else ""
                        )
                        + (
                            " | !! NOT shifted (still on the old "
                            f"version if they rejoin): {unshifted}"
                            if unshifted else ""
                        )
                    )
            # the v7 fleet_attribution block: the cross-host
            # waterfall — router stages + network + the stitched
            # backend decomposition, the retry-hop share, the
            # per-host stage spread, the cross-hop reconciliation
            # identity and the slowest exemplars naming host AND
            # stage
            fat = sv.get("fleet_attribution")
            if fat:
                recon = fat.get("reconciliation") or {}
                share = fat.get("retry_hop_share")
                lines.append(
                    f"  fleet trace: {fat.get('requests')} requests "
                    f"traced (stitched {fat.get('stitched')}, "
                    f"unstitched {fat.get('unstitched')})"
                    + (
                        f" | retry-hop share {share:.1%}"
                        if share is not None else ""
                    )
                    + (
                        f" | cross-hop recon: mean err "
                        f"{recon.get('mean_abs_err_pct')}%, "
                        f"{recon.get('violations')} violation(s) "
                        + ("OK" if recon.get("ok") else "BROKEN")
                        if recon.get("mean_abs_err_pct") is not None
                        else ""
                    )
                )
                stage_parts = [
                    f"{stage} {b['p99_ms']:.1f}"
                    for stage, b in (fat.get("stages") or {}).items()
                    if b is not None and b.get("p99_ms") is not None
                ]
                if stage_parts:
                    lines.append(
                        "    router p99/stage ms  "
                        + " > ".join(stage_parts)
                    )
                bparts = [
                    f"{stage} {b['p99_ms']:.1f}"
                    for stage, b in (
                        fat.get("backend_stages") or {}
                    ).items()
                    if b is not None and b.get("p99_ms") is not None
                ]
                if bparts:
                    lines.append(
                        "    backend p99/stage ms  " + " > ".join(bparts)
                    )
                per_host_fat = fat.get("per_host") or {}
                spread_max = fat.get("host_stage_spread_max")
                if per_host_fat:
                    lines.append(
                        "    per-host backend stage p99 (ms)"
                        + (
                            f" | spread max {spread_max}"
                            if spread_max is not None else ""
                        )
                    )
                    for label in sorted(per_host_fat):
                        hb = per_host_fat[label]
                        hparts = [
                            f"{stage} {b['p99_ms']:.1f}"
                            for stage, b in (
                                hb.get("stages") or {}
                            ).items()
                            if b is not None
                            and b.get("p99_ms") is not None
                        ]
                        lines.append(
                            f"      {label} "
                            f"({hb.get('requests')} req): "
                            + (
                                " > ".join(hparts)
                                if hparts else "no stitched samples"
                            )
                        )
                for p, wfs in sorted((fat.get("tail") or {}).items()):
                    for wf in wfs[:1]:
                        waterfall = " + ".join(
                            f"{stage} {ms:.1f}"
                            for stage, ms in (
                                wf.get("stages") or {}
                            ).items()
                        )
                        lines.append(
                            f"    slowest p{p}: {wf.get('trace')} on "
                            f"{wf.get('host')} "
                            f"({wf.get('attempts')} attempt(s)) "
                            f"{wf.get('total_ms')}ms = {waterfall} | "
                            f"slowest stage {wf.get('slowest_stage')}"
                        )
            # the v4 request-path attribution: per-priority p99
            # decomposed by lifecycle stage, the reconciliation
            # identity, and the slowest exemplars' waterfalls
            att = sv.get("attribution")
            if att:
                recon = att.get("reconciliation") or {}
                share = att.get("queue_share")
                lines.append(
                    f"  trace: {att.get('requests')} requests traced "
                    f"(sampled {att.get('sampled')}, 1/"
                    f"{att.get('sample_every')})"
                    + (
                        f" | queue share {share:.0%}"
                        if share is not None else ""
                    )
                    + (
                        f" | stage sum vs e2e: mean err "
                        f"{recon.get('mean_abs_err_pct')}% "
                        + ("OK" if recon.get("ok") else "BROKEN")
                        if recon.get("mean_abs_err_pct") is not None
                        else ""
                    )
                )
                stage_names = list((att.get("stages") or {}).keys())
                per_priority_att = att.get("per_priority") or {}
                if per_priority_att and stage_names:
                    lines.append(
                        "  "
                        + f"{'class':<9}"
                        + "".join(f"{s:>10}" for s in stage_names)
                        + f"{'e2e':>10}"
                    )

                    def _a(block):
                        if not block or block.get("p99_ms") is None:
                            return "-"
                        return f"{block['p99_ms']:.1f}"

                    for p in sorted(per_priority_att, key=int):
                        v = per_priority_att[p]
                        stages_p = v.get("stages") or {}
                        lines.append(
                            "  "
                            + f"p99 p{p:<4}"
                            + "".join(
                                f"{_a(stages_p.get(s)):>10}"
                                for s in stage_names
                            )
                            + f"{_a(v.get('e2e')):>10}"
                        )
                for p, wfs in sorted((att.get("tail") or {}).items()):
                    for wf in wfs[:1]:
                        waterfall = " + ".join(
                            f"{stage} {ms:.1f}"
                            for stage, ms in (
                                wf.get("stages") or {}
                            ).items()
                        )
                        lines.append(
                            f"    slowest p{p}: #{wf.get('seq')} "
                            f"{wf.get('total_ms')}ms = {waterfall}"
                        )
            # the v8 capacity block (obs/capacity.py): the demand
            # ledger's per-key rates, utilization gauges, the SLO
            # burn-rate episodes and the saturation-headroom estimate
            cap = sv.get("capacity")
            if cap:
                burn_max = cap.get("burn_rate_max")
                headroom_rps = cap.get("headroom_rps")
                shed_max = cap.get("demand_shed_ratio_max")
                lines.append(
                    "  capacity:"
                    + (
                        f" burn max {burn_max}"
                        if burn_max is not None else " burn max -"
                    )
                    + (
                        f" | headroom {headroom_rps} rps"
                        if headroom_rps is not None else ""
                    )
                    + (
                        f" | worst shed ratio {shed_max:.1%}"
                        if shed_max is not None else ""
                    )
                )
                demand = cap.get("demand") or {}
                keys = demand.get("keys") or {}
                if keys:
                    lines.append(
                        "    "
                        + f"{'model|tenant|prio':<28}"
                        + f"{'offered':>9}{'admit':>9}"
                        + f"{'done':>9}{'shed':>9}"
                    )
                    for key in sorted(keys):
                        row = keys[key]
                        lines.append(
                            "    "
                            + f"{key:<28}"
                            + f"{row.get('offered_rps', 0):>9}"
                            + f"{row.get('admitted_rps', 0):>9}"
                            + f"{row.get('completed_rps', 0):>9}"
                            + f"{row.get('shed_rps', 0):>9}"
                        )
                budget = cap.get("slo_budget") or {}
                for ep in budget.get("episodes") or []:
                    t_end = ep.get("t_end")
                    lines.append(
                        f"    burn episode: {ep.get('detector')} "
                        f"peak {ep.get('peak_burn_rate')} "
                        + (
                            f"({ep.get('t_end') - ep.get('t_start'):.1f}s)"
                            if t_end is not None else "(still open)"
                        )
                    )
                hr = cap.get("headroom") or {}
                if hr.get("capacity_rps_est") is not None:
                    tts = hr.get("seconds_to_saturation")
                    lines.append(
                        f"    est capacity {hr['capacity_rps_est']} rps"
                        + (
                            f" | saturates in {tts:.0f}s at current slope"
                            if tts is not None else ""
                        )
                    )
                # fleet-merged producer: per-host freshness + gates
                flc = cap.get("fleet")
                if flc:
                    lines.append(
                        f"    fleet: {flc.get('hosts_fresh')} fresh / "
                        f"{flc.get('hosts_stale')} stale host(s)"
                    )
                    for label in sorted(flc.get("hosts") or {}):
                        hb = (flc.get("hosts") or {})[label]
                        lines.append(
                            f"      {label}: "
                            + ("STALE" if hb.get("stale") else "fresh")
                            + f" | offered {hb.get('offered_rps')} rps"
                            + f" | burn {hb.get('burn_rate_max')}"
                            + f" | headroom {hb.get('headroom_rps')}"
                        )
    if tta:
        lines.append("time-to-accuracy (val top-1):")
        for r in tta:
            elapsed = (
                f"{r['elapsed_s']:9.1f}s" if r["elapsed_s"] is not None
                else "        -"
            )
            lines.append(
                f"  epoch {r['epoch']:>4}  {elapsed}  acc1 {r['acc1']:6.2f}"
            )
        if best:
            lines.append(
                f"  best: {best['acc1']:.2f} @ epoch {best['epoch']}"
            )
    if components:
        lines.append("loss components (per-epoch means, first -> last):")
        for name, vals in components.items():
            lines.append(
                f"  {name:<12} {vals[0]:.5g} -> {vals[-1]:.5g} "
                f"({len(vals)} epochs)"
            )
    if attribution:
        cats = attribution.get("categories_ms_per_step") or {}
        if cats:
            cap = attribution.get("captured") or {}
            lines.append(
                "device attribution (ms/step over "
                f"{attribution.get('n_steps')} traced steps @ epoch "
                f"{cap.get('epoch')} step {cap.get('start_step')}):"
            )
            total = attribution.get("step_total_ms")
            for name, ms in cats.items():
                share = f" ({ms / total:.0%})" if total else ""
                lines.append(f"  {name:<16} {ms:8.3f} ms{share}")
            if total:
                lines.append(f"  {'step total':<16} {total:8.3f} ms")
            if attribution.get("mfu") is not None:
                lines.append(
                    f"  MFU {attribution['mfu']:.1%} of "
                    f"{attribution.get('peak_tflops')} TFLOP/s bf16 peak"
                )
        host = attribution.get("host_phases_ms_per_step") or {}
        if host:
            lines.append(
                "host phases in window: "
                + "  ".join(f"{k} {v:.3f} ms" for k, v in host.items())
            )
        hbm = attribution.get("hbm")
        if hbm:
            if hbm.get("limit_gib"):
                lines.append(
                    f"hbm: peak {hbm['peak_gib']:.2f} GiB of "
                    f"{hbm['limit_gib']:.2f} GiB "
                    f"({hbm['utilization']:.0%})"
                )
            else:
                lines.append(f"hbm: peak {hbm['peak_gib']:.2f} GiB")
    res = resilience
    if (
        res["checkpoints"]
        or res["restart_count"]
        or res["restores"]
        or res["preempts"]
        or res["data_errors"]
    ):
        parts = []
        if res["checkpoints"]:
            mid = res["mid_epoch_checkpoints"]
            parts.append(
                f"{res['checkpoints']} checkpoint(s)"
                + (f" ({mid} mid-epoch)" if mid else "")
                + (
                    f", last {res['last_checkpoint_age_s']:.0f}s before "
                    "the run's last event"
                    if res["last_checkpoint_age_s"] is not None
                    else ""
                )
            )
        if res["restart_count"]:
            parts.append(f"restart #{res['restart_count']} in lineage")
        lines.append("resilience: " + ("  ".join(parts) or "events only"))
        for r in res["restores"]:
            lines.append(
                f"  restored from {r.get('source')} (epoch "
                f"{r.get('epoch')} step {r.get('step_in_epoch')}, "
                f"integrity {r.get('integrity')}"
                + (", FELL BACK to checkpoint.old" if r.get("fallback") else "")
                + ")"
            )
            if r.get("resharded"):
                tf, tt = r.get("topology_from") or {}, r.get("topology_to") or {}
                lines.append(
                    "    elastic resume: "
                    f"{tf.get('processes')} proc x {tf.get('devices')} dev"
                    f" -> {tt.get('processes')} proc x {tt.get('devices')}"
                    " dev (global arrays resharded)"
                )
        for p in res["preempts"]:
            lines.append(
                f"  preempted by signal {p.get('signum')} at epoch "
                f"{p.get('epoch')} step {p.get('step_in_epoch')} "
                + (
                    "(coordinated pod-wide mid-epoch checkpoint saved)"
                    if p.get("coordinated")
                    else "(mid-epoch checkpoint saved)"
                )
            )
        if res["data_errors"]:
            lines.append(
                f"  !! {res['data_errors']} corrupt sample(s) substituted "
                "(data_error events)"
            )
    if probes:
        lines.append(
            "binarization probes (per-layer, first -> last interval/epoch):"
        )
        lines.append(
            f"  {'layer':<28} {'flip rate':>22} {'kurtosis':>22}"
        )
        for layer, d in sorted(probes.items()):
            fr = (
                f"{d.get('flip_rate_first', float('nan')):.2e} -> "
                f"{d.get('flip_rate_last', float('nan')):.2e}"
            )
            ku = (
                f"{d.get('kurtosis_first', float('nan')):8.3f} -> "
                f"{d.get('kurtosis_last', float('nan')):8.3f}"
            )
            lines.append(f"  {layer:<28} {fr:>22} {ku:>22}")
    return "\n".join(lines), summary
