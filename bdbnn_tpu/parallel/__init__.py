from bdbnn_tpu.parallel import mesh
from bdbnn_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    batch_spec,
    create_sharded_state,
    initialize_distributed,
    jit_train_step,
    make_mesh,
    param_spec,
    params_shardings,
    replicated,
    shard_batch,
    shard_variables,
)

__all__ = [
    "mesh",
    "DATA_AXIS",
    "MODEL_AXIS",
    "batch_sharding",
    "batch_spec",
    "create_sharded_state",
    "initialize_distributed",
    "jit_train_step",
    "make_mesh",
    "param_spec",
    "params_shardings",
    "replicated",
    "shard_batch",
    "shard_variables",
]
