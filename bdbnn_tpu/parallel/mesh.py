"""Device mesh + sharding layer — the NCCL/DDP replacement.

The reference scales via ``torch.nn.DataParallel`` /
NCCL ``DistributedDataParallel`` with TCP rendezvous (reference
``train.py:237-314``; its multi-proc rendezvous was actually broken —
per-rank MASTER_PORT, SURVEY.md Appendix B #4). The TPU-native design
needs none of that machinery:

- ``jax.distributed.initialize()`` + the TPU runtime discover the pod
  (no MASTER_ADDR, no ports, no backend flag);
- a ``jax.sharding.Mesh`` over all chips with axes ``('data',
  'model')`` replaces process groups; gradients are averaged by XLA
  collectives compiled into the step (``psum`` over ICI within a
  slice, DCN across slices) instead of DDP backward hooks;
- parameters are replicated over 'data' and (optionally) sharded over
  'model' on their output-channel axis — tensor parallelism the
  reference never had, useful for wide layers / the FC head;
- per-host input feeding uses :func:`bdbnn_tpu.data.pipeline.
  host_shard_indices` + :func:`jax.make_array_from_process_local_data`.

Everything here works identically on a real pod and on a CPU-simulated
mesh (``--xla_force_host_platform_device_count``), which is how the
test suite exercises it.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def initialize_distributed(**kwargs) -> None:
    """Multi-host bring-up (↔ dist.init_process_group, reference
    ``train.py:248``): a single call, no rendezvous configuration. Safe
    to call only in true multi-process deployments."""
    jax.distributed.initialize(**kwargs)


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    model_parallel: int = 1,
) -> Mesh:
    """('data', 'model') mesh over all devices. data-parallel size =
    n_devices / model_parallel. model_parallel=1 ≡ pure DP (the
    reference's only strategy)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model={model_parallel}")
    arr = np.array(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def batch_spec(ndim: int) -> P:
    """Batch axis sharded over 'data', feature axes replicated."""
    return P(DATA_AXIS, *([None] * (ndim - 1)))


def batch_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(ndim))


def param_spec(
    path_key: str, leaf, *, model_parallel: int, min_shard_size: int = 256
) -> P:
    """Parameter partition spec.

    Replicated by default (pure DP). With model_parallel > 1, shard the
    output-channel (last) axis of large kernels over 'model' — 4-D conv
    kernels and 2-D dense kernels whose out-dim divides evenly and is
    big enough to be worth the collective."""
    if model_parallel > 1 and hasattr(leaf, "ndim") and leaf.ndim >= 2:
        out = leaf.shape[-1]
        if out % model_parallel == 0 and out >= min_shard_size:
            return P(*([None] * (leaf.ndim - 1)), MODEL_AXIS)
    return P()


def params_shardings(mesh: Mesh, params) -> Any:
    """NamedSharding pytree for params (and by structure, opt state
    leaves created from params)."""
    model_parallel = mesh.shape[MODEL_AXIS]

    def spec_for(path, leaf):
        key = "/".join(str(getattr(p, "key", p)) for p in path)
        return NamedSharding(
            mesh, param_spec(key, leaf, model_parallel=model_parallel)
        )

    return jax.tree_util.tree_map_with_path(spec_for, params)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_variables(mesh: Mesh, variables):
    """Place init-time variables onto the mesh: params per
    :func:`params_shardings`, batch_stats replicated."""
    out = dict(variables)
    out["params"] = jax.device_put(
        variables["params"], params_shardings(mesh, variables["params"])
    )
    if "batch_stats" in variables:
        out["batch_stats"] = jax.device_put(
            variables["batch_stats"], replicated(mesh)
        )
    return out


def shard_batch(mesh: Mesh, *arrays: np.ndarray):
    """Host-local per-example arrays → globally-sharded arrays over the
    'data' axis (variadic: images, labels, masks, ... — anything whose
    leading axis is the batch).

    Single-process: a plain device_put with the batch sharding.
    Multi-host: each process passes its local shard and JAX assembles
    the global array (the DistributedSampler replacement's second
    half)."""
    if jax.process_count() > 1:
        return tuple(
            jax.make_array_from_process_local_data(
                batch_sharding(mesh, a.ndim), a
            )
            for a in arrays
        )
    return tuple(
        jax.device_put(a, batch_sharding(mesh, a.ndim)) for a in arrays
    )


def create_sharded_state(mesh: Mesh, variables, tx, state_cls):
    """Build a TrainState already laid out on the mesh.

    Params are placed per :func:`params_shardings` (replicated for pure
    DP, channel-sharded over 'model' when model_parallel > 1) BEFORE
    ``tx.init`` runs, so optimizer-state leaves inherit the param
    shardings (``zeros_like`` preserves sharding) — no separate
    opt-state spec needed. Remaining single-device leaves (the step
    counter, optimizer schedule counts) are replicated onto the mesh so
    EVERY leaf carries a mesh sharding — checkpoint restore relies on
    that to re-place leaves exactly.
    """
    placed = shard_variables(mesh, variables)
    state = state_cls.create(placed, tx)

    def _mesh_place(x):
        if hasattr(x, "sharding") and isinstance(x.sharding, NamedSharding):
            return x
        return jax.device_put(x, replicated(mesh))

    return jax.tree_util.tree_map(_mesh_place, state)


# ---------------------------------------------------------------------------
# Cross-host coordination (pod-grade fault tolerance)
# ---------------------------------------------------------------------------


def topology(mesh: Optional[Mesh] = None) -> dict:
    """The run's process/device layout as a strict-JSON dict — recorded
    in checkpoint sidecars (``resume.json``) at save time and compared
    against the restoring run's layout to detect an ELASTIC resume
    (restore onto a different topology; ``restore`` event fields
    ``topology_from`` / ``topology_to`` / ``resharded``)."""
    out = {
        "processes": jax.process_count(),
        "devices": jax.device_count(),
    }
    if mesh is not None:
        out["mesh"] = {
            "data": int(mesh.shape[DATA_AXIS]),
            "model": int(mesh.shape[MODEL_AXIS]),
        }
    return out


def coordinate_flags(values: Sequence[float]) -> np.ndarray:
    """Cross-host agreement on step-boundary trigger flags: elementwise
    MAX over every process's local vector.

    This is the primitive behind coordinated preemption (docs/design.md
    §7): signal delivery is per-process, so hosts latch SIGTERM at
    different steps — but every host calls this at every step boundary,
    so the first boundary AFTER any host latched is the SAME boundary
    on every host, and all processes run the collective save for that
    step together (barriers align, no mixed-step shards). Max-reduce
    also broadcasts process-0's wallclock-cadence decision and any
    host's pending forensics request.

    Single-process runs return the local vector untouched (no
    collective, no cost). Multi-process runs pay one small allgather
    per step boundary — noise next to a train step's collectives.
    MUST be called by every process with a same-length vector (it is a
    collective op).
    """
    local = np.asarray(values, np.float32)
    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(local)).max(axis=0)


def broadcast_host_int(value: int) -> int:
    """Process-0's ``value`` on every process (identity when single
    process). Used to agree on one run-directory timestamp per pod run
    — per-host clocks may straddle a second boundary, and hosts writing
    different run dirs would break the collective checkpoint, the
    shared manifest, and every post-hoc reader."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    return int(
        multihost_utils.broadcast_one_to_all(
            np.asarray([value], np.int64)
        )[0]
    )


def replica_devices(
    n: int, mesh: Optional[Mesh] = None
) -> Sequence[jax.Device]:
    """The ``n`` devices a serving replica pool places its engines on
    (one engine per device — data parallelism for inference, the serve
    counterpart of the 'data' mesh axis).

    With a mesh, replicas take the data axis's device order (one
    replica per data-parallel row, cycling through model-parallel
    columns only if ``n`` exceeds the rows — a replica should own a
    whole model shard group before doubling up). Without one, the flat
    ``jax.devices()`` order. ``n`` beyond the device count is an
    error: two replicas contending for one chip is a silent perf lie,
    not a bigger pool."""
    if n <= 0:
        raise ValueError(f"need n >= 1 replicas, got {n}")
    if mesh is not None:
        arr = np.asarray(mesh.devices)
        # data-major order: walk rows (data axis) first, then columns
        flat = list(arr.T.reshape(-1)) if arr.ndim == 2 else list(
            arr.reshape(-1)
        )
    else:
        flat = list(jax.devices())
    if n > len(flat):
        raise ValueError(
            f"{n} replicas over {len(flat)} devices: one engine per "
            "device is the contract (shrink --replicas or grow the mesh)"
        )
    return flat[:n]


def jit_train_step(step_fn) -> Any:
    """Compile a train step for mesh execution.

    Shardings follow the data: the state is placed by
    :func:`create_sharded_state` and batches by :func:`shard_batch`;
    GSPMD then inserts the gradient all-reduce (psum over ICI) exactly
    where DDP's backward hooks ran NCCL ring-allreduce — but fused
    into the compiled step. ``donate_argnums=0`` reuses the old state's
    HBM for the new state (parameters update in place, as DDP does).
    """
    return jax.jit(step_fn, donate_argnums=(0,))
