"""bdbnn_tpu — a TPU-native (JAX/XLA/Pallas/pjit) framework for training
bimodal-distributed binarized neural networks (BD-BNN).

Re-designed from scratch against the behavior of the BlueAnon/BD-BNN
reference (PyTorch/CUDA/NCCL), with a TPU-first architecture:

- binarization as ``jax.custom_vjp`` transforms (STE / ApproxSign / EDE)
  instead of autograd-module mutation (reference ``train.py:409-415``),
- pure jit-compiled train steps (losses fused by XLA) instead of
  per-batch Python objects (reference ``train.py:461-484``),
- ``jax.sharding.Mesh`` + XLA collectives over ICI/DCN instead of NCCL
  DistributedDataParallel (reference ``train.py:237-314``),
- grain/tf.data-style host-sharded input pipelines instead of
  ``torch.utils.data.DataLoader`` (reference ``loader.py``).
"""

from bdbnn_tpu import configs, data, losses, models, nn, parallel, train, utils

__version__ = "0.1.0"

__all__ = [
    "configs",
    "data",
    "losses",
    "models",
    "nn",
    "parallel",
    "train",
    "utils",
    "__version__",
]
