from bdbnn_tpu.train import ede, optim, state, step
from bdbnn_tpu.train.ede import cpt_tk
from bdbnn_tpu.train.optim import (
    conv_weight_mask,
    cosine_epoch_schedule,
    linear_epoch_schedule,
    make_optimizer,
)
from bdbnn_tpu.train.state import StepConfig, TrainState
from bdbnn_tpu.train.step import (
    make_eval_step,
    make_train_step,
    make_ts_train_step,
    topk_correct,
)

__all__ = [
    "ede",
    "optim",
    "state",
    "step",
    "cpt_tk",
    "conv_weight_mask",
    "cosine_epoch_schedule",
    "linear_epoch_schedule",
    "make_optimizer",
    "StepConfig",
    "TrainState",
    "make_eval_step",
    "make_train_step",
    "make_ts_train_step",
    "topk_correct",
]
