"""EDE (error decay estimator) annealing schedule.

Parity with reference ``utils/utils.py:6-14``:

    t(e) = 10 ** (log10(1e-2) + (log10(1e1) - log10(1e-2)) / E * e)
    k(e) = max(1 / t(e), 1)

i.e. ``t`` sweeps 1e-2 → 1e1 log-linearly over ``tot_epochs`` and ``k``
compensates early-training attenuation. The reference pushes (t, k)
onto every ``nn.Conv2d`` as module attributes each epoch
(``train.py:409-415``), forcing autograd to read module state; here
they are plain scalars passed as *traced arguments* into the jitted
step, so the annealing never retraces or recompiles.

This traced-scalar discipline is generalized by the binarizer-family
registry (:mod:`bdbnn_tpu.nn.binarize`): every family may carry a
per-epoch schedule tuple (``ede`` → this module's (t, k); ``proximal``
→ an annealed δ), produced host-side by
:meth:`BinarizerFamily.schedule` and fed into the step exactly like
(t, k) always was. ``cpt_tk`` stays the canonical EDE math — the
registry's ``ede`` entry calls it, keeping reference parity pinned in
one place.
"""

from __future__ import annotations

import math
from typing import Tuple

T_MIN = 1e-2
T_MAX = 1e1


def cpt_tk(epoch: int, tot_epochs: int) -> Tuple[float, float]:
    lo, hi = math.log10(T_MIN), math.log10(T_MAX)
    t = 10.0 ** (lo + (hi - lo) / tot_epochs * epoch)
    k = max(1.0 / t, 1.0)
    return t, k
