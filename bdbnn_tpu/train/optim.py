"""Optimizer / LR-schedule policy (optax), reference-parity.

Reference policy (``train.py:316-336``):

- CIFAR: SGD(lr, momentum .9, weight-decay on ALL params) +
  ``CosineAnnealingLR(T_max=epochs, eta_min=0)`` stepped per epoch;
- ImageNet: Adam with weight decay applied ONLY to the "weight
  parameters" (``p.ndimension() == 4 or 'conv' in pname``,
  ``train.py:326-331``) + ``LambdaLR`` linear decay
  ``1 − epoch/epochs`` stepped per epoch.

Torch-parity notes:

- torch SGD/Adam weight decay is the *additive-to-gradient* (L2) form,
  not AdamW's decoupled form → ``optax.add_decayed_weights`` is chained
  BEFORE the momentum / Adam transform;
- torch schedulers step per **epoch** (``train.py:423``), so schedules
  here are step functions of ``step // steps_per_epoch`` — piecewise-
  constant within an epoch, exactly like the reference.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import optax
from flax import traverse_util


def conv_weight_mask(params) -> dict:
    """Pytree of bools marking the reference's Adam weight-decay group:
    4-D kernels or any param whose dotted path contains 'conv'
    (↔ ``train.py:326-329``)."""
    flat = traverse_util.flatten_dict(params)
    mask = {
        k: (v.ndim == 4 or any("conv" in part for part in k))
        for k, v in flat.items()
    }
    return traverse_util.unflatten_dict(mask)


def cosine_epoch_schedule(
    base_lr: float, epochs: int, steps_per_epoch: int, eta_min: float = 0.0
) -> Callable:
    """torch CosineAnnealingLR(T_max=epochs) stepped per epoch."""

    def schedule(step):
        epoch = step // steps_per_epoch
        return eta_min + (base_lr - eta_min) * 0.5 * (
            1.0 + jax.numpy.cos(math.pi * epoch / epochs)
        )

    return schedule


def linear_epoch_schedule(
    base_lr: float, epochs: int, steps_per_epoch: int
) -> Callable:
    """torch LambdaLR(lambda e: 1 - e/epochs) stepped per epoch."""

    def schedule(step):
        epoch = step // steps_per_epoch
        return base_lr * (1.0 - epoch / epochs)

    return schedule


def make_optimizer(
    params,
    *,
    dataset: str,
    lr: float,
    epochs: int,
    steps_per_epoch: int,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    policy: str = "",
) -> optax.GradientTransformation:
    """The full reference policy keyed on dataset (``train.py:316-336``).

    ``policy`` overrides the dataset keying: "sgd-cosine" (the
    reference's CIFAR policy) or "adam-linear" (its ImageNet policy,
    masked weight decay). Useful because deep binary nets on small
    datasets learn far faster under the adaptive policy — both
    policies remain exactly the reference's own.
    """
    if policy and policy not in ("sgd-cosine", "adam-linear"):
        raise ValueError(f"unknown opt policy {policy!r}")
    adam = (
        policy == "adam-linear"
        if policy
        else dataset == "imagenet"
    )
    if adam:
        schedule = linear_epoch_schedule(lr, epochs, steps_per_epoch)
        return optax.chain(
            optax.masked(
                optax.add_decayed_weights(weight_decay),
                conv_weight_mask(params),
            ),
            optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8),
            optax.scale_by_learning_rate(schedule),
        )
    schedule = cosine_epoch_schedule(lr, epochs, steps_per_epoch)
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.trace(decay=momentum, nesterov=False),
        optax.scale_by_learning_rate(schedule),
    )
