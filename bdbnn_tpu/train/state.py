"""Train state + step configuration.

The reference mutates a single argparse namespace and module attributes
at runtime (SURVEY.md §5.6); here all step-relevant knobs are frozen
into a hashable :class:`StepConfig` at trace time and everything that
varies per epoch (EDE (t, k), the kurtosis gate) is a *traced* input,
so one compiled step serves the whole run.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    """Pure pytree train state (params + BN stats + optimizer state)."""

    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: optax.OptState

    @classmethod
    def create(cls, variables, tx: optax.GradientTransformation):
        import jax.numpy as jnp

        params = variables["params"]
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
        )

    @property
    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}


@dataclasses.dataclass(frozen=True)
class StepConfig:
    """Static (trace-time) configuration of a train step.

    Mirrors the reference's loss wiring: total loss =
    ``beta·layerKL + alpha·logitKL + w_lambda_ce·CE + λ·kurt
    [+ λ_l2·L2 + λ_wr·WR]`` (reference ``train.py:515, 636``). The
    plain (non-TS) step is the special case alpha=beta=0,
    w_lambda_ce=1.

    Appendix-B fixes folded in: ``w_lambda_ce`` exists as a real knob
    (reference read it undefined, #3), and the L2 / |W|→±1 regularizers
    are actually added to the loss when enabled (#2).
    """

    # kurtosis
    w_kurtosis: bool = False
    kurt_paths: Tuple[Tuple[str, ...], ...] = ()
    kurt_targets: Tuple[float, ...] = ()
    kurtosis_mode: str = "avg"
    w_lambda_kurtosis: float = 1.0
    # auxiliary regularizers (Appendix B #2 — wired in, default off)
    w_l2_reg: bool = False
    w_lambda_l2: float = 0.0
    w_wr_reg: bool = False
    w_lambda_wr: float = 0.0
    # distillation (TS step)
    teacher_student: bool = False
    react: bool = False
    alpha: float = 0.9
    beta: float = 200.0
    temperature: float = 4.0
    w_lambda_ce: float = 1.0
    kd_pairs: Tuple[Tuple[Tuple[str, ...], Tuple[str, ...]], ...] = ()
    # EDE (legacy flag: True ⇔ the 'ede' binarizer family — kept so
    # direct StepConfig builders (bench.py, tests) stay source-stable)
    ede: bool = False
    # binarizer family (nn/binarize.py registry): the resolved family
    # NAME plus the two facts the jitted step needs at trace time —
    # whether the family carries a per-epoch schedule (its traced
    # scalars are then passed into model.apply as `tk`) and whether it
    # samples (a per-step jax.random key is then threaded through the
    # 'binarize' rng stream, derived from (rng_seed, state.step) so a
    # resumed step replays the same masks bitwise)
    binarizer: str = "ste"
    binarizer_schedule: bool = False
    binarizer_stochastic: bool = False
    rng_seed: int = 0
    # observability: emit optax.global_norm(grads) as metrics
    # ['grad_norm'] — the estimator-starvation probe (VERDICT r4 weak
    # #5). Default OFF so bench/profile workloads that build StepConfig
    # directly measure the unperturbed step; fit() turns it on.
    log_grad_norm: bool = False
    # binarization health probes (obs/probes.py): per-layer sign-flip
    # counts + latent-weight kurtosis, computed in the jitted step and
    # drained with the existing DeviceMetrics sums. Same default-OFF
    # rationale as log_grad_norm; fit() populates these from the hooked
    # kurtosis layers (or every non-stem conv when no hooks).
    probe_paths: Tuple[Tuple[str, ...], ...] = ()
    probe_names: Tuple[str, ...] = ()
    # emit metrics['nonfinite'] (1 per step with a NaN/Inf loss) for the
    # drain-time fail-fast policy
    track_nonfinite: bool = False
    # device-side input normalization (TPU-first input path): when set
    # to per-channel ((mean,...), (std,...)) in 0-1 scale, the step
    # receives RAW uint8 NHWC batches and normalizes on device — the
    # host->device transfer carries 1 byte/px instead of 4 and the
    # normalize fuses into the first conv's prologue under XLA
    input_norm: Optional[Tuple[Tuple[float, ...], Tuple[float, ...]]] = None

    def resolved(self) -> "StepConfig":
        """Apply the react-mode overrides the reference applies inside
        the batch loop (``train.py:605-609``): beta=0, w_lambda_ce=0."""
        if self.teacher_student and self.react:
            return dataclasses.replace(self, beta=0.0, w_lambda_ce=0.0)
        return self
