"""Jitted train / eval / teacher-student steps.

TPU-first redesign of the reference's batch loops (``train.py:441-554``
plain, ``train.py:556-675`` teacher-student, ``train.py:677-714``
validation): everything inside a step — forward, all loss terms,
backward, optimizer update, metrics — is one pure function compiled
once by XLA. The reference's per-batch Python work (kurtosis-object
reconstruction ``train.py:461-484``, O(L²) module pair scans in
``KD_loss.py:59-66``) happens here once at trace time and fuses into
the compiled program.

Per-epoch variation enters as traced scalars:

- ``tk``         — the binarizer family's schedule tuple: EDE (t, k)
  (↔ module mutation ``train.py:409-415``), proximal (δ,) — whatever
  the active family (nn/binarize.py registry) anneals,
- ``kurt_gate``  — 1.0 when ``epoch >= kurtepoch`` (↔ ``train.py:497``),

so no retrace ever happens across epochs. The stochastic family's
sampling key is likewise derived INSIDE the step from
``(rng_seed, state.step)`` (``jax.random.fold_in``) — pure in the
traced inputs, so a preempted run resumed at the same step replays the
same binarization masks bitwise.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from bdbnn_tpu.losses.kd import (
    distribution_loss,
    layer_weight_kl,
    softmax_cross_entropy,
)
from bdbnn_tpu.losses.kurtosis import (
    kurtosis_regularization,
    l2_regularization,
    weight_to_pm1_regularization,
)
from bdbnn_tpu.models.resnet import get_by_path
from bdbnn_tpu.obs.probes import nonfinite_flag, probe_metrics
from bdbnn_tpu.train.state import StepConfig, TrainState

Array = jax.Array
Batch = Tuple[Array, Array]  # (images NHWC float32, labels int)


def topk_correct(
    logits: Array, labels: Array, ks=(1, 5), valid: Optional[Array] = None
) -> Dict[str, Array]:
    """Counts of top-k correct predictions (↔ utils.accuracy,
    reference ``utils/utils.py:72-85``, which returns percentages —
    counts sum exactly under psum/meters). ``valid`` (0/1 per example)
    masks padded rows out of the counts."""
    out = {}
    k_max = max(ks)
    k_max = min(k_max, logits.shape[-1])
    _, top = jax.lax.top_k(logits, k_max)
    hit = (top == labels[:, None]).astype(jnp.int32)
    if valid is not None:
        hit = hit * valid.astype(jnp.int32)[:, None]
    for k in ks:
        kk = min(k, logits.shape[-1])
        out[f"top{k}"] = jnp.sum(hit[:, :kk])
    return out


def _apply_kwargs(cfg: StepConfig, state: TrainState, tk) -> Dict[str, Any]:
    """The per-family extras of a train-mode ``model.apply``: the
    traced schedule tuple (``tk``) when the family anneals one, and
    the ``binarize`` rng stream when it samples. Schedule-free,
    deterministic families contribute nothing — the default path is
    bitwise the pre-registry apply."""
    kwargs: Dict[str, Any] = {}
    if cfg.ede or cfg.binarizer_schedule:
        kwargs["tk"] = tk
    if cfg.binarizer_stochastic:
        kwargs["rngs"] = {
            "binarize": jax.random.fold_in(
                jax.random.PRNGKey(cfg.rng_seed), state.step
            )
        }
    return kwargs


def _prep_images(images: Array, input_norm) -> Array:
    """Device-side normalization of raw uint8 batches (StepConfig.
    input_norm): identical math to the host pipeline's ``normalize`` —
    ``(x/255 - mean)/std`` in float32 — executed on device where it
    fuses into the first conv's prologue."""
    if input_norm is None:
        return images
    mean, std = input_norm
    x = images.astype(jnp.float32) / 255.0
    return (x - jnp.asarray(mean, jnp.float32)) / jnp.asarray(
        std, jnp.float32
    )


def _regularization_terms(params, cfg: StepConfig, kurt_gate: Array):
    """λ·kurt (+ optional L2 / |W|→±1) over the hooked latent weights."""
    terms = {}
    total = jnp.float32(0.0)
    if cfg.w_kurtosis and cfg.kurt_paths:
        weights = [get_by_path(params, p) for p in cfg.kurt_paths]
        kurt = kurtosis_regularization(
            weights, cfg.kurt_targets, cfg.kurtosis_mode
        )
        kurt = cfg.w_lambda_kurtosis * kurt * kurt_gate
        terms["loss_kurt"] = kurt
        total = total + kurt
        if cfg.w_l2_reg:
            l2 = cfg.w_lambda_l2 * l2_regularization(weights)
            terms["loss_l2"] = l2
            total = total + l2
        if cfg.w_wr_reg:
            wr = cfg.w_lambda_wr * weight_to_pm1_regularization(weights)
            terms["loss_wr"] = wr
            total = total + wr
    return total, terms


def _step_metrics(
    aux: Dict[str, Array],
    logits: Array,
    labels: Array,
    grads,
    old_params,
    new_params,
    cfg: StepConfig,
) -> Dict[str, Array]:
    """Assemble the per-step metric dict (shared by the plain and TS
    steps): loss terms, example-weighted loss sum, top-k counts, and —
    per StepConfig — the grad-norm, binarization-probe and non-finite
    observability signals. Everything is a DeviceMetrics-summable
    on-device scalar; nothing here syncs the host."""
    metrics = {
        **aux,
        # example-weighted sum: epoch means must weight each step by
        # its example count, not average per-step means (which skews
        # when the final print interval is shorter — VERDICT r3 #6)
        "loss_sum": aux["loss"] * labels.shape[0],
        # global gradient norm (cfg.log_grad_norm): the direct probe
        # for estimator starvation (EDE's backward k·t·sech²(t·x) → 0
        # a.e. as t anneals to 10 — VERDICT r4 weak #5)
        **(
            {"grad_norm": optax.global_norm(grads)}
            if cfg.log_grad_norm
            else {}
        ),
        **topk_correct(logits, labels),
        "count": jnp.int32(labels.shape[0]),
    }
    if cfg.probe_paths:
        with jax.named_scope("probes"):
            metrics.update(
                probe_metrics(old_params, new_params, cfg.probe_paths,
                              cfg.probe_names)
            )
    if cfg.track_nonfinite:
        metrics["nonfinite"] = nonfinite_flag(aux["loss"])
    return metrics


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    cfg: StepConfig,
) -> Callable:
    """Plain train step: loss = CE + λ·kurt [+ L2 + WR]
    (↔ reference ``train()``, ``train.py:441-554``)."""
    cfg = cfg.resolved()

    def train_step(state: TrainState, batch: Batch, tk: Array, kurt_gate: Array):
        images, labels = batch
        images = _prep_images(images, cfg.input_norm)

        def loss_fn(params):
            kwargs = _apply_kwargs(cfg, state, tk)
            logits, mutated = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
                **kwargs,
            )
            ce = softmax_cross_entropy(logits, labels)
            reg, terms = _regularization_terms(params, cfg, kurt_gate)
            loss = ce + reg
            aux = {"loss": loss, "loss_ce": ce, **terms, "logits": logits}
            return loss, (mutated["batch_stats"], aux)

        grads, (new_bs, aux) = jax.grad(loss_fn, has_aux=True)(state.params)
        # "optimizer" named scope: the optax update attributes as its
        # own device trace category (obs/trace.py DEVICE_SPANS)
        with jax.named_scope("optimizer"):
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        logits = aux.pop("logits")
        metrics = _step_metrics(
            aux, logits, labels, grads, state.params, new_params, cfg
        )
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_bs,
            opt_state=new_opt,
        )
        return new_state, metrics

    return train_step


def make_ts_train_step(
    model,
    teacher_model,
    tx: optax.GradientTransformation,
    cfg: StepConfig,
) -> Callable:
    """Teacher–student step: loss = β·layerKL + α·logitKL +
    w_lambda_ce·CE + λ·kurt (↔ ``train_teacher_student()``,
    ``train.py:556-675``; react mode zeroes β and CE,
    ``train.py:605-609``)."""
    cfg = cfg.resolved()

    def ts_train_step(
        state: TrainState,
        teacher_variables,
        batch: Batch,
        tk: Array,
        kurt_gate: Array,
    ):
        images, labels = batch
        images = _prep_images(images, cfg.input_norm)

        def loss_fn(params):
            kwargs = _apply_kwargs(cfg, state, tk)
            logits, mutated = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
                **kwargs,
            )
            # frozen teacher: eval mode, no grads (↔ requires_grad=False
            # + .eval(), reference train.py:275-277)
            t_logits = teacher_model.apply(
                teacher_variables, images, train=False
            )
            t_logits = jax.lax.stop_gradient(t_logits)

            ce = softmax_cross_entropy(logits, labels) * cfg.w_lambda_ce
            kl_c = distribution_loss(logits, t_logits) * cfg.alpha
            if cfg.beta != 0.0 and cfg.kd_pairs:
                sw = [get_by_path(params, sp) for sp, _ in cfg.kd_pairs]
                tw = [
                    get_by_path(teacher_variables["params"], tp)
                    for _, tp in cfg.kd_pairs
                ]
                kl_layer = layer_weight_kl(sw, tw) * cfg.beta
            else:
                kl_layer = jnp.float32(0.0)
            reg, terms = _regularization_terms(params, cfg, kurt_gate)
            loss = kl_layer + kl_c + ce + reg
            aux = {
                "loss": loss,
                "loss_ce": ce,
                "loss_kl": kl_layer,
                "loss_kl_c": kl_c,
                **terms,
                "logits": logits,
            }
            return loss, (mutated["batch_stats"], aux)

        grads, (new_bs, aux) = jax.grad(loss_fn, has_aux=True)(state.params)
        with jax.named_scope("optimizer"):
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        logits = aux.pop("logits")
        metrics = _step_metrics(
            aux, logits, labels, grads, state.params, new_params, cfg
        )
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_bs,
            opt_state=new_opt,
        )
        return new_state, metrics

    return ts_train_step


def make_eval_step(model, input_norm=None) -> Callable:
    """Validation step (↔ ``validate()``, ``train.py:677-714``).

    Takes ``(images, labels, valid)``: eval batches are padded to a
    fixed shape (so every host compiles one program and runs the same
    number of steps on a pod) and ``valid`` masks the padding out of
    every reduction. Returns SUMS — with sharded inputs GSPMD reduces
    them globally, so each host sees the global counts (the reference's
    ``validate()`` had no cross-rank reduction; host-local accuracy
    drove best-model selection). ``input_norm`` as in StepConfig:
    uint8 batches normalized on device."""

    def eval_step(state: TrainState, batch):
        images, labels, valid = batch
        images = _prep_images(images, input_norm)
        logits = model.apply(state.variables, images, train=False)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        v = valid.astype(nll.dtype)
        return {
            "loss_sum": jnp.sum(nll * v),
            **topk_correct(logits, labels, valid=valid),
            "count": jnp.sum(valid.astype(jnp.int32)),
        }

    return eval_step
