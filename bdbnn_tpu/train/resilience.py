"""Preemption tolerance: signal handling, checkpoint cadence, retrying I/O.

At pod scale TPU preemption is routine — a maintenance event or a
scheduler reclaim delivers SIGTERM and the process has seconds to make
its work durable. Before this module, checkpoints were epoch-granular
(`train/loop.py`): a SIGTERM anywhere inside an epoch threw away up to
a full epoch of work, and nothing proved that resume reproduced an
uninterrupted run. This module provides the *policy* pieces; the
mechanism (what a checkpoint contains, how it commits atomically) lives
in :mod:`bdbnn_tpu.utils.checkpoint`.

- :class:`PreemptionHandler` — a context manager that latches SIGTERM /
  SIGINT into a flag the epoch loop polls at step boundaries (signals
  must never interrupt a step mid-flight: the flag is checked between
  dispatches, where the train state is consistent and saveable).
- :class:`CheckpointPolicy` — step-interval (``--save-every-steps``)
  and wallclock-interval (``--save-every-mins``) checkpoint cadence.
  Step-interval cadence is *deterministic in step count*, so on a
  multi-host pod every process decides to save at the same step and the
  collective save's barriers line up.
- :class:`PreemptedError` + :data:`PREEMPT_EXIT_CODE` — the loop raises
  after the mid-epoch checkpoint lands; the CLI maps it to exit code 75
  (``EX_TEMPFAIL``: "transient failure, retry me"), which is what pod
  schedulers key restart-vs-fail decisions on.

Multi-host: signal *delivery* is per-process, so hosts latch the
preemption flag at different steps — acting on the local flag alone
would misalign the collective save (barrier hang, or shards from
different steps). The train loop therefore runs a COORDINATION step at
every step boundary of a multi-process run: each host contributes its
local trigger vector (latched signal, wallclock-cadence decision,
pending forensics request) to a cross-host max all-reduce
(:func:`bdbnn_tpu.parallel.coordinate_flags`), so every process sees
the same agreed triggers at the same step and runs the collective save
together. Process 0 is the wallclock leader: only its clock feeds the
``--save-every-mins`` decision, and the all-reduce broadcasts it — no
per-host clock skew can desynchronize the cadence. The step-count
cadence needs no leader (it is deterministic in completed steps).
:meth:`CheckpointPolicy.due`'s ``clock_leader`` flag implements the
split; the agreement itself lives in the train loop
(``train/loop.py``), keeping this module stdlib-only.

Stdlib-only: importable without jax/numpy (the CLI maps the exit code
before any backend exists).
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Callable, Optional, Tuple

# 75 = EX_TEMPFAIL ("temporary failure; the user is invited to retry").
# Distinct from 0 (done), 1 (crash) and 128+signum (killed without
# cleanup) — a supervisor seeing 75 knows the run checkpointed itself
# and wants to be restarted with --resume.
PREEMPT_EXIT_CODE = 75


class PreemptedError(RuntimeError):
    """Raised by the train loop AFTER the preemption checkpoint landed."""

    def __init__(self, signum: int, epoch: int, step_in_epoch: int):
        self.signum = signum
        self.epoch = epoch
        self.step_in_epoch = step_in_epoch
        super().__init__(
            f"preempted by signal {signum} at epoch {epoch} step "
            f"{step_in_epoch} (mid-epoch checkpoint saved)"
        )


class PreemptionHandler:
    """Latch SIGTERM/SIGINT into a flag polled at step boundaries.

    Use as a context manager around the epoch loop; previous handlers
    are restored on exit. A SECOND SIGINT raises ``KeyboardInterrupt``
    immediately — a human hammering ctrl-C must always be able to kill
    a run that is stuck inside a save.

    Installing signal handlers is only legal from the main thread;
    elsewhere (fit() called from a worker thread) the handler degrades
    to an inert no-op with ``installed = False`` instead of crashing.
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)):
        self.signals = signals
        self.installed = False
        self.signum: Optional[int] = None
        self._prev: dict = {}
        self._sigint_count = 0

    @property
    def preempted(self) -> bool:
        return self.signum is not None

    def _handle(self, signum, frame):
        if signum == signal.SIGINT:
            self._sigint_count += 1
            if self._sigint_count > 1:
                raise KeyboardInterrupt
        self.signum = signum

    def __enter__(self) -> "PreemptionHandler":
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handle)
            self.installed = True
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self.installed = False
        return None


class CheckpointPolicy:
    """When to checkpoint, beyond the epoch boundary.

    ``every_steps`` triggers after N completed steps since the last
    save (deterministic across hosts); ``every_mins`` triggers once the
    wallclock interval elapses. On multi-process runs only process 0's
    clock feeds the wallclock decision (``due(clock_leader=False)`` on
    the others) and the train loop's coordination all-reduce broadcasts
    it, so pods get wallclock cadence without trusting per-host clocks.
    Either can be 0 (off); with both 0 the policy is inert (``active``
    False) and the loop skips the per-step bookkeeping entirely.
    """

    def __init__(
        self,
        every_steps: int = 0,
        every_mins: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.every_steps = max(int(every_steps), 0)
        self.every_secs = max(float(every_mins), 0.0) * 60.0
        self._clock = clock
        self._steps_since = 0
        self._last_save = clock()

    @property
    def active(self) -> bool:
        return bool(self.every_steps or self.every_secs)

    def tick(self) -> None:
        """Record one completed step."""
        self._steps_since += 1

    def due(self, clock_leader: bool = True) -> bool:
        """True when a save is due. ``clock_leader``: whether THIS
        process's wallclock may decide (process 0 on pods; the
        coordination all-reduce carries the decision to the rest)."""
        if self.every_steps and self._steps_since >= self.every_steps:
            return True
        if (
            clock_leader
            and self.every_secs
            and (self._clock() - self._last_save) >= self.every_secs
        ):
            return True
        return False

    def step(self) -> bool:
        """Record one completed step; True when a save is due (the
        single-process convenience wrapper over tick + due)."""
        self.tick()
        return self.due()

    def note_saved(self) -> None:
        """Reset both cadences (call after ANY save, incl. epoch-end)."""
        self._steps_since = 0
        self._last_save = self._clock()


__all__ = [
    "PREEMPT_EXIT_CODE",
    "CheckpointPolicy",
    "PreemptedError",
    "PreemptionHandler",
]
