"""Training orchestration: the reference's ``main_worker`` + epoch loop
(``train.py:214-439``) rebuilt around jitted steps and a device mesh.

Differences by design (TPU-first):

- no process spawning, no rendezvous: one python process per host,
  ``jax.distributed.initialize()`` when multi-host (SURVEY.md §5.8);
- the epoch loop feeds per-epoch scalars — EDE (t, k), the kurtosis
  epoch gate — into ONE compiled train step instead of mutating module
  attributes / rebuilding loss objects per batch;
- metrics accumulate ON DEVICE and are fetched once per print interval
  (the reference's per-batch ``.item()`` forced a device sync every
  step — ``train.py:518-524`` — which under XLA's async dispatch would
  serialize the pipeline);
- eval batches are padded + masked to a fixed shape and sharded like
  train batches, so the reduced metrics are global on every host
  (the reference's ``validate()`` was rank-local);
- checkpointing via Orbax with best-model copy; scalar logs carry
  epoch means (Appendix B #15 fix);
- multi-process durability is COORDINATED: every step boundary of a
  collective run agrees on (preempt signal, checkpoint cadence,
  forensics) via a cross-host max all-reduce, so saves are aligned
  collectives and a signal on one host exits the whole pod at 75
  (train/resilience.py module docstring; docs/design.md §7).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bdbnn_tpu.configs.config import RunConfig
from bdbnn_tpu.data import (
    ImageFolder,
    ImageFolderPipeline,
    MPImageFolderPipeline,
    TFDataImageFolderPipeline,
    tfdata_available,
    Pipeline,
    load_cifar10,
    load_cifar100,
    synthetic_dataset,
)
from bdbnn_tpu.losses.kd import match_conv_pairs
from bdbnn_tpu.losses.kurtosis import resolve_targets
from bdbnn_tpu.models import (
    conv_weight_paths,
    create_model,
    get_by_path,
    module_path_str,
)
from bdbnn_tpu.models.torch_import import load_torch_checkpoint
from bdbnn_tpu.nn.binarize import resolve_family, set_active_family
from bdbnn_tpu.obs import (
    EventWriter,
    ObsHooks,
    StepPhaseTimer,
    TraceCapture,
    emit_memory_event,
    parse_profile_at,
    read_manifest,
    write_manifest,
)
from bdbnn_tpu.obs.probes import NonFiniteLossError, drain_probe_report
from bdbnn_tpu.parallel import (
    broadcast_host_int,
    coordinate_flags,
    create_sharded_state,
    jit_train_step,
    make_mesh,
    shard_batch,
    shard_variables,
    topology,
)
from bdbnn_tpu.train.ede import cpt_tk
from bdbnn_tpu.train.optim import make_optimizer
from bdbnn_tpu.train.resilience import (
    CheckpointPolicy,
    PreemptedError,
    PreemptionHandler,
)
from bdbnn_tpu.train.state import StepConfig, TrainState
from bdbnn_tpu.train.step import (
    make_eval_step,
    make_train_step,
    make_ts_train_step,
)
from bdbnn_tpu.utils import (
    DeviceMetrics,
    Mean,
    ProgressLog,
    ScalarWriter,
    Throughput,
    format_eta,
    load_checkpoint,
    load_variables,
    make_log_dir,
    save_checkpoint,
    setup_logger,
)


def select_hooked_paths(params, cfg: RunConfig):
    """Kurtosis hook selection (↔ reference ``train.py:387-406``):
    ``weight_name=('all',)`` → every conv weight except the first
    (``all_convs[1:]``), minus ``remove_weight_name`` matches;
    otherwise the named layers (QAT ``float_weight`` naming is native
    here)."""
    paths = conv_weight_paths(params)
    by_name = {module_path_str(p): p for p in paths}
    if "all" in cfg.weight_name:
        selected = [module_path_str(p) for p in paths[1:]]
        # NB: the reference's removal loop mutates while iterating and
        # can skip entries (Appendix B #9) — this filter is exact.
        selected = [
            n
            for n in selected
            if not any(rm in n for rm in cfg.remove_weight_name)
        ]
    else:
        selected = [n for n in cfg.weight_name if n in by_name]
    return tuple(by_name[n] for n in selected)


def build_datasets(cfg: RunConfig, *, val_only: bool = False):
    """Dataset + pipelines per config (↔ reference ``loader.py`` +
    ``train.py:370-379``). A missing data directory is a HARD ERROR
    unless ``--synthetic`` was passed — a typo'd path must never turn
    into a plausible-looking run on random tensors.

    ``val_only`` (serving's offline ``predict``) skips loading the
    train split entirely and returns ``(None, val_pipe, image_size)`` —
    an inference pass must not pay the train split's I/O or worker
    pools."""
    host_id = jax.process_index()
    num_hosts = jax.process_count()
    per_host_batch = cfg.batch_size // num_hosts
    image_size = 224 if cfg.dataset == "imagenet" else 32

    if cfg.synthetic:
        val_ds = synthetic_dataset(
            cfg.synthetic_val_size, image_size, cfg.num_classes, seed=2
        )
        transform = None
        if cfg.dataset == "imagenet":
            from bdbnn_tpu.data import IMAGENET_MEAN, IMAGENET_STD, normalize

            transform = lambda im, rng: normalize(im, IMAGENET_MEAN, IMAGENET_STD)
        mk = lambda ds, train: Pipeline(
            ds, per_host_batch, train=train, transform=transform,
            seed=cfg.seed or 0, host_id=host_id, num_hosts=num_hosts,
        )
        if val_only:
            return None, mk(val_ds, False), image_size
        train_ds = synthetic_dataset(
            cfg.synthetic_train_size, image_size, cfg.num_classes, seed=1
        )
        return mk(train_ds, True), mk(val_ds, False), image_size

    if cfg.dataset in ("cifar10", "cifar100"):
        loader = load_cifar10 if cfg.dataset == "cifar10" else load_cifar100
        try:
            train_ds = None if val_only else loader(cfg.data, "train")
            val_ds = loader(cfg.data, "test")
        except (FileNotFoundError, OSError) as e:
            raise FileNotFoundError(
                f"{cfg.dataset} data not found under {cfg.data!r} ({e}); "
                "pass a valid --data dir, or --synthetic for a smoke run"
            ) from e
        mk = lambda ds, train: Pipeline(
            ds,
            per_host_batch,
            train=train,
            seed=cfg.seed or 0,
            host_id=host_id,
            num_hosts=num_hosts,
            device_normalize=cfg.device_normalize,
        )
        if val_only:
            return None, mk(val_ds, False), image_size
        return mk(train_ds, True), mk(val_ds, False), image_size

    try:
        # Input engine (cfg.input_backend; SURVEY §2.1 #19):
        #   tfdata  — tf.data C++ threadpool, the BASELINE.json pod path
        #   mp      — worker processes (↔ reference's 16 DataLoader
        #             workers, loader.py:83)
        #   threads — in-process fallback (tests, debugging)
        # auto = tfdata when tensorflow is present, else mp/threads by
        # --workers.
        backend = cfg.input_backend
        workers = 4 if cfg.workers is None else cfg.workers
        if backend == "auto":
            backend = (
                "tfdata"
                if tfdata_available()
                else ("mp" if workers > 0 else "threads")
            )
        elif backend == "tfdata" and not tfdata_available():
            # fail BEFORE model build/compile, not minutes later at the
            # first epoch's _import_tf()
            raise RuntimeError(
                "--input-backend tfdata requested but tensorflow is not "
                "importable here; install it or use --input-backend mp"
            )
        if backend == "mp" and workers <= 0:
            backend = "threads"
        # tfdata autotunes its C++ pool to the host (that is the point
        # of this backend) — but an EXPLICIT -j (cfg.workers not None,
        # even -j 4) pins a private fixed-size tf.data threadpool, so a
        # user throttling host threads on a shared machine actually
        # gets the throttle (ADVICE r4: -j was silently ignored under
        # tfdata).
        pipe_cls, extra = {
            "tfdata": (
                TFDataImageFolderPipeline,
                # explicit -j pins a private pool; 0 would mean "shared
                # autotuned pool" to tf.data (pipeline.py num_threads
                # contract) — the opposite of an explicit throttle — so
                # an explicit -j <= 0 clamps to the minimum pool of 1
                {}
                if cfg.workers is None
                else {"num_threads": max(cfg.workers, 1)},
            ),
            "mp": (MPImageFolderPipeline, {"num_workers": workers}),
            "threads": (ImageFolderPipeline, {}),
        }[backend]

        def mk_folder(split, train):
            return pipe_cls(
                ImageFolder(os.path.join(cfg.data, split)),
                per_host_batch,
                train=train,
                seed=cfg.seed or 0,
                host_id=host_id,
                num_hosts=num_hosts,
                device_normalize=cfg.device_normalize,
                **extra,
            )

        train_pipe = None if val_only else mk_folder("train", True)
        val_pipe = mk_folder("val", False)
    except (FileNotFoundError, OSError) as e:
        raise FileNotFoundError(
            f"imagenet data not found under {cfg.data!r} ({e}); "
            "pass a valid --data dir, or --synthetic for a smoke run"
        ) from e
    return train_pipe, val_pipe, 224


def _overlay(template, loaded, *, scope: str, allow_missing: bool,
             alias_float_weight: bool = False):
    """Overlay ``loaded`` leaves onto ``template``, strictly.

    - every loaded leaf must land on a template leaf of the SAME SHAPE
      (raise otherwise — silently keeping random init produced wrong
      teachers, ADVICE round 1);
    - unconsumed loaded keys raise;
    - template leaves absent from the checkpoint raise unless
      ``allow_missing`` (pretrained-student init wants that: binary
      extras like act shifts aren't in an FP checkpoint);
    - ``alias_float_weight`` maps checkpoint ``weight`` onto template
      ``float_weight`` — the reference's QAT-name fallback
      (``train.py:404``) used when initializing binary students from FP
      checkpoints.
    """
    consumed, missing = set(), []

    def rec(tmpl, load, path, load_path):
        if not isinstance(tmpl, dict):
            if load is None:
                missing.append("/".join(path))
                return tmpl
            arr = jnp.asarray(load)
            if tuple(arr.shape) != tuple(tmpl.shape):
                raise ValueError(
                    f"{scope}: shape mismatch at {'/'.join(path)}: "
                    f"checkpoint {tuple(arr.shape)} vs model {tuple(tmpl.shape)}"
                )
            consumed.add("/".join(load_path))
            return arr.astype(tmpl.dtype)
        out = {}
        for k, v in tmpl.items():
            sub, lk = None, k
            if isinstance(load, dict):
                sub = load.get(k)
                if sub is None and alias_float_weight and k == "float_weight":
                    sub, lk = load.get("weight"), "weight"
            out[k] = rec(v, sub, path + [k], load_path + [lk])
        return out

    merged = rec(template, loaded, [], [])

    def flatten_keys(node, path):
        if not isinstance(node, dict):
            yield "/".join(path)
            return
        for k, v in node.items():
            yield from flatten_keys(v, path + [k])

    loaded_keys = set(flatten_keys(loaded, [])) if loaded else set()
    unconsumed = loaded_keys - consumed
    if unconsumed:
        raise ValueError(
            f"{scope}: checkpoint keys not consumed by the model "
            f"(arch mismatch?): {sorted(unconsumed)[:8]}"
            + ("..." if len(unconsumed) > 8 else "")
        )
    if missing and not allow_missing:
        raise ValueError(
            f"{scope}: model params missing from checkpoint: "
            f"{sorted(missing)[:8]}" + ("..." if len(missing) > 8 else "")
        )
    return merged


def _fast_forward_counts(opt_state, step: int):
    """Set every ``count`` field in a (nested) optax state to ``step``
    — the schedule-position part of resuming from a foreign (torch)
    checkpoint that carries no optax state."""

    def rec(node):
        # "count" must be a real FIELD: every namedtuple inherits a
        # .count *method* from tuple (optax's EmptyState would match a
        # bare hasattr check and crash _replace)
        if "count" in getattr(node, "_fields", ()):
            node = node._replace(
                count=jnp.asarray(step, jnp.asarray(node.count).dtype)
            )
        if isinstance(node, tuple):
            typ = type(node)
            mapped = [rec(c) for c in node]
            return typ(*mapped) if hasattr(node, "_fields") else typ(mapped)
        if isinstance(node, dict):
            # dict-based optax states (e.g. inject_hyperparams wraps the
            # inner state in a dict) carry counts too — ADVICE r2
            out = {
                k: (
                    jnp.asarray(step, jnp.asarray(v).dtype)
                    if k == "count" and not isinstance(v, (dict, tuple))
                    else rec(v)
                )
                for k, v in node.items()
            }
            return out
        return node

    return rec(opt_state)


def build_teacher(cfg: RunConfig, image_size: int):
    """Frozen FP teacher (↔ reference ``train.py:250-277``). Without a
    teacher checkpoint a TS run fails loudly — distilling from a
    random-init teacher is a silently-meaningless run."""
    teacher = create_model(cfg.arch_teacher, cfg.dataset, dtype=cfg.dtype)
    variables = teacher.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, image_size, image_size, 3)),
        train=False,
    )
    if cfg.resume_teacher:
        # NB: the reference checks the WRONG flag here (args.resume,
        # train.py:260 — Appendix B #7); fixed. Accepts EITHER a
        # reference-format torch file OR a native (Orbax) run dir, so a
        # fit()-trained float twin can teach without leaving the
        # framework (reference teachers were torch-only, train.py:265).
        if os.path.isdir(cfg.resume_teacher):
            loaded = load_variables(cfg.resume_teacher)
        else:
            loaded = load_torch_checkpoint(cfg.resume_teacher)
        variables = {
            "params": _overlay(
                variables["params"], loaded["params"],
                scope="teacher params", allow_missing=False,
            ),
            "batch_stats": _overlay(
                variables.get("batch_stats", {}), loaded["batch_stats"],
                scope="teacher batch_stats", allow_missing=False,
            ),
        }
    elif not cfg.allow_random_teacher:
        raise ValueError(
            "teacher-student run without --resume-teacher: the teacher "
            "would be random-init and KD meaningless. Pass a teacher "
            "checkpoint (or allow_random_teacher=True in smoke tests)."
        )
    return teacher, variables


def _detector_code(detector: str) -> int:
    """Stable numeric code for a health-detector name — what rides the
    coordination all-reduce (floats only) so every host derives the
    same forensics tag. Unknown names map past the registry (rendered
    as a generic ``alert`` snapshot)."""
    from bdbnn_tpu.obs.health import DETECTORS

    try:
        return DETECTORS.index(detector)
    except ValueError:
        return len(DETECTORS)


def _pack_host_rng() -> Dict:
    """The legacy np.random global state as strict-JSON scalars (the
    ``resume.json`` sidecar carries it; ~4KB)."""
    name, keys, pos, has_gauss, cached = np.random.get_state(legacy=True)
    return {
        "name": name,
        "keys": [int(x) for x in keys],
        "pos": int(pos),
        "has_gauss": int(has_gauss),
        "cached_gaussian": float(cached),
    }


def _unpack_host_rng(d: Dict) -> None:
    np.random.set_state(
        (
            d["name"],
            np.asarray(d["keys"], dtype=np.uint32),
            int(d["pos"]),
            int(d["has_gauss"]),
            float(d["cached_gaussian"]),
        )
    )


def _checkpoint_topology(resume_path: str) -> Optional[Dict]:
    """The topology recorded in the resume target's checkpoint sidecar
    (``resume.json``'s ``topology`` field) — None for torch files and
    pre-elastic checkpoints."""
    if not resume_path or os.path.isfile(resume_path):
        return None
    from bdbnn_tpu.utils.checkpoint import _candidate_dirs, read_resume_state

    for cand in _candidate_dirs(resume_path):
        if os.path.isdir(cand):
            topo = read_resume_state(cand).get("topology")
            if topo:
                return topo
    return None


def _resume_lineage(resume_path: str, model_parallel: int = 1) -> Dict:
    """Manifest extras recording restart ancestry: ``resumed_from`` (the
    --resume argument) and ``restart_lineage`` (every prior run dir in
    the chain, oldest first — carried forward from the prior run's own
    manifest, so a thrice-preempted run lists all three ancestors).
    Elastic resumes also record ``topology_from`` (the checkpoint
    writer's process/device layout, from its ``resume.json`` sidecar)
    and ``topology_to`` (this run's layout) so the topology lineage is
    auditable from the manifest alone."""
    if not resume_path:
        return {}
    prior_dir = resume_path
    if os.path.isfile(prior_dir):  # a torch .pth file
        prior_dir = os.path.dirname(prior_dir) or "."
    prior = None
    # the manifest lives in the run dir — which is either the --resume
    # path itself or its parent (--resume pointing at checkpoint/)
    for cand in (prior_dir, os.path.dirname(prior_dir.rstrip(os.sep))):
        if cand:
            m = read_manifest(cand)
            if m is not None:
                prior, prior_dir = m, cand
                break
    lineage = list((prior or {}).get("restart_lineage") or [])
    lineage.append(os.path.abspath(prior_dir))
    out = {
        "resumed_from": os.path.abspath(resume_path),
        "restart_lineage": lineage,
    }
    topo_from = _checkpoint_topology(resume_path)
    if topo_from is not None:
        out["topology_from"] = topo_from
        # the mesh doesn't exist yet at manifest time, but its shape is
        # a pure function of (device count, model_parallel) — record it
        # so topology_to compares field-for-field with topology_from
        # (a mesh-less dict would read as a phantom reshard)
        topo_to = topology()
        if model_parallel and topo_to["devices"] % model_parallel == 0:
            topo_to["mesh"] = {
                "data": topo_to["devices"] // model_parallel,
                "model": int(model_parallel),
            }
        out["topology_to"] = topo_to
    return out


@dataclasses.dataclass
class _Resilience:
    """fit()-scoped preemption/cadence bundle threaded into the epoch
    loop. ``save`` is a closure over fit's checkpoint bookkeeping:
    ``save(state, epoch, step_in_epoch, reason)`` commits a checkpoint
    + emits the ``checkpoint`` event + resets the cadence;
    ``save_forensics(state, epoch, step, detector_code)`` snapshots
    under ``<run_dir>/forensics/``.

    ``collective`` (multi-process run): every step boundary runs a
    COORDINATION step — each host's local trigger vector (latched
    signal number, wallclock/step cadence decision, pending forensics
    request) goes through a cross-host max all-reduce
    (:func:`bdbnn_tpu.parallel.coordinate_flags`), so every process
    acts on the SAME agreed triggers at the SAME step and the
    collective Orbax save's barriers align. This is what makes
    flag-triggered preemption saves, ``--save-every-mins`` (process-0's
    clock, broadcast by the all-reduce) and forensics snapshots safe on
    pods — the per-host-flag carve-outs of PR 3/4 are gone. Single-
    process runs skip the all-reduce entirely (the local vector IS the
    agreement)."""

    handler: PreemptionHandler
    policy: CheckpointPolicy
    save: Any
    events: EventWriter
    collective: bool = False
    clock_leader: bool = True
    save_forensics: Any = None
    # pending coordinated-forensics request: health-detector code + 1
    # (0 = none) — set by the forensics hook on collective runs,
    # consumed at the next step boundary's agreement
    forensics_request: int = 0

    def request_forensics(self, detector_code: int) -> None:
        """Latch a forensics-snapshot request (collective runs): the
        alert fired at THIS host's drain, but the aligned save must
        happen at a step boundary every host agrees on."""
        self.forensics_request = int(detector_code) + 1

    def _agree(self, cadence_due: bool):
        """One coordination step: (signum, cadence, forensics_code)
        agreed across all processes (elementwise max). On collective
        runs this is a collective op — every process must call it at
        the same point in its step sequence."""
        local = (
            float(self.handler.signum or 0),
            1.0 if cadence_due else 0.0,
            float(self.forensics_request),
        )
        if not self.collective:
            return int(local[0]), bool(cadence_due), int(local[2])
        agreed = coordinate_flags(local)
        return int(agreed[0]), bool(agreed[1] >= 1.0), int(agreed[2])

    def preempt_exit(
        self, state, epoch: int, step_in_epoch: int,
        already_durable: bool = False, signum: Optional[int] = None,
    ) -> None:
        """The preemption exit protocol: make the state durable (unless
        a checkpoint of exactly this state just committed), emit
        ``preempt``, raise. On collective runs the caller passes the
        AGREED ``signum`` (the local handler may never have latched —
        the signal landed on another host) and every process runs the
        aligned collective save together."""
        signum = int(signum or self.handler.signum or 0)
        target_epoch = epoch if step_in_epoch else epoch + 1
        if not already_durable:
            self.save(state, epoch, step_in_epoch, "preempt")
        self.events.emit(
            "preempt",
            signum=signum,
            epoch=target_epoch,
            step_in_epoch=step_in_epoch,
            saved=True,
            coordinated=self.collective,
            coordination_step=step_in_epoch,
        )
        raise PreemptedError(signum, target_epoch, step_in_epoch)

    def after_step(self, state, epoch: int, next_step: int) -> None:
        """Called at each step boundary (state consistent, saveable).
        Agreed preemption → final mid-epoch checkpoint, ``preempt``
        event, raise; agreed forensics → aligned snapshot; agreed
        cadence → mid-epoch checkpoint and continue."""
        cadence_due = False
        if self.policy.active:
            self.policy.tick()
            cadence_due = self.policy.due(clock_leader=self.clock_leader)
        signum, cadence, forensic = self._agree(cadence_due)
        if signum:
            self.preempt_exit(state, epoch, next_step, signum=signum)
        if forensic and self.save_forensics is not None:
            self.forensics_request = 0
            self.save_forensics(state, epoch, next_step, forensic - 1)
        if cadence:
            self.save(state, epoch, next_step, "interval")

    def poll_boundary(self, state=None, epoch: int = 0,
                      boundary_step: int = 0) -> int:
        """Coordinated check at an epoch boundary (no cadence tick —
        the epoch-end save is imminent). Returns the agreed signal
        number, 0 when no host latched. A forensics request latched at
        the epoch's FINAL drain (the one step with no ``after_step``)
        is consumed here too when ``state`` is given, so the promised
        snapshot cannot be silently dropped at a run's last epoch.
        Every process must call this at the same loop point (it
        coordinates)."""
        signum, _, forensic = self._agree(False)
        if (
            forensic
            and state is not None
            and self.save_forensics is not None
            and not signum  # preemption wins: its save is imminent
        ):
            self.forensics_request = 0
            self.save_forensics(state, epoch, boundary_step, forensic - 1)
        return signum


def fit(cfg: RunConfig) -> Dict[str, float]:
    """End-to-end training (↔ ``main_worker`` + epoch loop)."""
    resources: list = []
    try:
        return _fit(cfg, resources)
    finally:
        # release input-worker pools (MPImageFolderPipeline spawns
        # processes that otherwise live until GC) and flush/close the
        # scalar writer on EVERY exit path (evaluate-return, exception)
        for r in resources:
            close = getattr(r, "close", None)
            if callable(close):
                close()


def _fit(cfg: RunConfig, _resources: list) -> Dict[str, float]:
    cfg = cfg.validate()
    # the binarizer family (nn/binarize.py registry) is a trace-time
    # constant: install it BEFORE any model/step is built. validate()
    # already canonicalized cfg.binarizer (--ede -> "ede", default ->
    # "ste"), so the manifest records exactly what is installed here.
    family = set_active_family(resolve_family(cfg.binarizer, ede=cfg.ede))
    if cfg.distributed_init:
        jax.distributed.initialize()

    # pod runs share ONE run dir across hosts: the collective Orbax
    # save, the manifest and the event timeline all assume a single
    # directory, and per-host clocks can straddle a second boundary —
    # so the timestamp is process-0's, broadcast to everyone
    primary = jax.process_index() == 0
    proc = jax.process_index()
    stamp = None
    if jax.process_count() > 1:
        # gmtime, not localtime: the broadcast only fixes clock skew —
        # hosts with different TZ env would still format the same
        # instant into different dir names
        stamp = time.strftime(
            "%Y-%m-%d_%H-%M-%S",
            time.gmtime(broadcast_host_int(int(time.time()))),
        )
    log_path = make_log_dir(cfg.log_path, cfg.w_kurtosis_target, stamp=stamp)
    logger = setup_logger(
        log_path, filename="log.txt" if primary else f"log.p{proc}.txt"
    )
    writer = ScalarWriter(
        log_path,
        name="scalars.jsonl" if primary else f"scalars.p{proc}.jsonl",
        tensorboard=primary,
    )
    _resources.append(writer)
    logger.info("config: %s", cfg)

    # unified telemetry: provenance manifest + structured event channel
    # live next to log.txt/scalars.jsonl from the first moment of the
    # run, so even a crashed run is diagnosable post hoc (`summarize`)
    # — including restart ancestry when this run resumes another.
    # Metrics are global (GSPMD-reduced on every host), so process 0's
    # events.jsonl is the canonical timeline readers consume; the other
    # hosts write per-process events.p<i>.jsonl for forensics
    manifest = write_manifest(
        log_path, cfg,
        extra=_resume_lineage(cfg.resume, cfg.model_parallel),
        write=primary,
    )
    events = EventWriter(
        log_path,
        name="events.jsonl" if primary else f"events.p{proc}.jsonl",
        max_bytes=int(cfg.events_max_mb * 2**20),
    )
    _resources.append(events)
    logger.info(
        "telemetry: manifest.json + events.jsonl in %s (config %s)",
        log_path, manifest["config_hash"],
    )

    if cfg.seed is not None:
        np.random.seed(cfg.seed)

    train_pipe, val_pipe, image_size = build_datasets(cfg)
    _resources.extend((train_pipe, val_pipe))
    if hasattr(val_pipe, "on_data_error"):
        # eval-side graceful degradation reports too (train-side wiring
        # happens per epoch in _train_epoch, where the epoch is known)
        val_pipe.on_data_error = lambda info: events.emit(
            "data_error", where="eval", **info
        )
    steps_per_epoch = max(train_pipe.steps_per_epoch(), 1)

    mesh = make_mesh(model_parallel=cfg.model_parallel)
    model = create_model(
        cfg.arch, cfg.dataset, dtype=cfg.dtype, twoblock=cfg.twoblock,
        remat=cfg.remat,
    )
    rng = jax.random.PRNGKey(cfg.seed or 0)
    variables = model.init(
        rng, jnp.zeros((1, image_size, image_size, 3)), train=True
    )
    if cfg.pretrained:
        # FP-checkpoint init of the (binary or float) student — the
        # reference's torchvision ``pretrained=True`` path
        # (``train.py:285-288``) without network egress: latent
        # float_weights take the FP conv weights (QAT-name fallback,
        # ``train.py:404``), binary-only extras keep their init.
        loaded = load_torch_checkpoint(cfg.pretrained_path)
        variables = dict(variables)
        variables["params"] = _overlay(
            variables["params"], loaded["params"],
            scope="pretrained student", allow_missing=True,
            alias_float_weight=True,
        )
        if loaded.get("batch_stats"):
            variables["batch_stats"] = _overlay(
                variables.get("batch_stats", {}), loaded["batch_stats"],
                scope="pretrained student bn", allow_missing=True,
            )
        logger.info("initialized student from %s", cfg.pretrained_path)
    logger.info(
        "model %s: %d params",
        cfg.arch,
        sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(variables["params"])),
    )

    tx = make_optimizer(
        variables["params"],
        dataset=cfg.dataset,
        lr=cfg.lr,
        epochs=cfg.epochs,
        steps_per_epoch=steps_per_epoch,
        momentum=cfg.momentum,
        weight_decay=cfg.weight_decay,
        policy=cfg.opt_policy,
    )
    state = create_sharded_state(mesh, variables, tx, TrainState)

    # kurtosis hook selection + per-layer targets
    hooked = select_hooked_paths(variables["params"], cfg) if cfg.w_kurtosis else ()
    targets = (
        resolve_targets(
            len(hooked),
            scalar_target=cfg.w_kurtosis_target,
            diffkurt=cfg.diffkurt,
            dataset=cfg.dataset,
            teacher_student=cfg.teacher_student,
        )
        if hooked
        else ()
    )

    # binarization probes ride on the kurtosis hook selection; runs
    # without kurtosis hooks probe every non-stem conv (the same "all"
    # convention select_hooked_paths uses)
    probe_paths: tuple = ()
    if cfg.probe_binarization and not cfg.arch.endswith("_float"):
        # float twins (teacher training) have no binarization to probe;
        # skipping them also keeps the per-step kurtosis pass out of
        # runs it can't inform
        probe_paths = hooked or tuple(
            conv_weight_paths(variables["params"])[1:]
        )
    probe_names = tuple(module_path_str(p) for p in probe_paths)
    probe_sizes = {
        n: int(np.prod(get_by_path(variables["params"], p).shape))
        for n, p in zip(probe_names, probe_paths)
    }

    input_norm = None
    if cfg.device_normalize:
        from bdbnn_tpu.data import (
            CIFAR_MEAN,
            CIFAR_STD,
            IMAGENET_MEAN,
            IMAGENET_STD,
        )

        mean, std = (
            (IMAGENET_MEAN, IMAGENET_STD)
            if cfg.dataset == "imagenet"
            else (CIFAR_MEAN, CIFAR_STD)
        )
        input_norm = (tuple(map(float, mean)), tuple(map(float, std)))

    step_cfg = StepConfig(
        w_kurtosis=cfg.w_kurtosis,
        kurt_paths=hooked,
        kurt_targets=tuple(targets),
        kurtosis_mode=cfg.kurtosis_mode,
        w_lambda_kurtosis=cfg.w_lambda_kurtosis,
        w_l2_reg=cfg.w_l2_reg,
        w_lambda_l2=cfg.w_lambda_l2,
        w_wr_reg=cfg.w_wr_reg,
        w_lambda_wr=cfg.w_lambda_wr,
        teacher_student=cfg.teacher_student,
        react=cfg.react,
        alpha=cfg.alpha,
        beta=cfg.beta,
        temperature=cfg.temperature,
        w_lambda_ce=cfg.w_lambda_ce,
        ede=cfg.ede,
        binarizer=family.name,
        binarizer_schedule=family.schedule_len > 0,
        binarizer_stochastic=family.stochastic,
        rng_seed=cfg.seed or 0,
        input_norm=input_norm,
        # fit() runs want the starvation probe; bench/profile build
        # their own StepConfig and measure the unperturbed step
        log_grad_norm=True,
        probe_paths=probe_paths,
        probe_names=probe_names,
        track_nonfinite=cfg.nonfinite_policy != "ignore",
    )

    teacher_variables = None
    if cfg.teacher_student:
        teacher, teacher_variables = build_teacher(cfg, image_size)
        teacher_variables = shard_variables(mesh, teacher_variables)
        s_paths = conv_weight_paths(variables["params"])
        t_paths = conv_weight_paths(teacher_variables["params"])
        t_by_name = {module_path_str(p): p for p in t_paths}
        pair_names = match_conv_pairs(
            [module_path_str(p) for p in s_paths],
            list(t_by_name),
        )
        s_by_name = {module_path_str(p): p for p in s_paths}
        # Name-equal pairs can collide across block families (a
        # bottleneck teacher reuses layerS_B.conv1/conv2 names with
        # different kernel shapes than a basic-block student). The
        # layer KL is elementwise over weight tensors, so shape-equal
        # is a hard requirement — validate here, at init, not at jit
        # trace time.
        kd_pairs, mismatched = [], []
        for a, b in pair_names:
            sp, tp = s_by_name[a], t_by_name[b]
            ss = get_by_path(variables["params"], sp).shape
            ts = get_by_path(teacher_variables["params"], tp).shape
            if ss == ts:
                kd_pairs.append((sp, tp))
            else:
                mismatched.append((a, ss, ts))
        if mismatched and step_cfg.resolved().beta != 0.0:
            a, ss, ts = mismatched[0]
            raise ValueError(
                f"layer-KL (beta={step_cfg.beta}) needs shape-matched "
                f"student/teacher conv pairs, but {len(mismatched)} "
                f"name-matched pairs differ in shape (first: {a!r} "
                f"student {ss} vs teacher {ts}). Cross-architecture "
                "teachers (e.g. resnet50_float over a basic-block "
                "student) support logit-only KD: use --react or "
                "--beta 0."
            )
        step_cfg = dataclasses.replace(step_cfg, kd_pairs=tuple(kd_pairs))
        # teacher variables are a traced ARGUMENT, not a closure: baked
        # constants would bloat the executable + HBM and recompile on
        # teacher swap (round-1 weakness #10)
        ts_step = jax.jit(
            make_ts_train_step(model, teacher, tx, step_cfg),
            donate_argnums=(0,),
        )
        train_step = lambda st, batch, tk, gate: ts_step(
            st, teacher_variables, batch, tk, gate
        )
    else:
        train_step = jit_train_step(make_train_step(model, tx, step_cfg))

    eval_step = jax.jit(make_eval_step(model, input_norm=input_norm))
    # empty/padded eval batches must match the real batches' dtype or
    # the jitted eval step would retrace per dtype
    eval_fill_dtype = np.uint8 if cfg.device_normalize else np.float32

    def _sched(epoch):
        """Schedule state entering ``epoch`` — the exact scalars the
        first step of that epoch will be fed. Recorded in `checkpoint`
        events at save time and in the `restore` event at resume time,
        so the fault-injection tests can assert the resume point's EDE
        (t, k) and kurtosis gate are bitwise-identical to what the
        interrupted run would have used."""
        t, k = cpt_tk(epoch, cfg.epochs) if cfg.ede else (1.0, 1.0)
        gate = 1.0 if epoch >= cfg.kurtepoch else 0.0
        return float(t), float(k), float(gate)

    def _sched_values(epoch):
        """The active family's schedule tuple entering ``epoch`` — the
        generalized form of (t, k): () for schedule-free families,
        cpt_tk for ede (bitwise the legacy pair), (δ,) for proximal.
        Recorded next to ede_t/ede_k in checkpoint/restore events so
        ANY family's resume point is auditable bitwise."""
        return [float(v) for v in family.schedule(epoch, cfg.epochs)]

    best_acc1, best_epoch = 0.0, -1
    start_epoch = cfg.start_epoch
    start_step = 0
    if cfg.resume:
        if cfg.resume.endswith((".pth", ".pth.tar", ".pt")):
            # reference-format torch student checkpoint (train.py:346-366)
            import torch

            from bdbnn_tpu.models.torch_import import convert_torch_state_dict

            raw = torch.load(cfg.resume, map_location="cpu", weights_only=False)
            sd = raw.get("state_dict", raw) if isinstance(raw, dict) else raw
            loaded = convert_torch_state_dict(sd)

            # overlay produces host arrays — re-place each leaf with the
            # sharding the mesh-built state already carries, or the TP
            # layout (and multi-host addressability) would be lost
            def _placed_like(new_tree, like_tree):
                return jax.tree_util.tree_map(
                    lambda n, l: jax.device_put(n, l.sharding)
                    if hasattr(l, "sharding")
                    else n,
                    new_tree,
                    like_tree,
                )

            new_params = _placed_like(
                _overlay(
                    state.params, loaded["params"],
                    scope="resume student", allow_missing=True,
                    alias_float_weight=True,
                ),
                state.params,
            )
            new_bs = state.batch_stats
            if loaded.get("batch_stats"):
                new_bs = _placed_like(
                    _overlay(
                        state.batch_stats, loaded["batch_stats"],
                        scope="resume student bn", allow_missing=True,
                    ),
                    state.batch_stats,
                )
            state = state.replace(params=new_params, batch_stats=new_bs)
            if isinstance(raw, dict) and not cfg.reset_resume:
                start_epoch = int(raw.get("epoch", 0))
                best_acc1 = float(raw.get("best_acc1", 0.0))
                # fast-forward the step counter AND every optax count so
                # the step-indexed LR schedule resumes where the torch
                # run left off (torch Adam moments are not translated —
                # they restart; the schedule position must not)
                resume_step = start_epoch * steps_per_epoch
                state = state.replace(
                    step=jnp.asarray(resume_step, jnp.int32),
                    opt_state=_fast_forward_counts(
                        state.opt_state, resume_step
                    ),
                )
                # the EDE anneal and kurtosis gate are epoch-keyed, so
                # fast-forwarding the LR to start_epoch*steps_per_epoch
                # implies the SAME epoch index feeds cpt_tk — record
                # that state so schedule consistency is auditable
                ede_t, ede_k, kurt_gate = _sched(start_epoch)
                logger.warning(
                    "torch .pth resume: LR schedule fast-forwarded to "
                    "step %d (EDE t=%.6g k=%.6g, kurt gate %.0f at "
                    "epoch %d); Adam moments restart (not translated "
                    "from torch optimizer state)",
                    resume_step, ede_t, ede_k, kurt_gate, start_epoch,
                )
                events.emit(
                    "restore",
                    source=cfg.resume,
                    format="torch",
                    fallback=False,
                    integrity="missing",
                    epoch=start_epoch,
                    step_in_epoch=0,
                    lr_step=resume_step,
                    ede_t=ede_t,
                    ede_k=ede_k,
                    kurt_gate=kurt_gate,
                    restored=[
                        "params", "batch_stats", "epoch", "best_acc1",
                        "lr_step", "ede_schedule",
                    ],
                    not_restored=[
                        "opt_moments", "step_in_epoch", "host_rng",
                        "best_epoch",
                    ],
                )
            else:
                events.emit(
                    "restore",
                    source=cfg.resume,
                    format="torch",
                    fallback=False,
                    integrity="missing",
                    epoch=start_epoch,
                    step_in_epoch=0,
                    restored=["params", "batch_stats"],
                    not_restored=[
                        "epoch", "best_acc1", "lr_step", "opt_moments",
                        "step_in_epoch", "host_rng", "best_epoch",
                    ],
                )
        else:
            restored = load_checkpoint(
                cfg.resume, state, reset_resume=cfg.reset_resume
            )
            state = restored["state"]
            start_epoch = restored["epoch"]
            best_acc1 = restored["best_acc1"]
            best_epoch = restored.get("best_epoch", -1)
            start_step = restored.get("step_in_epoch", 0)
            if restored.get("host_rng"):
                _unpack_host_rng(restored["host_rng"])
            if restored.get("fallback"):
                logger.warning(
                    "committed checkpoint unusable; restored the "
                    "previous one from %s", restored["source"],
                )
            # elastic-resume lineage: the checkpoint records its
            # writer's topology; compare with ours to flag a reshard.
            # The restore itself is topology-portable (global arrays,
            # re-placed per the current mesh's NamedSharding) and the
            # (epoch, step) cursor is global, so a reshard needs no
            # special handling beyond being RECORDED.
            topo_from = restored.get("topology")
            topo_to = topology(mesh)
            resharded = None
            if topo_from:
                resharded = (
                    int(topo_from.get("processes", -1)) != topo_to["processes"]
                    or int(topo_from.get("devices", -1)) != topo_to["devices"]
                    or (topo_from.get("mesh") or topo_to["mesh"])
                    != topo_to["mesh"]
                )
            if resharded:
                logger.info(
                    "elastic resume: checkpoint written by %s restored "
                    "onto %s (global arrays resharded to the current "
                    "mesh)", topo_from, topo_to,
                )
            ede_t, ede_k, kurt_gate = _sched(start_epoch)
            events.emit(
                "restore",
                source=restored["source"],
                format="orbax",
                fallback=bool(restored.get("fallback")),
                integrity=restored.get("integrity"),
                epoch=start_epoch,
                step_in_epoch=start_step,
                lr_step=int(jax.device_get(state.step)),
                ede_t=ede_t,
                ede_k=ede_k,
                kurt_gate=kurt_gate,
                binarizer=cfg.binarizer,
                sched=_sched_values(start_epoch),
                topology_from=topo_from,
                topology_to=topo_to,
                resharded=resharded,
                restored=[
                    "params", "batch_stats", "opt_state", "lr_step",
                    "epoch", "best_acc1", "best_epoch", "step_in_epoch",
                    "host_rng",
                ]
                if not cfg.reset_resume
                else ["params", "batch_stats"],
                not_restored=[] if not cfg.reset_resume else [
                    "opt_state", "lr_step", "epoch", "best_acc1",
                    "best_epoch", "step_in_epoch", "host_rng",
                ],
            )
        logger.info(
            "resumed from %s at epoch %d step %d",
            cfg.resume, start_epoch, start_step,
        )

    # --profile-at capture windows (arbitrary EPOCH:STEP[:NSTEPS]
    # points); bare --profile-dir keeps its legacy meaning as the
    # epoch-0 window at [profile_start, profile_start+profile_steps)
    windows = [
        parse_profile_at(spec, default_steps=cfg.profile_steps)
        for spec in cfg.profile_at
    ]
    if not windows and cfg.profile_dir:
        windows = [(0, cfg.profile_start, cfg.profile_steps)]
    # auto-forensics schedules windows on this tracer dynamically, so
    # it must exist (with no static windows) whenever forensics could
    # fire — traces land where `summarize` already looks
    forensics_on = (
        cfg.health and cfg.health_forensics and cfg.health_max_forensics > 0
    )
    tracer = None
    if windows or forensics_on:
        trace_dir = cfg.profile_dir or os.path.join(log_path, "profile")
        tracer = TraceCapture(trace_dir, windows)

    # online health monitor: per-drain pathology detectors over the
    # signals the drains already carry (obs/health.py)
    health_monitor = None
    if cfg.health:
        from bdbnn_tpu.obs import HealthConfig, HealthMonitor
        from bdbnn_tpu.obs import apply_health_overrides

        health_monitor = HealthMonitor(
            apply_health_overrides(HealthConfig(), cfg.health_thresholds),
            events,
            epochs=cfg.epochs,
            kurt_target=cfg.w_kurtosis_target if cfg.w_kurtosis else None,
        )

    forensics_used = [0]

    def _save_forensics_ckpt(st, epoch, step_cursor, detector_code):
        """Forensics snapshot under ``<run_dir>/forensics/`` with full
        resume state — restorable like any checkpoint. Single-process
        runs call this inline at the alerting drain; collective runs
        call it from the NEXT step boundary's coordination agreement
        (every host passes the same coordinated (epoch, step, detector)
        and the collective save's barriers align)."""
        from bdbnn_tpu.obs.health import DETECTORS

        detector = (
            DETECTORS[detector_code]
            if 0 <= detector_code < len(DETECTORS)
            else "alert"
        )
        tag = f"{detector}_e{epoch}_s{step_cursor}"
        t0 = time.time()
        ede_t, ede_k, kg = _sched(epoch)
        path = save_checkpoint(
            os.path.join(log_path, "forensics", tag), st,
            epoch=epoch, arch=cfg.arch, best_acc1=best_acc1,
            is_best=False, step_in_epoch=step_cursor,
            resume_state={
                "best_epoch": int(best_epoch),
                "host_rng": _pack_host_rng(),
                "lr_step": int(jax.device_get(st.step)),
                "ede_t": ede_t,
                "ede_k": ede_k,
                "kurt_gate": kg,
                "binarizer": cfg.binarizer,
                "sched": _sched_values(epoch),
                "topology": topology(mesh),
            },
        )
        events.emit(
            "checkpoint",
            reason="forensics",
            detector=detector,
            coordinated=jax.process_count() > 1,
            epoch=epoch,
            step_in_epoch=step_cursor,
            lr_step=int(jax.device_get(st.step)),
            path=path,
            seconds=round(time.time() - t0, 3),
        )
        return path

    def _forensics(st, epoch, step_cursor, alerts):
        """An alert fired at a drain: snapshot the live state under
        <run_dir>/forensics/ (the main checkpoint chain is untouched)
        and schedule a bounded trace window over the NEXT steps, so
        the step-level evidence exists the moment the pathology does.
        Bounded by --health-max-forensics. Collective (multi-process)
        runs DEFER the checkpoint to the next step boundary's
        coordination all-reduce (detectors with host-local inputs like
        throughput can fire on ONE host, and a unilateral Orbax save
        would be an unaligned collective) — the per-host trace window
        is still scheduled immediately."""
        if not forensics_on or forensics_used[0] >= cfg.health_max_forensics:
            return
        forensics_used[0] += 1
        detector = alerts[0]["detector"]
        path = None
        if jax.process_count() == 1:
            path = _save_forensics_ckpt(
                st, epoch, step_cursor, _detector_code(detector)
            )
        else:
            # resil is assigned before the epoch loop runs (late-bound
            # closure); the agreed snapshot lands at the next boundary
            resil.request_forensics(_detector_code(detector))
        window_at = None
        if tracer is not None:
            # never schedule at/after the epoch's step count: the window
            # would open on the loop's final maybe_start and capture an
            # EMPTY trace whose profile event poisons the attribution
            # (summarize/compare key on the newest trace). An alert at
            # the epoch's last drain traces the pathology's
            # continuation from the next epoch's first steps instead
            # (when one exists).
            if step_cursor < steps_per_epoch:
                window_at = (epoch, step_cursor)
            elif epoch + 1 < cfg.epochs:
                window_at = (epoch + 1, 0)
            if window_at is not None:
                tracer.schedule(*window_at, cfg.health_forensics_steps)
        logger.warning(
            "auto-forensics for %s: checkpoint %s, trace window %s",
            detector,
            path or "(deferred to the next coordinated step boundary)",
            f"{cfg.health_forensics_steps} steps from epoch "
            f"{window_at[0]} step {window_at[1]}"
            if window_at is not None
            else "(skipped: run ends here)",
        )

    obs = ObsHooks(
        events=events,
        timer=StepPhaseTimer(),
        probe_sizes=probe_sizes,
        nonfinite_policy=cfg.nonfinite_policy,
        tracer=tracer,
        health=health_monitor,
        forensics=_forensics,
    )

    if cfg.evaluate:
        acc1 = _validate(
            eval_step, state, val_pipe, mesh, logger, writer, 0,
            fill_dtype=eval_fill_dtype, events=events,
            nonfinite_policy=cfg.nonfinite_policy,
        )
        return {"acc1": acc1}

    # north-star clock (BASELINE "wall-clock to 63%"): includes compile
    # and input time — everything a user actually waits for. Only
    # meaningful for from-scratch runs: a resumed process can't know
    # the pre-resume wall-clock, so the metric is disabled rather than
    # reported misleadingly small.
    t_fit = time.time()
    time_to_target = None
    track_target = cfg.target_acc > 0 and start_epoch == 0 and not cfg.resume
    if cfg.target_acc > 0 and not track_target:
        logger.warning(
            "time-to-target disabled: resumed at epoch %d, pre-resume "
            "wall-clock unknown", start_epoch,
        )

    events.emit(
        "run_start",
        config_hash=manifest["config_hash"],
        start_epoch=start_epoch,
        start_step=start_step,
        epochs=cfg.epochs,
        steps_per_epoch=steps_per_epoch,
        probed_layers=list(probe_sizes),
    )

    # wallclock cadence is pod-safe: process 0 is the clock leader and
    # its decision rides the step-boundary coordination all-reduce, so
    # per-host clock skew can no longer desynchronize the collective
    # save (train/resilience.py module docstring)
    policy = CheckpointPolicy(cfg.save_every_steps, cfg.save_every_mins)

    def _save_ckpt(st, epoch, step_in_epoch, reason, is_best=False):
        """Commit a checkpoint (mid-epoch when step_in_epoch > 0) with
        full resume state, emit the ``checkpoint`` event carrying the
        schedule scalars the RESUMED run must reproduce bitwise, and
        reset the cadence."""
        t0 = time.time()
        # the epoch the resume will enter: the current one (mid-epoch)
        # or the next (epoch-end)
        target_epoch = epoch if step_in_epoch else epoch + 1
        ede_t, ede_k, kurt_gate = _sched(target_epoch)
        lr_step = int(jax.device_get(st.step))
        path = save_checkpoint(
            log_path, st,
            epoch=epoch, arch=cfg.arch, best_acc1=best_acc1,
            is_best=is_best, step_in_epoch=step_in_epoch,
            resume_state={
                "best_epoch": int(best_epoch),
                "host_rng": _pack_host_rng(),
                "lr_step": lr_step,
                "ede_t": ede_t,
                "ede_k": ede_k,
                "kurt_gate": kurt_gate,
                "binarizer": cfg.binarizer,
                "sched": _sched_values(target_epoch),
                # writer topology: what an elastic resume compares its
                # own layout against (restore event reshard lineage)
                "topology": topology(mesh),
            },
        )
        events.emit(
            "checkpoint",
            reason=reason,
            epoch=target_epoch,
            step_in_epoch=step_in_epoch,
            lr_step=lr_step,
            ede_t=ede_t,
            ede_k=ede_k,
            kurt_gate=kurt_gate,
            binarizer=cfg.binarizer,
            sched=_sched_values(target_epoch),
            # True when this save ran as an aligned collective decided
            # by the step-boundary coordination all-reduce
            coordinated=jax.process_count() > 1,
            path=path,
            seconds=round(time.time() - t0, 3),
        )
        policy.note_saved()

    if start_step >= steps_per_epoch:
        logger.warning(
            "resume cursor step %d >= %d steps/epoch (config change "
            "since the checkpoint?); epoch %d will run no steps",
            start_step, steps_per_epoch, start_epoch,
        )

    handler = PreemptionHandler()
    resil = _Resilience(
        handler, policy, _save_ckpt, events,
        collective=jax.process_count() > 1,
        clock_leader=primary,
        save_forensics=_save_forensics_ckpt,
    )
    with handler:
        for epoch in range(start_epoch, cfg.epochs):
            t, k = cpt_tk(epoch, cfg.epochs) if cfg.ede else (1.0, 1.0)
            if cfg.ede:
                # the annealed estimator's schedule, next to grad_norm —
                # the pair that separates schedule-budget from gradient
                # starvation when an EDE run stalls (VERDICT r4 weak #5)
                writer.add_scalar("EDE t", float(t), epoch)
                writer.add_scalar("EDE k", float(k), epoch)
            # the family's schedule tuple enters the jitted step as
            # traced scalars (the EDE discipline, generalized): ede's
            # (t, k) bitwise as before, proximal's (δ,), () families
            # keep the legacy placeholder pair the step never reads
            sched_vals = family.schedule(epoch, cfg.epochs)
            if sched_vals and family.name != "ede":
                for i, v in enumerate(sched_vals):
                    writer.add_scalar(
                        f"Binarizer {family.name} s{i}", float(v), epoch
                    )
            tk = (
                tuple(jnp.float32(v) for v in sched_vals)
                if sched_vals
                else (jnp.float32(t), jnp.float32(k))
            )
            kurt_gate = jnp.float32(1.0 if epoch >= cfg.kurtepoch else 0.0)

            state = _train_epoch(
                train_step, state, train_pipe, mesh, epoch, tk, kurt_gate,
                cfg, steps_per_epoch, logger, writer, obs=obs,
                start_step=start_step if epoch == start_epoch else 0,
                resil=resil,
            )
            # coordinated epoch-boundary check (the epoch's final step
            # has no after_step): a flag that landed on ANY host during
            # the last step means save NOW, before validation — at
            # ImageNet scale eval outlasts the preemption grace period,
            # and SIGKILL mid-eval would discard the whole epoch
            boundary_signum = resil.poll_boundary(
                state, epoch, steps_per_epoch
            )
            if boundary_signum:
                resil.preempt_exit(state, epoch, 0, signum=boundary_signum)
            acc1 = _validate(
                eval_step, state, val_pipe, mesh, logger, writer, epoch,
                fill_dtype=eval_fill_dtype, events=events,
                nonfinite_policy=cfg.nonfinite_policy,
            )

            if (
                time_to_target is None
                and track_target
                and acc1 >= cfg.target_acc
            ):
                time_to_target = time.time() - t_fit
                writer.add_scalar("Time to target (s)", time_to_target, epoch)
                logger.info(
                    " ##### reached target Acc@1 %.2f at epoch %d after %.1fs",
                    cfg.target_acc, epoch, time_to_target,
                )

            # HBM watermark at the epoch boundary: one cheap allocator
            # query per device per epoch, no device sync (memory event;
            # obs/memory.py). The post-compile poll already pinned the
            # steady-state footprint — these catch drift (fragmentation,
            # eval-shape growth), which is exactly what the hbm_creep
            # detector watches.
            mem_rec = emit_memory_event(
                events, "epoch", jax.local_devices(), epoch=epoch
            )
            if health_monitor is not None:
                health_monitor.observe_memory(mem_rec)

            is_best = acc1 > best_acc1
            if is_best:
                best_epoch = epoch
            best_acc1 = max(acc1, best_acc1)
            writer.add_scalar("Best val Acc1", best_acc1, epoch)
            logger.info(
                " ***** Best acc is Acc@1 %.3f, epoch %d, log %s",
                best_acc1, best_epoch, log_path,
            )
            _save_ckpt(state, epoch, 0, "epoch", is_best=is_best)

            # the signal landed during validation/checkpointing — the
            # epoch-end checkpoint above is already durable, so exit
            # the preemption protocol without another save (coordinated:
            # all hosts agree before any of them exits)
            boundary_signum = resil.poll_boundary(
                state, epoch, steps_per_epoch
            )
            if boundary_signum:
                resil.preempt_exit(
                    state, epoch, 0, already_durable=True,
                    signum=boundary_signum,
                )

    if tracer is not None and tracer.unfired():
        # an unreachable spec (epoch resumed past, start step beyond
        # the epoch's step count) must not be discovered by rerunning
        # an hours-long job that silently wrote no trace
        logger.warning(
            "--profile-at window(s) never fired (epoch resumed past, or "
            "start step beyond the epoch's %d steps): %s",
            steps_per_epoch,
            ", ".join(
                f"{e}:{s}:{n}" for e, s, n in tracer.unfired()
            ),
        )

    if health_monitor is not None:
        # run-end health roll-up (the `health` event): alert totals by
        # detector + severity, the record `summarize --strict` gates on
        health_monitor.emit_summary()
        if health_monitor.alerts:
            logger.warning(
                "run finished with %d health alert(s): %s",
                len(health_monitor.alerts),
                ", ".join(
                    f"{k} x{v}"
                    for k, v in sorted(health_monitor.counts().items())
                ),
            )
    events.emit(
        "run_end",
        best_acc1=best_acc1,
        best_epoch=best_epoch,
        wall_s=round(time.time() - t_fit, 1),
        **(
            {"time_to_target_s": round(time_to_target, 1)}
            if time_to_target is not None
            else {}
        ),
    )
    writer.close()
    out = {"best_acc1": best_acc1, "best_epoch": float(best_epoch)}
    if time_to_target is not None:
        out["time_to_target_s"] = round(time_to_target, 1)
    return out


def _apply_nonfinite_policy(policy, logger, events, msg, **fields):
    """cfg.nonfinite_policy at a detection site: record the event, then
    raise / warn / stay silent."""
    if events is not None:
        events.emit("nonfinite", policy=policy, message=msg, **fields)
    if policy == "raise":
        raise NonFiniteLossError(
            msg + " (nonfinite_policy='raise'; pass --nonfinite-policy "
            "warn to keep going)"
        )
    if policy == "warn":
        logger.warning("%s (nonfinite_policy='warn')", msg)


def _interval_observe(
    obs, logger, epoch, step_idx, interval_steps, sums, n, rate, probe_m
):
    """Drain-time telemetry: the non-finite fail-fast check, per-layer
    probe folding, the ``train_interval`` event, and the health
    monitor's detector pass. Pure host work on the already-fetched
    float sums — no device syncs. Returns the health alerts fired (for
    the caller's auto-forensics, which needs the live state)."""
    if obs is None:
        return []
    bad = int(sums.get("nonfinite", 0))
    if bad:
        _apply_nonfinite_policy(
            obs.nonfinite_policy, logger, obs.events,
            f"non-finite train loss in {bad}/{interval_steps} step(s) of "
            f"the interval ending at epoch {epoch} step {step_idx}",
            epoch=epoch, step=step_idx, bad_steps=bad, where="train",
        )
    flip_rate, kurt = drain_probe_report(
        sums, obs.probe_sizes, interval_steps
    )
    for name, v in flip_rate.items():
        probe_m.setdefault(f"Probe flip {name}", Mean(name)).add(
            v, interval_steps
        )
    for name, v in kurt.items():
        probe_m.setdefault(f"Probe kurt {name}", Mean(name)).add(
            v, interval_steps
        )
    obs.events.emit(
        "train_interval",
        epoch=epoch,
        step=step_idx,
        steps=interval_steps,
        loss=round(sums["loss_sum"] / n, 6),
        top1=round(100.0 * sums["top1"] / n, 3),
        img_per_s=round(rate, 2),
        **(
            {"grad_norm": round(sums["grad_norm"] / interval_steps, 6)}
            if "grad_norm" in sums
            else {}
        ),
        **obs.timer.snapshot(),
        **(
            {"flip_rate": {k: round(v, 8) for k, v in flip_rate.items()}}
            if flip_rate
            else {}
        ),
        **(
            {"kurtosis": {k: round(v, 4) for k, v in kurt.items()}}
            if kurt
            else {}
        ),
    )
    alerts = []
    if obs.health is not None:
        alerts = obs.health.observe_interval(
            epoch=epoch,
            step=step_idx,
            loss=sums["loss_sum"] / n,
            img_per_s=rate,
            flip_rate=flip_rate,
            kurtosis=kurt,
        )
        for a in alerts:
            logger.warning(
                "HEALTH ALERT [%s] %s: %s",
                a["severity"], a["detector"], a["message"],
            )
    return alerts


def _profile_window_done(obs, logger, info):
    """A capture window closed: record the ``profile`` event `summarize`
    keys its attribution section on, and tell the human."""
    obs.events.emit("profile", **info)
    logger.info(
        "profiler trace written to %s (epoch %d steps %d..+%d)",
        info["trace_dir"], info["epoch"], info["start_step"],
        info["steps"] - 1,
    )


def _train_epoch(
    train_step, state, pipe, mesh, epoch, tk, kurt_gate, cfg,
    steps_per_epoch, logger, writer, obs=None, start_step=0, resil=None,
):
    """One epoch. The hot loop never syncs with the device: metrics go
    into a lazy on-device accumulator and are drained once every
    ``print_freq`` steps (vs the reference's per-batch ``.item()``,
    ``train.py:518-524``). Telemetry rides the SAME cadence: step-phase
    wall time is perf_counter deltas around calls the loop already
    makes, probes come back inside the drained sums, and events are
    emitted only at drain points — the drain count per epoch is
    identical with obs on or off (pinned by tests/test_obs.py).

    Trace capture (``--profile-at`` windows, obs.tracer) is
    exception-safe: the ``finally`` below flushes an open window
    exactly once, so a step raising between start and stop can neither
    leave the profiler recording forever nor double-stop it. While a
    window is open, the loop's host phases are TraceAnnotation'd
    (``data_wait`` / ``dispatch``) so the trace attributes host time
    too; outside windows the annotations are free nullcontexts."""
    devmet = DeviceMetrics()
    loss_m = Mean("Loss", "{:.4e}")
    top1_m = Mean("Acc@1", "{:6.2f}")
    top5_m = Mean("Acc@5", "{:6.2f}")
    comp_m: Dict[str, Mean] = {}
    probe_m: Dict[str, Mean] = {}
    thr = Throughput()
    progress = ProgressLog(steps_per_epoch, logger, prefix=f"Epoch: [{epoch}]")
    n_chips = max(jax.device_count(), 1)
    timer = obs.timer if obs is not None else None
    tracer = obs.tracer if obs is not None else None
    annot = (
        tracer.annotate
        if tracer is not None
        else (lambda _name: contextlib.nullcontext())
    )

    def fence():
        # drain queued steps so the trace holds the windowed work
        jax.tree_util.tree_leaves(state.params)[0].block_until_ready()

    t_epoch = time.time()

    if timer is not None:
        # the timer persists across epochs: drop the eval/checkpoint
        # wall between epochs so it can't dilute the first interval's
        # data-wait share
        timer.reset()
    if obs is not None and hasattr(pipe, "on_data_error"):
        # graceful input degradation: a substituted corrupt sample
        # becomes a `data_error` event instead of a dead run
        pipe.on_data_error = lambda info: obs.events.emit(
            "data_error", epoch=epoch, **info
        )
    it = iter(pipe.epoch(epoch, start_step))
    step_idx = start_step - 1
    try:
        while True:
            # the window for the UPCOMING step opens before its data
            # fetch, so the first traced step's data_wait annotation is
            # inside the trace (host_phases ms/step divides by the full
            # window — a late start would under-report data-wait)
            if tracer is not None and tracer.maybe_start(epoch, step_idx + 1):
                logger.info(
                    "profiler trace started (epoch %d step %d) -> %s",
                    epoch, step_idx + 1, tracer.trace_dir,
                )
            t_mark = time.perf_counter()
            try:
                with annot("data_wait"):
                    x, y = next(it)
            except StopIteration:
                break
            step_idx += 1
            if timer is not None:
                timer.add("data_wait", time.perf_counter() - t_mark)
            t_mark = time.perf_counter()
            with annot("dispatch"):
                gx, gy = shard_batch(mesh, x, y)
                state, m = train_step(state, (gx, gy), tk, kurt_gate)
            devmet.add(m)
            t_done = time.perf_counter()
            if timer is not None:
                timer.add("dispatch", t_done - t_mark)
                if step_idx == start_step and timer.compile_s is None:
                    # the process's first call blocks the host on
                    # trace+compile (also when resuming at
                    # start_epoch>0 or mid-epoch at start_step>0);
                    # subsequent dispatches are sub-ms async enqueues,
                    # so this host-side duration IS the compile cost
                    timer.record_compile(t_done - t_mark)
                    obs.events.emit(
                        "compile", seconds=round(t_done - t_mark, 3)
                    )
                    # the compiled program's HBM footprint, before any
                    # training drift (memory event; obs/memory.py) —
                    # also the hbm_creep detector's baseline
                    rec = emit_memory_event(
                        obs.events, "post_compile", jax.local_devices(),
                        epoch=epoch,
                    )
                    if obs.health is not None:
                        obs.health.observe_memory(rec)
            if tracer is not None:
                info = tracer.maybe_stop(epoch, step_idx, fence=fence)
                if info is not None:
                    _profile_window_done(obs, logger, info)

            if step_idx % cfg.print_freq == 0:
                interval_steps = devmet.pending_steps
                t_mark = time.perf_counter()
                sums = devmet.drain()  # the ONE host sync per interval
                if timer is not None:
                    timer.add("drain", time.perf_counter() - t_mark)
                n = max(sums["count"], 1.0)
                _add_component_means(comp_m, sums, interval_steps)
                # loss_sum is example-weighted at the step (loss ×
                # count), so interval and epoch means are exact
                # regardless of interval length (VERDICT r3 #6: /steps
                # skewed short final intervals)
                loss_m.add(sums["loss_sum"] / n, n)
                top1_m.add(100.0 * sums["top1"] / n, n)
                top5_m.add(100.0 * sums["top5"] / n, n)
                rate = thr.tick(n)
                alerts = _interval_observe(
                    obs, logger, epoch, step_idx, interval_steps, sums, n,
                    rate, probe_m,
                )
                if alerts and obs is not None and obs.forensics is not None:
                    # the state after step step_idx corresponds to
                    # resume cursor step_idx + 1 — the same convention
                    # as resil.after_step
                    obs.forensics(state, epoch, step_idx + 1, alerts)
                progress.emit(
                    step_idx,
                    [
                        loss_m.render(),
                        top1_m.render(),
                        top5_m.render(),
                        f"img/s {rate:8.1f} ({rate / n_chips:7.1f}/chip)",
                    ],
                )
                sec_per_step = (time.time() - t_epoch) / max(
                    step_idx + 1 - start_step, 1
                )
                remain_steps = (cfg.epochs - epoch) * steps_per_epoch - step_idx
                logger.info(">>>>>>>>>>>> Remaining Time: %s <<<<<<<<<<<<",
                            format_eta(remain_steps * sec_per_step))
            # step boundary: the state is consistent and saveable.
            # Preemption → mid-epoch checkpoint + `preempt` event +
            # PreemptedError; --save-every-steps/--save-every-mins due →
            # mid-epoch checkpoint. Skipped on the epoch's final step
            # (the epoch-end save is imminent and strictly richer).
            if resil is not None and step_idx + 1 < steps_per_epoch:
                resil.after_step(state, epoch, step_idx + 1)
    finally:
        # EXACTLY-ONCE stop on every exit path: a short epoch that ends
        # before the window's step budget, or a raising step mid-window
        # (the profiler would otherwise record forever and write
        # nothing — or, fenced naively, die a second death re-raising
        # from block_until_ready and mask the original error)
        if tracer is not None:
            def _quiet_fence():
                try:
                    fence()
                except Exception:
                    pass  # the original exception is already in flight

            info = tracer.stop_if_active(
                fence=_quiet_fence, last_step=step_idx
            )
            if info is not None:
                _profile_window_done(obs, logger, info)

    # final partial interval + epoch means
    if devmet.pending_steps:
        interval_steps = devmet.pending_steps
        t_mark = time.perf_counter()
        sums = devmet.drain()
        if timer is not None:
            timer.add("drain", time.perf_counter() - t_mark)
        n = max(sums["count"], 1.0)
        _add_component_means(comp_m, sums, interval_steps)
        loss_m.add(sums["loss_sum"] / n, n)
        top1_m.add(100.0 * sums["top1"] / n, n)
        top5_m.add(100.0 * sums["top5"] / n, n)
        rate = thr.tick(n)
        alerts = _interval_observe(
            obs, logger, epoch, step_idx, interval_steps, sums, n, rate,
            probe_m,
        )
        if alerts and obs is not None and obs.forensics is not None:
            obs.forensics(state, epoch, step_idx + 1, alerts)
    # epoch means (Appendix B #15 fix: mean, not last batch)
    writer.add_scalar("Train Loss", loss_m.mean, epoch)
    writer.add_scalar("Train Acc1", top1_m.mean, epoch)
    writer.add_scalar("Train Acc5", top5_m.mean, epoch)
    writer.add_scalar("Train img/s/chip", thr.cumulative / n_chips, epoch)
    # loss components (CE / layer-KL / logit-KL / kurt / L2 / WR as
    # configured) — auditable per-epoch evidence that every term of the
    # 4-term TS loss (reference train.py:596-611) stays finite
    for key, meter in sorted(comp_m.items()):
        writer.add_scalar(f"Train {key}", meter.mean, epoch)
    # per-layer probe trajectories ("Probe flip <layer>" / "Probe kurt
    # <layer>") — the flip-rate/kurtosis curves `summarize` renders
    for key, meter in sorted(probe_m.items()):
        writer.add_scalar(key, meter.mean, epoch)
    if obs is not None:
        obs.events.emit(
            "epoch",
            epoch=epoch,
            loss=round(loss_m.mean, 6),
            top1=round(top1_m.mean, 3),
            img_per_s_chip=round(thr.cumulative / n_chips, 2),
            wall_s=round(time.time() - t_epoch, 3),
        )
    return state


def _add_component_means(comp_m, sums, interval_steps):
    """Fold drained per-step-mean loss-component sums into host meters
    (``loss_ce`` / ``loss_kl*`` / ``loss_kurt`` / ``grad_norm`` / ...),
    weighted by the interval's step count."""
    if not interval_steps:
        return
    for key, val in sums.items():
        if (
            key.startswith("loss_") and key != "loss_sum"
        ) or key == "grad_norm":
            comp_m.setdefault(key, Mean(key)).add(
                val / interval_steps, interval_steps
            )


def _pad_eval_batch(x, y, batch_size):
    """Pad a (possibly short) host-local eval batch to the fixed shape,
    returning (x, y, valid)."""
    n = len(x)
    valid = np.zeros((batch_size,), np.float32)
    valid[:n] = 1.0
    if n < batch_size:
        pad = batch_size - n
        x = np.concatenate([x, np.zeros((pad, *x.shape[1:]), x.dtype)])
        y = np.concatenate([y, np.zeros((pad,), y.dtype)])
    return x, y, valid


def _validate(eval_step, state, pipe, mesh, logger, writer, epoch,
              fill_dtype=np.float32, events=None, nonfinite_policy=None):
    """Mesh-sharded validation with global metrics (↔ ``validate()``,
    ``train.py:677-714``; the reference reduced nothing across ranks).
    Batches are padded to the pipeline batch size and masked, so one
    compiled program serves every step incl. the remainder."""
    bs = pipe.batch_size
    # every host executes exactly pipe.eval_steps() collectives: hosts
    # whose shard ran out feed fully-masked batches (valid = 0) so no
    # host launches a collective the others never join. Per-step sums
    # accumulate ON DEVICE (lazy jnp adds, mirroring DeviceMetrics) —
    # one host sync per validation, not per batch (the reference's
    # .item()-per-batch pattern, train.py:699-706).
    totals = None
    it = pipe.epoch(0)
    for _ in range(pipe.eval_steps()):
        try:
            x, y = next(it)
            x, y = np.asarray(x), np.asarray(y)
        except StopIteration:
            x = np.zeros((0, *pipe.image_shape), fill_dtype)
            y = np.zeros((0,), np.int64)
        x, y, valid = _pad_eval_batch(x, y, bs)
        gx, gy, gv = shard_batch(mesh, x, y, valid)
        m = eval_step(state, (gx, gy, gv))
        totals = (
            m
            if totals is None
            else {k: totals[k] + v for k, v in m.items()}
        )
    fetched = jax.device_get(totals) if totals is not None else {}
    loss_sum = float(fetched.get("loss_sum", 0.0))
    top1_sum = float(fetched.get("top1", 0.0))
    top5_sum = float(fetched.get("top5", 0.0))
    count = max(float(fetched.get("count", 0.0)), 1.0)
    acc1 = 100.0 * top1_sum / count
    acc5 = 100.0 * top5_sum / count
    logger.info(
        " * Acc@1 %.3f Acc@5 %.3f (val loss %.4f)",
        acc1, acc5, loss_sum / count,
    )
    writer.add_scalar("Val Loss", loss_sum / count, epoch)
    writer.add_scalar("Val Acc1", acc1, epoch)
    writer.add_scalar("Val Acc5", acc5, epoch)
    if events is not None:
        # count is the GLOBAL example total (GSPMD psums each host's
        # masked shard): on a pod it must equal the full val-split
        # size, which is how the fault-matrix tests prove eval is
        # sharded over hosts rather than replicated per host
        events.emit(
            "eval",
            epoch=epoch,
            acc1=round(acc1, 4),
            acc5=round(acc5, 4),
            loss=round(loss_sum / count, 6),
            count=int(float(fetched.get("count", 0.0))),
        )
    # the loss is the eval-side NaN signal (acc1 is a ratio of boolean
    # correct-counts and is finite for any weights); "ignore" mirrors
    # the train side, where it disables detection entirely
    if nonfinite_policy not in (None, "ignore") and not np.isfinite(
        loss_sum
    ):
        _apply_nonfinite_policy(
            nonfinite_policy, logger, events,
            f"non-finite validation loss at epoch {epoch}",
            epoch=epoch, where="eval",
        )
    return acc1
