"""Training orchestration: the reference's ``main_worker`` + epoch loop
(``train.py:214-439``) rebuilt around jitted steps and a device mesh.

Differences by design (TPU-first):

- no process spawning, no rendezvous: one python process per host,
  ``jax.distributed.initialize()`` when multi-host (SURVEY.md §5.8);
- the epoch loop feeds per-epoch scalars — EDE (t, k), the kurtosis
  epoch gate — into ONE compiled train step instead of mutating module
  attributes / rebuilding loss objects per batch;
- checkpointing via Orbax with best-model copy; scalar logs carry
  epoch means (Appendix B #15 fix).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bdbnn_tpu.configs.config import RunConfig
from bdbnn_tpu.data import (
    ImageFolder,
    ImageFolderPipeline,
    Pipeline,
    load_cifar10,
    load_cifar100,
    synthetic_dataset,
)
from bdbnn_tpu.losses.kd import match_conv_pairs
from bdbnn_tpu.losses.kurtosis import resolve_targets
from bdbnn_tpu.models import (
    conv_weight_paths,
    create_model,
    module_path_str,
)
from bdbnn_tpu.models.torch_import import load_torch_checkpoint
from bdbnn_tpu.parallel import (
    create_sharded_state,
    jit_train_step,
    make_mesh,
    shard_batch,
    shard_variables,
)
from bdbnn_tpu.train.ede import cpt_tk
from bdbnn_tpu.train.optim import make_optimizer
from bdbnn_tpu.train.state import StepConfig, TrainState
from bdbnn_tpu.train.step import (
    make_eval_step,
    make_train_step,
    make_ts_train_step,
)
from bdbnn_tpu.utils import (
    AverageMeter,
    ProgressMeter,
    ScalarWriter,
    format_eta,
    load_checkpoint,
    make_log_dir,
    save_checkpoint,
    setup_logger,
)


def select_hooked_paths(params, cfg: RunConfig):
    """Kurtosis hook selection (↔ reference ``train.py:387-406``):
    ``weight_name=('all',)`` → every conv weight except the first
    (``all_convs[1:]``), minus ``remove_weight_name`` matches;
    otherwise the named layers (QAT ``float_weight`` naming is native
    here)."""
    paths = conv_weight_paths(params)
    by_name = {module_path_str(p): p for p in paths}
    if "all" in cfg.weight_name:
        selected = [module_path_str(p) for p in paths[1:]]
        # NB: the reference's removal loop mutates while iterating and
        # can skip entries (Appendix B #9) — this filter is exact.
        selected = [
            n
            for n in selected
            if not any(rm in n for rm in cfg.remove_weight_name)
        ]
    else:
        selected = [n for n in cfg.weight_name if n in by_name]
    return tuple(by_name[n] for n in selected)


def build_datasets(cfg: RunConfig):
    """Dataset + pipelines per config (↔ reference ``loader.py`` +
    ``train.py:370-379``). Falls back to a synthetic set when the data
    dir is missing (smoke/bench runs)."""
    host_id = jax.process_index()
    num_hosts = jax.process_count()
    per_host_batch = cfg.batch_size // num_hosts
    image_size = 224 if cfg.dataset == "imagenet" else 32

    if cfg.dataset in ("cifar10", "cifar100"):
        loader = load_cifar10 if cfg.dataset == "cifar10" else load_cifar100
        try:
            train_ds = loader(cfg.data, "train")
            val_ds = loader(cfg.data, "test")
        except (FileNotFoundError, OSError):
            train_ds = synthetic_dataset(2048, 32, cfg.num_classes, seed=1)
            val_ds = synthetic_dataset(512, 32, cfg.num_classes, seed=2)
        mk = lambda ds, train: Pipeline(
            ds,
            per_host_batch,
            train=train,
            seed=cfg.seed or 0,
            host_id=host_id,
            num_hosts=num_hosts,
        )
        return mk(train_ds, True), mk(val_ds, False), image_size

    try:
        train_pipe = ImageFolderPipeline(
            ImageFolder(os.path.join(cfg.data, "train")),
            per_host_batch,
            train=True,
            seed=cfg.seed or 0,
            host_id=host_id,
            num_hosts=num_hosts,
            num_threads=cfg.workers,
        )
        val_pipe = ImageFolderPipeline(
            ImageFolder(os.path.join(cfg.data, "val")),
            per_host_batch,
            train=False,
            host_id=host_id,
            num_hosts=num_hosts,
            num_threads=cfg.workers,
        )
        return train_pipe, val_pipe, 224
    except (FileNotFoundError, OSError):
        train_ds = synthetic_dataset(2048, 224, cfg.num_classes, seed=1)
        val_ds = synthetic_dataset(256, 224, cfg.num_classes, seed=2)
        # ImageNet normalization constants for the synthetic path
        from bdbnn_tpu.data import IMAGENET_MEAN, IMAGENET_STD, normalize

        tr = Pipeline(
            train_ds, per_host_batch, train=True,
            transform=lambda im, rng: normalize(im, IMAGENET_MEAN, IMAGENET_STD),
            seed=cfg.seed or 0, host_id=host_id, num_hosts=num_hosts,
        )
        ev = Pipeline(
            val_ds, per_host_batch, train=False,
            transform=lambda im, rng: normalize(im, IMAGENET_MEAN, IMAGENET_STD),
            host_id=host_id, num_hosts=num_hosts,
        )
        return tr, ev, 224


def build_teacher(cfg: RunConfig, image_size: int):
    """Frozen FP teacher (↔ reference ``train.py:250-277``)."""
    teacher = create_model(cfg.arch_teacher, cfg.dataset)
    variables = teacher.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, image_size, image_size, 3)),
        train=False,
    )
    if cfg.resume_teacher:
        # NB: the reference checks the WRONG flag here (args.resume,
        # train.py:260 — Appendix B #7); fixed.
        loaded = load_torch_checkpoint(cfg.resume_teacher)
        variables = {
            "params": _merge(variables["params"], loaded["params"]),
            "batch_stats": _merge(
                variables.get("batch_stats", {}), loaded["batch_stats"]
            ),
        }
    return teacher, variables


def _merge(template, loaded):
    """Overlay loaded leaves onto the template (keeps template leaves
    missing from the checkpoint, e.g. binary-specific params)."""
    if not isinstance(template, dict):
        return jnp.asarray(loaded) if loaded is not None else template
    out = {}
    for k, v in template.items():
        out[k] = _merge(v, loaded.get(k)) if isinstance(loaded, dict) else v
    return out


def fit(cfg: RunConfig) -> Dict[str, float]:
    """End-to-end training (↔ ``main_worker`` + epoch loop)."""
    cfg = cfg.validate()
    if cfg.distributed_init:
        jax.distributed.initialize()

    log_path = make_log_dir(cfg.log_path, cfg.w_kurtosis_target)
    logger = setup_logger(log_path)
    writer = ScalarWriter(log_path)
    logger.info("config: %s", cfg)

    if cfg.seed is not None:
        np.random.seed(cfg.seed)

    train_pipe, val_pipe, image_size = build_datasets(cfg)
    steps_per_epoch = max(train_pipe.steps_per_epoch(), 1)

    mesh = make_mesh(model_parallel=cfg.model_parallel)
    model = create_model(cfg.arch, cfg.dataset)
    rng = jax.random.PRNGKey(cfg.seed or 0)
    variables = model.init(
        rng, jnp.zeros((1, image_size, image_size, 3)), train=True
    )
    logger.info(
        "model %s: %d params",
        cfg.arch,
        sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(variables["params"])),
    )

    tx = make_optimizer(
        variables["params"],
        dataset=cfg.dataset,
        lr=cfg.lr,
        epochs=cfg.epochs,
        steps_per_epoch=steps_per_epoch,
        momentum=cfg.momentum,
        weight_decay=cfg.weight_decay,
    )
    state = create_sharded_state(mesh, variables, tx, TrainState)

    # kurtosis hook selection + per-layer targets
    hooked = select_hooked_paths(variables["params"], cfg) if cfg.w_kurtosis else ()
    targets = (
        resolve_targets(
            len(hooked),
            scalar_target=cfg.w_kurtosis_target,
            diffkurt=cfg.diffkurt,
            dataset=cfg.dataset,
            teacher_student=cfg.teacher_student,
        )
        if hooked
        else ()
    )

    step_cfg = StepConfig(
        w_kurtosis=cfg.w_kurtosis,
        kurt_paths=hooked,
        kurt_targets=tuple(targets),
        kurtosis_mode=cfg.kurtosis_mode,
        w_lambda_kurtosis=cfg.w_lambda_kurtosis,
        w_l2_reg=cfg.w_l2_reg,
        w_lambda_l2=cfg.w_lambda_l2,
        w_wr_reg=cfg.w_wr_reg,
        w_lambda_wr=cfg.w_lambda_wr,
        teacher_student=cfg.teacher_student,
        react=cfg.react,
        alpha=cfg.alpha,
        beta=cfg.beta,
        temperature=cfg.temperature,
        w_lambda_ce=cfg.w_lambda_ce,
        ede=cfg.ede,
    )

    teacher_variables = None
    if cfg.teacher_student:
        teacher, teacher_variables = build_teacher(cfg, image_size)
        teacher_variables = shard_variables(mesh, teacher_variables)
        s_paths = conv_weight_paths(variables["params"])
        t_paths = conv_weight_paths(teacher_variables["params"])
        t_by_name = {module_path_str(p): p for p in t_paths}
        pair_names = match_conv_pairs(
            [module_path_str(p) for p in s_paths],
            list(t_by_name),
        )
        s_by_name = {module_path_str(p): p for p in s_paths}
        step_cfg = dataclasses.replace(
            step_cfg,
            kd_pairs=tuple(
                (s_by_name[a], t_by_name[b]) for a, b in pair_names
            ),
        )
        train_step = jit_train_step(
            lambda st, batch, tk, gate: make_ts_train_step(
                model, teacher, tx, step_cfg
            )(st, teacher_variables, batch, tk, gate)
        )
    else:
        train_step = jit_train_step(make_train_step(model, tx, step_cfg))

    eval_step = jax.jit(make_eval_step(model))

    best_acc1, best_epoch = 0.0, -1
    start_epoch = cfg.start_epoch
    if cfg.resume:
        restored = load_checkpoint(
            cfg.resume, state, reset_resume=cfg.reset_resume
        )
        state = restored["state"]
        start_epoch = restored["epoch"]
        best_acc1 = restored["best_acc1"]
        logger.info("resumed from %s at epoch %d", cfg.resume, start_epoch)

    if cfg.evaluate:
        acc1 = _validate(eval_step, state, val_pipe, logger, writer, 0, cfg)
        return {"acc1": acc1}

    for epoch in range(start_epoch, cfg.epochs):
        t, k = cpt_tk(epoch, cfg.epochs) if cfg.ede else (1.0, 1.0)
        tk = (jnp.float32(t), jnp.float32(k))
        kurt_gate = jnp.float32(1.0 if epoch >= cfg.kurtepoch else 0.0)

        state = _train_epoch(
            train_step, state, train_pipe, mesh, epoch, tk, kurt_gate,
            cfg, steps_per_epoch, logger, writer,
        )
        acc1 = _validate(eval_step, state, val_pipe, logger, writer, epoch, cfg)

        is_best = acc1 > best_acc1
        if is_best:
            best_epoch = epoch
        best_acc1 = max(acc1, best_acc1)
        writer.add_scalar("Best val Acc1", best_acc1, epoch)
        logger.info(
            " ***** Best acc is Acc@1 %.3f, epoch %d, log %s",
            best_acc1, best_epoch, log_path,
        )
        save_checkpoint(
            log_path, state,
            epoch=epoch, arch=cfg.arch, best_acc1=best_acc1, is_best=is_best,
        )

    writer.close()
    return {"best_acc1": best_acc1, "best_epoch": float(best_epoch)}


def _train_epoch(
    train_step, state, pipe, mesh, epoch, tk, kurt_gate, cfg,
    steps_per_epoch, logger, writer,
):
    meters = {
        "batch_time": AverageMeter("Time", ":6.3f"),
        "data_time": AverageMeter("Data", ":6.3f"),
        "loss": AverageMeter("Loss", ":.4e"),
        "top1": AverageMeter("Acc@1", ":6.2f"),
        "top5": AverageMeter("Acc@5", ":6.2f"),
    }
    progress = ProgressMeter(
        steps_per_epoch, meters.values(), logger,
        prefix=f"Epoch: [{epoch}]",
    )
    end = time.time()
    for i, (x, y) in enumerate(pipe.epoch(epoch)):
        meters["data_time"].update(time.time() - end)
        gx, gy = shard_batch(mesh, x, y)
        state, m = train_step(state, (gx, gy), tk, kurt_gate)
        n = int(m["count"])
        meters["loss"].update(float(m["loss"]), n)
        meters["top1"].update(100.0 * float(m["top1"]) / n, n)
        meters["top5"].update(100.0 * float(m["top5"]) / n, n)
        meters["batch_time"].update(time.time() - end)
        end = time.time()
        if i % cfg.print_freq == 0:
            progress.display(i)
            remain_iters = (cfg.epochs - epoch) * steps_per_epoch + (
                steps_per_epoch - i
            )
            eta = format_eta(remain_iters * meters["batch_time"].get_avg())
            logger.info(">>>>>>>>>>>> Remaining Time: %s <<<<<<<<<<<<", eta)
    # epoch means (Appendix B #15 fix: mean, not last batch)
    writer.add_scalar("Train Loss", meters["loss"].avg, epoch)
    writer.add_scalar("Train Acc1", meters["top1"].avg, epoch)
    writer.add_scalar("Train Acc5", meters["top5"].avg, epoch)
    return state


def _validate(eval_step, state, pipe, logger, writer, epoch, cfg):
    loss_m = AverageMeter("Loss", ":.4e")
    top1 = AverageMeter("Acc@1", ":6.2f")
    top5 = AverageMeter("Acc@5", ":6.2f")
    for x, y in pipe.epoch(0):
        m = eval_step(state, (jnp.asarray(x), jnp.asarray(y)))
        n = int(m["count"])
        loss_m.update(float(m["loss"]), n)
        top1.update(100.0 * float(m["top1"]) / n, n)
        top5.update(100.0 * float(m["top5"]) / n, n)
    logger.info(
        " * Acc@1 %.3f Acc@5 %.3f (val loss %.4f)",
        top1.avg, top5.avg, loss_m.avg,
    )
    writer.add_scalar("Val Loss", loss_m.avg, epoch)
    writer.add_scalar("Val Acc1", top1.avg, epoch)
    writer.add_scalar("Val Acc5", top5.avg, epoch)
    return top1.avg
